"""Exactly-once epoch-segment sink subsystem (ISSUE 20): the
stage/manifest visibility protocol, the recovery promote/truncate
rule, the append-only derivation through chained and fused plans,
SQL wiring (CREATE SINK ... FROM mv [AS APPEND-ONLY]), exactly-once
across kill/recover, and the observability surface (rw_sinks, sink
metric families, ctl sinks)."""

import asyncio
import json

import numpy as np
import pytest

from risingwave_tpu.connectors.sink import (
    AppendSegmentSink, EpochSegmentTarget, UpsertSegmentSink,
    manifest_key, seg_key,
)
from risingwave_tpu.frontend.parser import ParseError, Parser
from risingwave_tpu.frontend.planner import PlanError
from risingwave_tpu.storage.object_store import MemObjectStore
from risingwave_tpu.utils.failpoint import failpoints


class _Op:
    def __init__(self, insert):
        self.is_insert = insert


I, D = _Op(True), _Op(False)


def _records(*rows, op=None):
    return [(op or I, r) for r in rows]


# -- target protocol (unit) ------------------------------------------------

def test_stage_then_manifest_visibility():
    """Staged segments are INVISIBLE until the epoch's manifest
    exists; commit is listing-driven and idempotent."""
    t = EpochSegmentTarget(MemObjectStore(), mode="append",
                          field_names=["a"])
    enc = AppendSegmentSink(t)
    enc.stage(100, 0, _records((1,), (2,)))
    enc.stage(100, 1, _records((3,)))
    assert t.canonical_rows() == []          # no manifest yet
    assert sorted(t.uncommitted_epochs()) == [100]
    done = t.commit_upto(100)
    assert done == [100]
    assert len(t.canonical_rows()) == 3
    # idempotent: a re-derived commit from the same listing is a no-op
    m1 = t.manifests()
    assert t.commit_upto(100) == []
    assert t.manifests() == m1
    # zero-row writers stage nothing and the commit does not wait for
    # a segment per writer
    enc.stage(200, 0, [])
    enc.stage(200, 1, _records((4,)))
    assert t.commit_upto(200) == [200]
    assert len(t.manifests()[-1]["segments"]) == 1


def test_commit_never_passes_the_floor():
    t = EpochSegmentTarget(MemObjectStore(), field_names=["a"])
    enc = AppendSegmentSink(t)
    enc.stage(100, 0, _records((1,)))
    enc.stage(200, 0, _records((2,)))
    assert t.commit_upto(150) == [100]       # invariant 1
    assert sorted(t.uncommitted_epochs()) == [200]
    assert t.committed_epoch() == 100


def test_recover_promotes_and_truncates():
    """The recovery rule: floor ≥ E ⟹ staging of E is provably
    complete (invariant 2), so unmanifested epochs ≤ floor PROMOTE;
    epochs > floor TRUNCATE (their rows replay under fresh epochs);
    torn tmp garbage sweeps."""
    store = MemObjectStore()
    t = EpochSegmentTarget(store, field_names=["a"])
    enc = AppendSegmentSink(t)
    enc.stage(100, 0, _records((1,)))
    enc.stage(100, 1, _records((2,)))
    t.commit_upto(100)
    enc.stage(200, 0, _records((3,)))        # floor-covered, no manifest
    enc.stage(300, 0, _records((9,)))        # past the floor: dead rows
    store.upload("seg/garbage.tmp", b"torn") # mkstemp residue
    promoted, truncated = t.recover(200)
    assert (promoted, truncated) == ([200], [300])
    assert not store.exists(seg_key(300, 0))
    assert not store.exists("seg/garbage.tmp")
    rows = [json.loads(r)["a"] for r in t.canonical_rows()]
    assert sorted(rows) == [1, 2, 3]
    # idempotent: a second sweep changes nothing
    assert t.recover(200) == ([], [])
    # fresh-create sweep (floor=-1): truncate EVERYTHING unmanifested
    enc.stage(400, 0, _records((8,)))
    assert t.recover(-1) == ([], [400])
    assert sorted(rows) == [1, 2, 3]


def test_manifest_commit_fault_then_promote():
    """The storage-fault-during-commit chaos window, in miniature: a
    manifest PUT that raises leaves the epoch INVISIBLE (staging
    intact); recovery re-derives the same manifest from the durable
    listing — no row lost, none duplicated."""
    t = EpochSegmentTarget(MemObjectStore(), field_names=["a"])
    enc = AppendSegmentSink(t)
    enc.stage(100, 0, _records((1,), (2,)))
    with failpoints({"sink.manifest_commit": {
            "raise": "OSError", "times": 1}}):
        with pytest.raises(OSError):
            t.commit_upto(100)
        assert t.canonical_rows() == []      # invisible, not torn
        assert t.recover(100) == ([100], []) # promote from listing
    assert len(t.canonical_rows()) == 2


def test_kill_mid_stage_leaves_nothing_visible():
    """The SIGKILL-mid-stage window: death between fold/serialize and
    the atomic PUT stages nothing — recovery has nothing to see."""
    t = EpochSegmentTarget(MemObjectStore(), field_names=["a"])
    enc = AppendSegmentSink(t)
    with failpoints({"sink.stage.mid": {
            "raise": "OSError", "times": 1}}):
        with pytest.raises(OSError):
            enc.stage(100, 0, _records((1,)))
    assert t.staged_epochs() == {}
    assert t.recover(100) == ([], [])


def test_upsert_fold_and_tombstones():
    """Retractions fold per key within the epoch (last write wins); a
    surviving delete is a tombstone that erases across epochs."""
    t = EpochSegmentTarget(MemObjectStore(), mode="upsert",
                          field_names=["k", "v"])
    enc = UpsertSegmentSink(t, [0])
    # epoch 1: insert k=1,v=10; update k=1 to v=11 (D then I); k=2
    enc.stage(100, 0, [(I, (1, 10)), (D, (1, 10)), (I, (1, 11)),
                       (I, (2, 20))])
    t.commit_upto(100)
    state = {json.loads(r)["k"]: json.loads(r)["v"]
             for r in t.canonical_rows()}
    assert state == {1: 11, 2: 20}
    # epoch 2: delete k=2 — the tombstone survives the fold and erases
    # the earlier epoch's row from the canonical view
    enc.stage(200, 0, [(D, (2, 20))])
    t.commit_upto(200)
    state = {json.loads(r)["k"]: json.loads(r)["v"]
             for r in t.canonical_rows()}
    assert state == {1: 11}


def test_append_sink_refuses_retractions():
    t = EpochSegmentTarget(MemObjectStore(), field_names=["a"])
    enc = AppendSegmentSink(t)
    with pytest.raises(RuntimeError, match="append-only"):
        enc.encode([(D, (1,))])


# -- parser ----------------------------------------------------------------

def test_parse_create_sink_from_mv():
    for sql, ao in [
        ("CREATE SINK s FROM mv WITH (connector='epochlog', "
         "path='/x')", None),
        ("CREATE SINK s FROM mv AS APPEND-ONLY WITH "
         "(connector='epochlog', path='/x')", True),
        ("CREATE SINK s FROM mv AS APPEND ONLY WITH "
         "(connector='epochlog', path='/x')", True),
    ]:
        stmt = Parser(sql).parse()
        assert stmt.from_mv == "mv"
        assert stmt.append_only is ao
        # the synthesized select is SELECT * FROM mv
        assert stmt.select.from_item.name == "mv"
    # legacy AS-select form still parses
    stmt = Parser("CREATE SINK s AS SELECT a FROM t WITH "
                  "(connector='blackhole')").parse()
    assert stmt.from_mv is None
    with pytest.raises(ParseError, match="APPEND-ONLY"):
        Parser("CREATE SINK s FROM mv AS UPSERT WITH "
               "(connector='epochlog', path='/x')").parse()


# -- append-only derivation (satellite) ------------------------------------

def test_derive_append_only_chain_hint_and_fused():
    """_derive_append_only reads the chain-boundary hint (stamped from
    MvCatalog.append_only) and looks THROUGH FusedFragmentExecutor
    blocks — both without touching a live pipeline."""
    from risingwave_tpu.frontend.planner import StreamPlanner
    from risingwave_tpu.stream.executors.fused import (
        FusedFragmentExecutor,
    )

    class _Hinted:
        pass

    h = _Hinted()
    h.append_only_hint = True
    assert StreamPlanner._derive_append_only(h) is True
    h.append_only_hint = False
    assert StreamPlanner._derive_append_only(h) is False
    # a fused block is append-only iff its input is (the block
    # composes only append-only-transparent stages)
    h.append_only_hint = True
    fused = FusedFragmentExecutor.__new__(FusedFragmentExecutor)
    fused.input = h
    assert StreamPlanner._derive_append_only(fused) is True
    h.append_only_hint = False
    assert StreamPlanner._derive_append_only(fused) is False
    # unknown executors stay conservative
    assert StreamPlanner._derive_append_only(object()) is False


def test_sink_mode_derivation_multi_domain(tmp_path):
    """Two disjoint source→MV domains plus a fused plan: each sink's
    mode derives from ITS upstream MV's proof — a filter/project MV is
    append-only (append mode), an agg MV retracts (upsert mode), and
    AS APPEND-ONLY over the agg MV is refused unless forced."""
    from risingwave_tpu.frontend import Frontend

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute("SET stream_fusion = 'on'")
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=500, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE SOURCE bid2 WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=500, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW ao AS SELECT auction, price "
            "FROM bid WHERE price > 100")
        await fe.execute(
            "CREATE MATERIALIZED VIEW agg AS SELECT auction, "
            "count(*) AS c FROM bid2 GROUP BY auction")
        assert fe.catalog.mvs["ao"].append_only is True
        assert fe.catalog.mvs["agg"].append_only is False
        await fe.execute(
            f"CREATE SINK s_ao FROM ao WITH (connector='epochlog', "
            f"path='{tmp_path / 'ao'}')")
        await fe.execute(
            f"CREATE SINK s_agg FROM agg WITH (connector='epochlog', "
            f"path='{tmp_path / 'agg'}')")
        assert fe.catalog.sinks["s_ao"].mode == "append"
        assert fe.catalog.sinks["s_agg"].mode == "upsert"
        # AS APPEND-ONLY must be PROVEN, not asserted
        with pytest.raises(PlanError, match="append-only"):
            await fe.execute(
                f"CREATE SINK s_bad FROM agg AS APPEND-ONLY WITH "
                f"(connector='epochlog', path='{tmp_path / 'bad'}')")
        assert "s_bad" not in fe.catalog.sinks
        assert "s_bad" not in fe.sinks.names()   # no leaked registration
        # ... unless explicitly forced
        await fe.execute(
            f"CREATE SINK s_forced FROM agg AS APPEND-ONLY WITH "
            f"(connector='epochlog', path='{tmp_path / 'forced'}', "
            f"force='true')")
        assert fe.catalog.sinks["s_forced"].mode == "append"
        await fe.step(4)
        await fe.close()

    asyncio.run(run())


# -- SQL end to end --------------------------------------------------------

def _gen_bids_oracle(n):
    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
    cfg = NexmarkConfig(event_num=n, max_chunk_size=128)
    return gen_bids(np.arange(n * 46 // 50, dtype=np.int64), cfg)


def test_epoch_sink_exactly_once_across_restart(tmp_path):
    """The in-process acceptance arm: CREATE SINK ... FROM mv AS
    APPEND-ONLY, SIGKILL-style restart mid-stream (DDL replay +
    recovery sweep), and the committed sink content equals the source
    oracle — no row lost, none duplicated."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite

    out = str(tmp_path / "sink")
    obj = MemObjectStore()
    n = 3000

    async def phase1():
        fe = Frontend(HummockLite(obj), rate_limit=2, min_chunks=2)
        await fe.execute(
            f"CREATE SOURCE bid WITH (connector='nexmark', "
            f"nexmark.table.type='bid', nexmark.event.num={n}, "
            f"nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW mb AS SELECT auction, price "
            "FROM bid")
        await fe.execute(
            f"CREATE SINK s FROM mb AS APPEND-ONLY WITH "
            f"(connector='epochlog', path='{out}')")
        for _ in range(4):
            await fe.step()
        await fe.close()       # hard stop mid-stream

    async def phase2():
        fe = Frontend(HummockLite(obj), rate_limit=2, min_chunks=2)
        await fe.recover()
        for _ in range(30):
            await fe.step()
        await fe.close()

    asyncio.run(phase1())
    t_mid = EpochSegmentTarget.__new__(EpochSegmentTarget)
    from risingwave_tpu.connectors.sink import make_sink_target
    t_mid = make_sink_target({"path": out}, "append")
    assert t_mid.committed_epoch() > 0, "phase 1 committed nothing"
    asyncio.run(phase2())

    t = make_sink_target({"path": out}, "append")
    assert t.uncommitted_epochs() == {}
    got = sorted((json.loads(r)["auction"], json.loads(r)["price"])
                 for r in t.canonical_rows())
    bids = _gen_bids_oracle(n)
    want = sorted(zip(bids["auction"].tolist(),
                      bids["price"].tolist()))
    assert got == want


def test_epoch_sink_upsert_sql_matches_mv(tmp_path):
    """Upsert mode over an agg MV: the folded key→row state equals the
    MV's own content (the group key is the visible stream key, so no
    primary_key option is needed)."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.connectors.sink import make_sink_target

    out = str(tmp_path / "sink")

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW agg AS SELECT auction, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.execute(
            f"CREATE SINK s FROM agg WITH (connector='epochlog', "
            f"path='{out}')")
        await fe.step(25)
        rows = await fe.execute("SELECT * FROM agg")
        await fe.close()
        return rows

    mv_rows = asyncio.run(run())
    t = make_sink_target({"path": out}, "upsert")
    got = sorted((json.loads(r)["auction"], json.loads(r)["c"])
                 for r in t.canonical_rows())
    assert got == sorted((a, c) for a, c in mv_rows)


def test_upsert_sink_needs_visible_or_named_key(tmp_path):
    """An MV whose stream key is a hidden column cannot feed an upsert
    sink implicitly — the planner demands primary_key='...'; naming a
    visible column works, naming a missing one is refused."""
    from risingwave_tpu.frontend import Frontend

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW mv AS SELECT k, v FROM t")
        with pytest.raises(PlanError, match="primary_key"):
            await fe.execute(
                f"CREATE SINK s FROM mv WITH (connector='epochlog', "
                f"path='{tmp_path / 'a'}')")
        with pytest.raises(PlanError, match="not in sink schema"):
            await fe.execute(
                f"CREATE SINK s FROM mv WITH (connector='epochlog', "
                f"path='{tmp_path / 'b'}', primary_key='zz')")
        await fe.execute(
            f"CREATE SINK s FROM mv WITH (connector='epochlog', "
            f"path='{tmp_path / 'c'}', primary_key='k')")
        await fe.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
        await fe.step(2)
        await fe.execute("INSERT INTO t VALUES (1, 11)")
        await fe.step(2)
        await fe.close()

    asyncio.run(run())
    from risingwave_tpu.connectors.sink import make_sink_target
    t = make_sink_target({"path": str(tmp_path / "c")}, "upsert")
    state = {json.loads(r)["k"]: json.loads(r)["v"]
             for r in t.canonical_rows()}
    assert state == {1: 11, 2: 20}


def test_drop_sink_unregisters(tmp_path):
    from risingwave_tpu.frontend import Frontend

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=500, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW mb AS SELECT auction, price "
            "FROM bid")
        await fe.execute(
            f"CREATE SINK s FROM mb WITH (connector='epochlog', "
            f"path='{tmp_path / 's'}', primary_key='auction')")
        assert fe.sinks.names() == ["s"]
        await fe.step(3)
        await fe.execute("DROP SINK s")
        assert fe.sinks.names() == []
        assert "s" not in fe.catalog.sinks
        await fe.step(2)          # checkpoints keep flowing sink-free
        await fe.close()

    asyncio.run(run())


# -- observability ---------------------------------------------------------

def test_rw_sinks_and_metric_families(tmp_path):
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.utils.metrics import GLOBAL

    out = str(tmp_path / "sink")

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=1000, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW mb AS SELECT auction, price "
            "FROM bid")
        await fe.execute(
            f"CREATE SINK s FROM mb AS APPEND-ONLY WITH "
            f"(connector='epochlog', path='{out}')")
        await fe.step(12)
        rows = await fe.execute("SELECT * FROM rw_sinks")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    assert len(rows) == 1
    name, connector, mode, epoch, staged, nbytes, lag = rows[0]
    assert (name, connector, mode) == ("s", "epochlog", "append")
    assert epoch > 0
    assert staged == 0 and lag == 0      # converged: all committed
    text = GLOBAL.render()
    for family in ("sink_committed_epoch", "sink_rows_total",
                   "sink_staged_bytes"):
        assert f"# HELP {family}" in text, family
    assert 'sink_rows_total{mode="append",sink="s"}' in text


def test_ctl_sinks_verb(tmp_path, capsys):
    """`ctl sinks` recovers the data dir and prints the listing-driven
    sink view."""
    from risingwave_tpu.__main__ import main as cli_main
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    d = str(tmp_path / "rw")
    out = str(tmp_path / "sink")

    async def seed():
        fe = Frontend(HummockLite(LocalFsObjectStore(d)), min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=800, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW mb AS SELECT auction, price "
            "FROM bid")
        await fe.execute(
            f"CREATE SINK s FROM mb AS APPEND-ONLY WITH "
            f"(connector='epochlog', path='{out}')")
        await fe.step(4)
        await fe.close()

    asyncio.run(seed())
    with pytest.raises(SystemExit) as e:
        cli_main(["ctl", "--data-dir", d, "sinks"])
    assert e.value.code == 0
    text = capsys.readouterr().out
    assert "== sinks ==" in text
    assert "s [epochlog/append]" in text
    assert "committed_epoch 0x" in text
