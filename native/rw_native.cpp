// Native runtime kernels for hummock-lite's storage hot path.
//
// Reference parity: the role of the Rust block builder/decoder
// (src/storage/src/hummock/sstable/block.rs) and bloom construction
// (sstable/bloom.rs) — the per-entry byte-wrangling loops that sit on
// the checkpoint-upload and scan paths. Byte-for-byte compatible with
// the pure-Python implementation in risingwave_tpu/storage/sst.py:
// either side can read the other's SSTs (mixed deployments, and the
// Python path remains the portable fallback).
//
// Build: g++ -O2 -shared -fPIC -o librw_native.so rw_native.cpp

#include <cstdint>
#include <cstring>

namespace {

// CRC-32 (IEEE, zlib-compatible): crc32(prev, data) semantics.
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int j = 0; j < 8; j++)
            c = (c & 1) ? (0xEDB88320u ^ (c >> 1)) : (c >> 1);
        crc_table[i] = c;
    }
    crc_init_done = true;
}

uint32_t crc32_z(uint32_t prev, const uint8_t* p, long n) {
    if (!crc_init_done) crc_init();
    uint32_t c = prev ^ 0xFFFFFFFFu;
    for (long i = 0; i < n; i++)
        c = crc_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

inline long put_uvarint(uint8_t* out, long pos, uint64_t v) {
    while (v >= 0x80) {
        out[pos++] = (uint8_t)((v & 0x7F) | 0x80);
        v >>= 7;
    }
    out[pos++] = (uint8_t)v;
    return pos;
}

// Bounded varint read for LENGTH fields: returns new pos, or -1 on
// truncation or any value >= 2^28 (no block length is near that; a
// larger value is corrupt data and, if cast to long, could turn the
// caller's bounds checks negative — corrupt object-store bytes must
// fail cleanly, not read OOB).
inline long get_uvarint(const uint8_t* data, long pos, long len,
                        uint64_t* v) {
    int shift = 0;
    uint64_t r = 0;
    for (;;) {
        if (pos >= len || shift > 21) return -1;
        uint8_t b = data[pos++];
        r |= (uint64_t)(b & 0x7F) << shift;
        if (b < 0x80) break;
        shift += 7;
    }
    if (r >= (1u << 28)) return -1;
    *v = r;
    return pos;
}

}  // namespace

extern "C" {

// Prefix-compressed block encode. Entries must be pre-sorted by key.
// Returns bytes written, or -1 if out_cap is insufficient.
long rw_block_encode(const uint8_t* keys, const int32_t* key_lens,
                     const uint8_t* vals, const int32_t* val_lens,
                     int32_t n, int32_t restart_interval,
                     uint8_t* out, long out_cap) {
    long pos = 0;
    const uint8_t* last_key = nullptr;
    int32_t last_len = 0;
    const uint8_t* kp = keys;
    const uint8_t* vp = vals;
    for (int32_t i = 0; i < n; i++) {
        int32_t kl = key_lens[i], vl = val_lens[i];
        int32_t shared = 0;
        if (i % restart_interval != 0 && last_key != nullptr) {
            int32_t m = kl < last_len ? kl : last_len;
            while (shared < m && kp[shared] == last_key[shared]) shared++;
        }
        // worst case: 3 varints (≤10B each) + suffix + value
        if (pos + 30 + (kl - shared) + vl > out_cap) return -1;
        pos = put_uvarint(out, pos, (uint64_t)shared);
        pos = put_uvarint(out, pos, (uint64_t)(kl - shared));
        pos = put_uvarint(out, pos, (uint64_t)vl);
        memcpy(out + pos, kp + shared, (size_t)(kl - shared));
        pos += kl - shared;
        memcpy(out + pos, vp, (size_t)vl);
        pos += vl;
        last_key = kp;
        last_len = kl;
        kp += kl;
        vp += vl;
    }
    return pos;
}

// Block decode → concatenated keys/values + per-entry lengths.
// Returns entry count, or -1 on buffer overflow / malformed input.
long rw_block_decode(const uint8_t* data, long len,
                     uint8_t* keys_out, long keys_cap,
                     int32_t* key_lens,
                     uint8_t* vals_out, long vals_cap,
                     int32_t* val_lens, long max_entries) {
    long pos = 0, n = 0;
    long kpos = 0, vpos = 0;
    uint8_t prev_key[4096];
    long prev_len = 0;
    while (pos < len) {
        if (n >= max_entries) return -1;
        uint64_t shared, unshared, vlen;
        pos = get_uvarint(data, pos, len, &shared);
        if (pos < 0) return -1;
        pos = get_uvarint(data, pos, len, &unshared);
        if (pos < 0) return -1;
        pos = get_uvarint(data, pos, len, &vlen);
        if (pos < 0) return -1;
        long kl = (long)(shared + unshared);
        if (kl > 4096 || (long)shared > prev_len) return -1;
        if (pos + (long)unshared + (long)vlen > len) return -1;
        if (kpos + kl > keys_cap || vpos + (long)vlen > vals_cap)
            return -1;
        memcpy(prev_key + shared, data + pos, (size_t)unshared);
        pos += (long)unshared;
        prev_len = kl;
        memcpy(keys_out + kpos, prev_key, (size_t)kl);
        kpos += kl;
        key_lens[n] = (int32_t)kl;
        memcpy(vals_out + vpos, data + pos, (size_t)vlen);
        pos += (long)vlen;
        vpos += (long)vlen;
        val_lens[n] = (int32_t)vlen;
        n++;
    }
    return n;
}

// Bulk split-Bloom build: for each item, set k bits of bits[nbits].
// Hashes match the Python side: h1 = crc32(item), h2 = crc32(item,
// 0x9E3779B9) | 1, bit_j = (h1 + j*h2) % nbits, MSB-first packing.
void rw_bloom_build(const uint8_t* items, const int32_t* lens,
                    int32_t n, int32_t k, uint8_t* bits, long nbits) {
    const uint8_t* p = items;
    for (int32_t i = 0; i < n; i++) {
        uint32_t h1 = crc32_z(0, p, lens[i]);
        uint32_t h2 = crc32_z(0x9E3779B9u, p, lens[i]) | 1u;
        for (int32_t j = 0; j < k; j++) {
            uint64_t bit = ((uint64_t)h1 + (uint64_t)j * h2) % (uint64_t)nbits;
            bits[bit >> 3] |= (uint8_t)(1u << (7 - (bit & 7)));
        }
        p += lens[i];
    }
}

// Bloom probe for one item (same hash family). Returns 0/1.
int32_t rw_bloom_may_contain(const uint8_t* item, int32_t len,
                             const uint8_t* bits, long nbits,
                             int32_t k) {
    uint32_t h1 = crc32_z(0, item, len);
    uint32_t h2 = crc32_z(0x9E3779B9u, item, len) | 1u;
    for (int32_t j = 0; j < k; j++) {
        uint64_t bit = ((uint64_t)h1 + (uint64_t)j * h2) % (uint64_t)nbits;
        if (!((bits[bit >> 3] >> (7 - (bit & 7))) & 1)) return 0;
    }
    return 1;
}

}  // extern "C"
