"""Typed streaming plan IR: serializable fragments + executor factory.

Reference parity: the plan/service protos (SURVEY §2.2 — the reference
ships `StreamNode` protobufs from meta's fragmenter to compute nodes,
src/stream/src/from_proto/ builds executors from them). TPU re-design:
a JSON-able node tree — `source → project/filter → hash_agg → …` —
plus `build_fragment`, the plan-IR→executor factory. The coordinator
ships a fragment IR over the control channel and ANY worker
materializes it (no more per-query hand-wired fragment functions);
expressions serialize with full fidelity through `expr_to_ir`.

Node shapes (dicts, `op` discriminated):
  {"op": "source", "connector": {...opts}, "schema": [...],
   "actor_id": n, "split_table_id": n, "rate_limit": n,
   "min_chunks": n}
  {"op": "project", "input": N, "exprs": [...], "names": [...]}
  {"op": "filter",  "input": N, "pred": EXPR}
  {"op": "coalesce", "input": N, "target_rows": n,
   "max_chunks": n}                     # barrier-bounded chunk
                                        # coalescing (stream/coalesce)
  {"op": "fused", "input": N,
   "stages": [{"kind": "filter", "pred": EXPR} |
              {"kind": "project", "exprs": [...],
               "names": [...]}]}        # fused filter/project run —
                                        # ONE traced step per chunk
                                        # (ops/fused.py); hash_agg
                                        # nodes may instead carry the
                                        # same list as "fused_stages"
                                        # to inline it into the
                                        # kernel's jitted apply
  {"op": "row_id_gen", "input": N}
  {"op": "hash_agg", "input": N, "group": [...],
   "calls": [{"kind","input_idx","distinct","delimiter"}],
   "table_id": n, "append_only": bool, "output_names": [...],
   "dedup_table_ids": {input_idx: n},   # required per DISTINCT column
   "minput_table_ids": {call_idx: n}}   # required per retractable
                                        # min/max + per host agg
  {"op": "remote_input", "host": h, "port": n, "up_actor": n,
   "schema": [...]}                     # consume another fragment's
                                        # exchange; barriers arrive
                                        # in-band, so a fragment fed
                                        # only by these has no source
  {"op": "merge", "inputs": [N, ...],
   "coalesce_rows": n,
   "coalesce_chunks": n}                # N-way barrier-aligned fan-in
                                        # (coalesce_rows: re-merge
                                        # post-dispatch slivers, 0 off;
                                        # coalesce_chunks: linger bound)
                                        # over earlier nodes (merge.rs
                                        # over exchange inputs) — the
                                        # receive side of a hash
                                        # exchange from a parallel
                                        # upstream fragment
  {"op": "hash_join", "left": N, "right": N, "left_keys": [...],
   "right_keys": [...], "left_table_id": n, "right_table_id": n,
   "left_pk": [...], "right_pk": [...], "join_type": "inner",
   "left_dist_key": [...], "right_dist_key": [...],  # optional:
   "output_names": [...]}   # vnode dist of the join state tables
  {"op": "materialize", "input": N, "table_id": n, "pk": [...],
   "dist_key": [...]}           # optional: vnode partitioning of the
                                # MV rows (must be a pk subset) — set
                                # by the fragmenter when the fragment's
                                # exchange keys prefix the pk, so
                                # rescale can slice state by vnode
  {"op": "top_n", "input": N, "order_by": [[i, desc], ...],
   "offset": n, "limit": n|null, "table_id": n, "group": [...],
   "append_only": bool, "pk": [...]}
  {"op": "over_window", "input": N, "partition": [...],
   "order_by": [[i, desc], ...],
   "calls": [{"kind", "input_idx", "offset"}], "table_id": n,
   "input_pk": [...], "output_names": [...]}
  {"op": "project_set", "input": N,
   "items": [["scalar", EXPR] | ["series", [EXPR, ...]]],
   "names": [...], "pass_pk": [...]}
  {"op": "dynamic_filter", "left": N, "right": N, "left_col": n,
   "cmp": "<"|"<="|">"|">=", "table_id": n}
  {"op": "eowc_gate", "input": N, "wm_col": n, "table_id": n,
   "pk": [...]}
  {"op": "temporal_join", "left": N, "right": N, "left_keys": [...],
   "right_keys": [...], "outer": bool, "output_names": [...]}
  {"op": "dedup", "input": N, "keys": [...], "table_id": n}
  {"op": "backfill", "input": N, "mv_table_id": n, "mv_pk": [...],
   "progress_table_id": n}      # input feeds live deltas; the
                                # snapshot reads the LOCAL store
"""

from __future__ import annotations

import decimal
from typing import Dict, List, Optional

from risingwave_tpu.common.types import (
    DataType, Field, Interval, Schema,
)
from risingwave_tpu.expr.expr import (
    BinaryOp, Case, Cast, Expression, FuncCall, InputRef, Literal,
    UnaryOp,
)

# -- expression serde -----------------------------------------------------


def expr_to_ir(e: Expression) -> dict:
    if isinstance(e, InputRef):
        return {"t": "input", "i": e.index, "dt": e.return_type.value}
    if isinstance(e, Literal):
        v = e.value
        if isinstance(v, Interval):
            v = {"__interval": [v.months, v.days, v.usecs]}
        elif isinstance(v, bytes):
            v = {"__bytes": v.hex()}
        elif isinstance(v, decimal.Decimal):
            v = {"__decimal": str(v)}
        return {"t": "lit", "v": v, "dt": e.return_type.value}
    if isinstance(e, BinaryOp):
        return {"t": "bin", "op": e.op, "l": expr_to_ir(e.left),
                "r": expr_to_ir(e.right)}
    if isinstance(e, UnaryOp):
        return {"t": "un", "op": e.op, "c": expr_to_ir(e.child)}
    if isinstance(e, Cast):
        return {"t": "cast", "c": expr_to_ir(e.child),
                "dt": e.return_type.value}
    if isinstance(e, Case):
        return {"t": "case",
                "whens": [[expr_to_ir(c), expr_to_ir(v)]
                          for c, v in e.whens],
                "else": expr_to_ir(e.else_)}
    if isinstance(e, FuncCall):
        return {"t": "fn", "name": e.name,
                "dt": e.return_type.value,
                "args": [expr_to_ir(a) for a in e.args]}
    raise TypeError(f"unserializable expression {type(e).__name__}")


def _const_from_ir(v):
    if isinstance(v, dict):
        if "__interval" in v:
            m, d, us = v["__interval"]
            return Interval(months=m, days=d, usecs=us)
        if "__bytes" in v:
            return bytes.fromhex(v["__bytes"])
        if "__decimal" in v:
            return decimal.Decimal(v["__decimal"])
    return v


def expr_from_ir(d: dict) -> Expression:
    t = d["t"]
    if t == "input":
        return InputRef(d["i"], DataType(d["dt"]))
    if t == "lit":
        v = _const_from_ir(d["v"])
        return Literal(v, DataType(d["dt"]))
    if t == "bin":
        return BinaryOp(d["op"], expr_from_ir(d["l"]),
                        expr_from_ir(d["r"]))
    if t == "un":
        return UnaryOp(d["op"], expr_from_ir(d["c"]))
    if t == "cast":
        return Cast(expr_from_ir(d["c"]), DataType(d["dt"]))
    if t == "case":
        return Case([(expr_from_ir(c), expr_from_ir(v))
                     for c, v in d["whens"]],
                    expr_from_ir(d["else"]))
    if t == "fn":
        return FuncCall(d["name"],
                        [expr_from_ir(a) for a in d["args"]],
                        DataType(d["dt"]))
    raise TypeError(f"unknown expression IR {t!r}")


def stages_from_ir(in_schema: Schema, stages_ir: List[dict],
                   store=None):
    """IR stage list → FusedStages (the worker-side half of the
    fragmenter's _stages_ir). ``store`` backs the bare runtimes of
    absorbed row_id_gen / watermark_filter stages (their host-only
    executor handles never serialize)."""
    from risingwave_tpu.ops.fused import FusedStage, FusedStages
    stages = []
    for st in stages_ir:
        if st["kind"] == "filter":
            stages.append(FusedStage(
                "filter", "FilterExecutor",
                exprs=(expr_from_ir(st["pred"]),)))
        elif st["kind"] == "project":
            stages.append(FusedStage(
                "project", "ProjectExecutor",
                exprs=tuple(expr_from_ir(e) for e in st["exprs"]),
                names=tuple(st["names"])))
        elif st["kind"] == "row_id_gen":
            from risingwave_tpu.stream.executors.row_id_gen import (
                RowIdCounter,
            )
            stages.append(FusedStage(
                "row_id_gen", "RowIdGenExecutor",
                runtime=RowIdCounter(int(st.get("vnode_base", 0)))))
        elif st["kind"] == "watermark_filter":
            from risingwave_tpu.state.state_table import StateTable
            from risingwave_tpu.stream.executors.watermark_filter \
                import WATERMARK_STATE_SCHEMA, WatermarkRuntime
            wm_state = None
            if st.get("table_id") is not None and store is not None:
                wm_state = StateTable(int(st["table_id"]),
                                      WATERMARK_STATE_SCHEMA, [0],
                                      store)
            stages.append(FusedStage(
                "watermark_filter", "WatermarkFilterExecutor",
                time_col=int(st["time_col"]),
                delay_usecs=int(st["delay_usecs"]),
                runtime=WatermarkRuntime(wm_state)))
        elif st["kind"] == "hop_window":
            stages.append(FusedStage(
                "hop_window", "HopWindowExecutor",
                time_col=int(st["time_col"]),
                slide_usecs=int(st["slide_usecs"]),
                size_usecs=int(st["size_usecs"])))
        else:
            raise TypeError(f"unknown fused stage IR {st['kind']!r}")
    return FusedStages(in_schema, stages)


# node-index reference keys: every IR node points at earlier nodes in
# its fragment through these (plus the list-valued "inputs" of merge).
# Shared by the scheduler's exchange_in expansion and the exchange-
# elision rewrite's fragment fusion — two drifting copies would let a
# new ref key silently dangle after a splice.
NODE_REF_KEYS = ("input", "left", "right")


def remap_node_refs(node: dict, remap: Dict[int, int]) -> dict:
    """Copy of an IR node with every node-index reference remapped
    (fragment splicing / placeholder expansion)."""
    n2 = dict(node)
    for key in NODE_REF_KEYS:
        if isinstance(n2.get(key), int):
            n2[key] = remap[n2[key]]
    if isinstance(n2.get("inputs"), list):
        n2["inputs"] = [remap[i] for i in n2["inputs"]]
    return n2


class _SchemaShim:
    """Placeholder input for constructing a HashJoinExecutor whose
    side schema is a fused run's OUTPUT space — adopt_fused_input
    swaps in the real raw child right after construction."""

    def __init__(self, schema: Schema):
        self.schema = schema
        self.pk_indices: List[int] = []


def schema_to_ir(schema: Schema) -> List[dict]:
    return [{"name": f.name, "dt": f.data_type.value} for f in schema]


def schema_from_ir(ir: List[dict]) -> Schema:
    return Schema([Field(f["name"], DataType(f["dt"])) for f in ir])


# -- fragment factory (from_proto/ analog) --------------------------------


def build_fragment(nodes: List[dict], store, local,
                   channel_factory, actor_id: Optional[int] = None
                   ) -> tuple:
    """IR node list (topological; `input` indexes earlier nodes) →
    (source_executor, consumer_executor). `channel_factory()` returns
    (tx, rx) for the source's barrier channel; the caller registers
    tx with its barrier manager under the source's actor id.
    `actor_id` is THIS fragment's actor — required for remote_input
    nodes (the exchange edge is keyed (up_actor, down_actor)); a
    remote-fed fragment returns source_executor=None since its
    barriers arrive in-band over the exchange."""
    from risingwave_tpu.frontend.planner import (
        SPLIT_STATE_SCHEMA, _source_reader,
    )
    from risingwave_tpu.frontend.catalog import SourceCatalog
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.stream.executors.hash_agg import (
        AggCall, HashAggExecutor, agg_aux_tables, agg_state_schema,
    )
    from risingwave_tpu.stream.executors.row_id_gen import (
        RowIdGenExecutor,
    )
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )
    from risingwave_tpu.stream.executors.source import SourceExecutor
    from risingwave_tpu.ops.hash_agg import AggKind

    built: List[object] = []
    src_executor = None
    for node in nodes:
        op = node["op"]
        if op == "source":
            cat = SourceCatalog(
                name=node.get("name", "src"), source_id=0,
                schema=schema_from_ir(node["schema"]),
                options=dict(node["connector"]))
            reader = _source_reader(cat)
            tx, rx = channel_factory()
            split = StateTable(int(node["split_table_id"]),
                               SPLIT_STATE_SCHEMA, [0], store)
            local.register_sender(int(node["actor_id"]), tx)
            ex = SourceExecutor(
                reader, rx, split, actor_id=int(node["actor_id"]),
                rate_limit_chunks_per_barrier=node.get("rate_limit"),
                min_chunks_per_barrier=node.get("min_chunks"),
                freshness_key=node.get("freshness_key"))
            src_executor = ex
        elif op == "project":
            child = built[node["input"]]
            ex = ProjectExecutor(
                child, [expr_from_ir(e) for e in node["exprs"]],
                node["names"])
        elif op == "filter":
            child = built[node["input"]]
            ex = FilterExecutor(child, expr_from_ir(node["pred"]))
        elif op == "coalesce":
            from risingwave_tpu.stream.coalesce import (
                DEFAULT_MAX_CHUNKS, DEFAULT_TARGET_ROWS,
                CoalesceExecutor,
            )
            ex = CoalesceExecutor(
                built[node["input"]],
                target_rows=int(node.get("target_rows",
                                         DEFAULT_TARGET_ROWS)),
                max_chunks=int(node.get("max_chunks",
                                        DEFAULT_MAX_CHUNKS)))
        elif op == "row_id_gen":
            ex = RowIdGenExecutor(built[node["input"]])
        elif op == "fused":
            from risingwave_tpu.stream.executors.fused import (
                FusedFragmentExecutor,
            )
            child = built[node["input"]]
            ex = FusedFragmentExecutor(
                child, stages_from_ir(child.schema, node["stages"],
                                      store=store))
        elif op == "watermark_filter":
            from risingwave_tpu.stream.executors.watermark_filter \
                import WATERMARK_STATE_SCHEMA, WatermarkFilterExecutor
            wm_state = None
            if node.get("table_id") is not None:
                wm_state = StateTable(int(node["table_id"]),
                                      WATERMARK_STATE_SCHEMA, [0],
                                      store)
            ex = WatermarkFilterExecutor(
                built[node["input"]], int(node["time_col"]),
                Interval(usecs=int(node["delay_usecs"])), wm_state)
        elif op == "hop_window":
            from risingwave_tpu.stream.executors.hop_window import (
                HopWindowExecutor,
            )
            ex = HopWindowExecutor(
                built[node["input"]], int(node["time_col"]),
                Interval(usecs=int(node["slide_usecs"])),
                Interval(usecs=int(node["size_usecs"])))
        elif op == "remote_input":
            from risingwave_tpu.stream.remote import RemoteInput
            if actor_id is None:
                raise ValueError(
                    "remote_input needs the fragment's actor_id")
            ex = RemoteInput(node["host"], int(node["port"]),
                             int(node["up_actor"]), int(actor_id),
                             schema_from_ir(node["schema"]))
        elif op == "merge":
            from risingwave_tpu.stream.coalesce import (
                DEFAULT_MAX_CHUNKS,
            )
            from risingwave_tpu.stream.executor import ExecutorInfo
            from risingwave_tpu.stream.merge import MergeExecutors
            children = [built[i] for i in node["inputs"]]
            if len({len(c.schema) for c in children}) != 1:
                raise ValueError("merge inputs must share a schema")
            # re-coalesce post-dispatch slivers at the fan-in: N
            # parallel upstreams each deliver compacted 1/N slices,
            # and downstream keyed executors should see dense
            # target-sized batches again. The scheduler always writes
            # coalesce_rows (from the session knob via the cut edge);
            # absent == 0 == off, matching every other layer
            ex = MergeExecutors(
                ExecutorInfo(children[0].schema, [],
                             f"Merge({len(children)})"),
                children, actor_id=int(actor_id or 0),
                coalesce_rows=int(node.get("coalesce_rows", 0)),
                coalesce_chunks=int(node.get("coalesce_chunks",
                                             DEFAULT_MAX_CHUNKS)))
        elif op == "hash_join":
            from risingwave_tpu.stream.executors.hash_join import (
                HashJoinExecutor, JoinType,
            )
            left = built[node["left"]]
            right = built[node["right"]]
            # fused input sides (opt/fusion.py try_fuse_join): the
            # side's index space is the absorbed run's OUTPUT schema —
            # construct against schema shims, then adopt the runs so
            # the real (raw) children wire back in
            l_fs = (stages_from_ir(left.schema, node["left_fused"],
                                   store=store)
                    if node.get("left_fused") else None)
            r_fs = (stages_from_ir(right.schema, node["right_fused"],
                                   store=store)
                    if node.get("right_fused") else None)
            l_in = left if l_fs is None else _SchemaShim(l_fs.out_schema)
            r_in = right if r_fs is None else _SchemaShim(r_fs.out_schema)
            lt = StateTable(int(node["left_table_id"]), l_in.schema,
                            [int(i) for i in node["left_pk"]], store,
                            dist_key_indices=node.get("left_dist_key"))
            rt = StateTable(int(node["right_table_id"]), r_in.schema,
                            [int(i) for i in node["right_pk"]], store,
                            dist_key_indices=node.get(
                                "right_dist_key"))
            cap = node.get("state_cap")
            ex = HashJoinExecutor(
                l_in, r_in,
                [int(i) for i in node["left_keys"]],
                [int(i) for i in node["right_keys"]], lt, rt,
                actor_id=int(actor_id or 0),
                join_type=JoinType(node.get("join_type", "inner")),
                output_names=node.get("output_names"),
                state_cap=None if cap is None else int(cap))
            if l_fs is not None:
                ex.adopt_fused_input(0, l_fs, left)
            if r_fs is not None:
                ex.adopt_fused_input(1, r_fs, right)
        elif op == "materialize":
            from risingwave_tpu.stream.executors.materialize import (
                MaterializeExecutor,
            )
            child = built[node["input"]]
            dist = node.get("dist_key")
            mv = StateTable(int(node["table_id"]), child.schema,
                            [int(i) for i in node["pk"]], store,
                            dist_key_indices=(
                                [int(i) for i in dist]
                                if dist else None))
            ex = MaterializeExecutor(child, mv,
                                     mv_name=node.get("mv_name", ""))
        elif op == "sink":
            from risingwave_tpu.connectors.sink import (
                AppendSegmentSink, UpsertSegmentSink, make_sink_target,
            )
            from risingwave_tpu.stream.executors.sink import (
                CoordinatedSinkExecutor,
            )
            child = built[node["input"]]
            names = [f.name for f in child.schema]
            target = make_sink_target({"path": node["path"]},
                                      node["mode"], names)
            enc = (AppendSegmentSink(target)
                   if node["mode"] == "append"
                   else UpsertSegmentSink(
                       target, [int(i) for i in node.get("pk", [])]))
            # INLINE mode (no coordinator): the worker stages
            # synchronously at barrier passage, BEFORE the barrier is
            # collected — the meta-side floor then only ever covers
            # durable staging; manifests are the coordinator's job
            ex = CoordinatedSinkExecutor(
                child, node["sink_name"], enc,
                writer=int(node.get("writer", 0)),
                n_writers=int(node.get("n_writers", 1)))
        elif op == "hash_agg":
            child = built[node["input"]]
            calls = [AggCall(AggKind(c["kind"]),
                             c.get("input_idx"),
                             distinct=bool(c.get("distinct", False)),
                             delimiter=c.get("delimiter", ","))
                     for c in node["calls"]]
            group = list(node["group"])
            # a fused agg's index space is the absorbed run's OUTPUT
            # schema — rebuild the composed prelude first and derive
            # state schemas against it (coordinator parity)
            fused = None
            if node.get("fused_stages"):
                fused = stages_from_ir(child.schema,
                                       node["fused_stages"],
                                       store=store)
            agg_in_schema = child.schema if fused is None \
                else fused.out_schema
            sch, pk = agg_state_schema(agg_in_schema, group, calls)
            table = StateTable(int(node["table_id"]), sch, pk, store,
                               dist_key_indices=list(range(len(pk))))
            # default FALSE like HashAggExecutor itself: a silently
            # append-only agg over a retracting input would produce
            # wrong results; False at worst raises a clean
            # missing-minput error at construction
            append_only = bool(node.get("append_only", False))
            # aux state tables, ids shipped in the IR (the coordinator
            # owns catalog id allocation; deriving ids here could
            # collide with other fragments sharing the store)
            dedup_ids = {int(k): int(v) for k, v in
                         (node.get("dedup_table_ids") or {}).items()}
            minput_ids = {int(k): int(v) for k, v in
                          (node.get("minput_table_ids") or {}).items()}

            def _shipped_id(ids, field, key):
                tid = ids.get(key)
                if tid is None:
                    raise ValueError(
                        f"hash_agg: ship {field}[{key}] — the agg "
                        "needs that aux state table")
                return tid

            distinct_tables, minput_tables = agg_aux_tables(
                agg_in_schema, group, calls, append_only, store,
                dedup_table_id=lambda col: _shipped_id(
                    dedup_ids, "dedup_table_ids", col),
                minput_table_id=lambda j: _shipped_id(
                    minput_ids, "minput_table_ids", j))
            tier_cap = node.get("tier_cap")
            ex = HashAggExecutor(
                child, group, calls, table,
                append_only=append_only,
                output_names=node.get("output_names"),
                distinct_tables=distinct_tables,
                minput_tables=minput_tables,
                tier_cap=None if tier_cap is None else int(tier_cap),
                fused_stages=fused)
        elif op == "top_n":
            from risingwave_tpu.stream.executors.top_n import (
                GroupTopNExecutor,
            )
            child = built[node["input"]]
            pk = [int(i) for i in node["pk"]]
            state = StateTable(int(node["table_id"]), child.schema,
                               pk, store)
            ex = GroupTopNExecutor(
                child,
                [(int(i), bool(d)) for i, d in node["order_by"]],
                offset=int(node.get("offset", 0)),
                limit=node.get("limit"), state=state,
                group_indices=[int(i)
                               for i in node.get("group", [])],
                append_only=bool(node.get("append_only", False)),
                pk_indices=pk)
        elif op == "over_window":
            from risingwave_tpu.expr.window import (
                WindowCall, WindowFuncKind,
            )
            from risingwave_tpu.stream.executors.over_window import (
                OverWindowExecutor,
            )
            child = built[node["input"]]
            partition = [int(i) for i in node["partition"]]
            order = [(int(i), bool(d)) for i, d in node["order_by"]]
            calls = [WindowCall(WindowFuncKind(c["kind"]),
                                c.get("input_idx"),
                                offset=int(c.get("offset", 1)))
                     for c in node["calls"]]
            input_pk = [int(i) for i in node["input_pk"]]
            suffix = [i for i in input_pk if i not in partition
                      and i not in [o for o, _ in order]]
            state = StateTable(
                int(node["table_id"]), child.schema,
                partition + [i for i, _d in order] + suffix, store,
                dist_key_indices=partition)
            ex = OverWindowExecutor(
                child, partition, order, calls, state,
                input_pk=input_pk,
                output_names=node.get("output_names"),
                actor_id=int(actor_id or 0))
        elif op == "project_set":
            from risingwave_tpu.stream.executors.project_set import (
                ProjectSetExecutor,
            )
            child = built[node["input"]]
            items = []
            for kind, payload in node["items"]:
                if kind == "scalar":
                    items.append(("scalar", expr_from_ir(payload)))
                else:
                    items.append((kind, tuple(
                        expr_from_ir(e) for e in payload)))
            ex = ProjectSetExecutor(
                child, items, list(node["names"]),
                pass_pk=[int(i) for i in node.get("pass_pk", [])])
        elif op == "dynamic_filter":
            from risingwave_tpu.stream.executors.dynamic_filter \
                import DynamicFilterExecutor
            left = built[node["left"]]
            lstate = StateTable(int(node["table_id"]), left.schema,
                                list(left.pk_indices), store)
            ex = DynamicFilterExecutor(
                left, built[node["right"]], int(node["left_col"]),
                node["cmp"], lstate)
        elif op == "eowc_gate":
            from risingwave_tpu.stream.executors.eowc import (
                EowcGateExecutor,
            )
            child = built[node["input"]]
            state = StateTable(int(node["table_id"]), child.schema,
                               [int(i) for i in node["pk"]], store)
            ex = EowcGateExecutor(child, int(node["wm_col"]), state,
                                  actor_id=int(actor_id or 0))
        elif op == "temporal_join":
            from risingwave_tpu.stream.executors.temporal_join import (
                TemporalJoinExecutor,
            )
            ex = TemporalJoinExecutor(
                built[node["left"]], built[node["right"]],
                [int(i) for i in node["left_keys"]],
                [int(i) for i in node["right_keys"]],
                outer=bool(node.get("outer", False)),
                actor_id=int(actor_id or 0),
                output_names=node.get("output_names"))
        elif op == "dedup":
            from risingwave_tpu.stream.executors.dedup import (
                AppendOnlyDedupExecutor,
            )
            child = built[node["input"]]
            keys = [int(i) for i in node["keys"]]
            state = StateTable(int(node["table_id"]), child.schema,
                               keys, store)
            ex = AppendOnlyDedupExecutor(child, keys, state)
        elif op == "backfill":
            from risingwave_tpu.stream.executors.backfill import (
                PROGRESS_SCHEMA, BackfillExecutor,
            )
            child = built[node["input"]]
            mv = StateTable(int(node["mv_table_id"]), child.schema,
                            [int(i) for i in node["mv_pk"]], store)
            progress = StateTable(int(node["progress_table_id"]),
                                  PROGRESS_SCHEMA, [0], store)
            ex = BackfillExecutor(child, mv, progress)
        else:
            raise ValueError(f"unknown plan-IR op {op!r}")
        built.append(ex)
    return src_executor, built[-1]
