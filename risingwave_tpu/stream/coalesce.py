"""Chunk compaction + adaptive coalescing for the streaming spine.

Motivation (BENCH r5 / VERDICT r5): masked dispatch and per-chunk
device dispatch drown the hot path in sparse slivers — a parallelism-4
hash dispatch hands every downstream a full-capacity chunk that is
~1/4 visible, which then pays full exchange credit, full wire bytes
and a full ~2ms pjit dispatch per sliver. Hazelcast Jet
(arXiv:2103.10169) and TiLT (arXiv:2301.12030) both land on the same
discipline: amortize per-item overheads by keeping every batch dense
and right-sized. This module is that discipline for StreamChunks:

- ``compact(chunk)``: drop invisible rows (one vectorized gather),
  keeping UpdateDelete/UpdateInsert pairs atomic — a pair whose halves
  are split by visibility degrades to plain Delete/Insert, the same
  invariant HashDispatcher enforces across outputs. Output capacity is
  the next pow-2 bucket, so downstream jit caches see the same small
  shape set they already compile for.
- ``ChunkCoalescer``: a barrier-bounded accumulator that merges
  consecutive small chunks up to a target cardinality. It NEVER holds
  a chunk across a Barrier/Mutation — callers must flush() before
  forwarding any barrier, so checkpoint semantics and p99 barrier
  latency are never traded for throughput. Watermarks RE-SEQUENCE to
  the next flush point instead of forcing one: a watermark is a
  monotone lower bound, so buffered rows (which preceded it) emit
  first and later rows already satisfy it — watermark-per-chunk
  generators (WatermarkFilterExecutor) would otherwise force a flush
  per chunk and neutralize the whole layer. A watermark still never
  crosses a barrier.
- ``CoalesceExecutor``: the executor-chain form, inserted in front of
  keyed executors (hash_join/hash_agg) whose per-chunk device dispatch
  is what coalescing amortizes.

The coalescer only ever merges WHOLE compacted chunks (no splits), so
update pairs that survived compaction stay adjacent by construction.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Optional, Sequence

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, is_chunk
from risingwave_tpu.utils.metrics import STREAMING as _METRICS

# default target cardinality of a coalesced chunk (session var
# stream_chunk_target_rows; 0 disables coalescing) — matches the
# sources' max.chunk.size ballpark so a healthy dense stream passes
# through untouched
DEFAULT_TARGET_ROWS = 4096
# linger bound: a buffer holding this many chunks flushes even below
# the row target (session var stream_coalesce_linger_chunks) — bounds
# host memory and per-flush merge work, NOT latency (the barrier does
# that; this is the pathological-many-tiny-chunks backstop)
DEFAULT_MAX_CHUNKS = 64


def is_empty(chunk: StreamChunk) -> bool:
    """Zero visible rows — THE emptiness predicate (dispatchers and
    the remote send path share it so dense_rows semantics cannot
    drift). Compacted chunks answer from dense_rows; others pay one
    host .any() over the (host-resident on these paths) visibility."""
    if chunk.dense_rows is not None:
        return chunk.dense_rows == 0
    return not np.asarray(chunk.visibility).any()


def compact(chunk: StreamChunk) -> Optional[StreamChunk]:
    """Dense copy of a chunk's visible rows; None when none are.

    One vectorized host pass: visible rows gather into a fresh
    next-pow-2-capacity chunk whose visibility is a full prefix.
    UpdateDelete/UpdateInsert pairs whose halves straddle the
    visibility mask degrade to Delete/Insert (dispatch.rs:640
    invariant: nobody may see half an update pair); pairs that survive
    whole stay adjacent because the gather preserves row order.

    Already-dense chunks (visible rows form a full prefix) return the
    ORIGINAL object with ``dense_rows`` stamped — the fast path for
    healthy streams.
    """
    vis = np.asarray(chunk.visibility)
    idx = np.flatnonzero(vis)
    t = int(len(idx))
    if t == 0:
        return None
    ops = np.asarray(chunk.ops)
    # fast path: dense prefix in a right-sized bucket. A fully-visible
    # chunk cannot straddle a pair; a masked-tail prefix can ONLY
    # straddle at the boundary (U- at t-1, its U+ at t masked) — that
    # one case must take the degrade path below.
    if t == chunk.capacity or (
            int(idx[-1]) == t - 1
            and next_pow2(t) == chunk.capacity
            and not (ops[t - 1] == int(Op.UPDATE_DELETE)
                     and ops[t] == int(Op.UPDATE_INSERT))):
        chunk.dense_rows = t
        return chunk
    is_ud = ops == int(Op.UPDATE_DELETE)
    is_ui = ops == int(Op.UPDATE_INSERT)
    next_vis = np.roll(vis, -1)
    next_vis[-1] = False
    prev_vis = np.roll(vis, 1)
    prev_vis[0] = False
    next_is_ui = np.roll(is_ui, -1)
    next_is_ui[-1] = False
    prev_is_ud = np.roll(is_ud, 1)
    prev_is_ud[0] = False
    # U- whose U+ half is invisible → plain DELETE; U+ whose U- half
    # is invisible → plain INSERT
    degrade_del = vis & is_ud & next_is_ui & ~next_vis
    degrade_ins = vis & is_ui & prev_is_ud & ~prev_vis
    if degrade_del.any() or degrade_ins.any():
        ops = ops.copy()
        ops[degrade_del] = int(Op.DELETE)
        ops[degrade_ins] = int(Op.INSERT)
    cap = next_pow2(t)
    cols: List[Column] = []
    for c in chunk.columns:
        vals = np.asarray(c.values)
        if c.is_device:
            out = np.zeros(cap, dtype=vals.dtype)
        else:
            out = np.empty(cap, dtype=object)
        out[:t] = vals[idx]
        validity = None
        if c.validity is not None:
            v = np.ones(cap, dtype=bool)
            v[:t] = np.asarray(c.validity)[idx]
            validity = v
        cols.append(Column(c.data_type, out, validity))
    new_vis = np.zeros(cap, dtype=bool)
    new_vis[:t] = True
    new_ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
    new_ops[:t] = ops[idx]
    out_chunk = StreamChunk(chunk.schema, cols, new_vis, new_ops)
    out_chunk.dense_rows = t
    if chunk.capacity > cap:
        _METRICS.compaction_rows_saved.inc(chunk.capacity - cap)
    return out_chunk


def merge_chunks(chunks: Sequence[StreamChunk]) -> StreamChunk:
    """Concatenate COMPACTED chunks (dense prefixes) into one dense
    chunk. Whole-chunk concatenation only — update pairs never split."""
    assert chunks, "merge_chunks needs at least one chunk"
    if len(chunks) == 1:
        return chunks[0]
    schema = chunks[0].schema
    sizes = [c.dense_rows if c.dense_rows is not None
             else c.cardinality() for c in chunks]
    total = int(sum(sizes))
    cap = next_pow2(max(total, 1))
    ncols = len(schema)
    cols: List[Column] = []
    for j in range(ncols):
        dt = schema[j].data_type
        if dt.is_device:
            first = np.asarray(chunks[0].columns[j].values)
            out = np.zeros(cap, dtype=first.dtype)
        else:
            out = np.empty(cap, dtype=object)
        has_validity = any(c.columns[j].validity is not None
                           for c in chunks)
        validity = np.ones(cap, dtype=bool) if has_validity else None
        at = 0
        for c, n in zip(chunks, sizes):
            col = c.columns[j]
            out[at:at + n] = np.asarray(col.values)[:n]
            if has_validity and col.validity is not None:
                validity[at:at + n] = np.asarray(col.validity)[:n]
            at += n
        cols.append(Column(dt, out, validity))
    vis = np.zeros(cap, dtype=bool)
    vis[:total] = True
    ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
    at = 0
    for c, n in zip(chunks, sizes):
        ops[at:at + n] = np.asarray(c.ops)[:n]
        at += n
    out_chunk = StreamChunk(schema, cols, vis, ops)
    out_chunk.dense_rows = total
    return out_chunk


class ChunkCoalescer:
    """Barrier-bounded accumulator of small chunks.

    ``push(chunk)`` returns the chunks ready to emit NOW (possibly
    empty); ``flush()`` drains whatever is buffered. The OWNER is
    responsible for calling flush() before forwarding ANY control
    message (Barrier/Watermark/Mutation) — that call is what makes the
    linger barrier-bounded.
    """

    def __init__(self, target_rows: int = DEFAULT_TARGET_ROWS,
                 max_chunks: int = DEFAULT_MAX_CHUNKS):
        self.target_rows = max(1, int(target_rows))
        self.max_chunks = max(1, int(max_chunks))
        self._buf: List[StreamChunk] = []
        self._rows = 0
        # col_idx → latest held watermark (monotone per col, so the
        # newest value subsumes older ones)
        self._held_wms: dict = {}

    def __len__(self) -> int:
        return len(self._buf)

    @property
    def buffered_rows(self) -> int:
        return self._rows

    def push(self, chunk: StreamChunk) -> List[StreamChunk]:
        _METRICS.coalesce_chunks_in.inc()
        c = compact(chunk)
        if c is None:
            return []                       # empty chunks vanish here
        t = c.dense_rows
        out: List[StreamChunk] = []
        if t >= self.target_rows:
            # big chunk passes through; buffered older rows go FIRST
            # (emission order == arrival order)
            f = self.flush()
            if f is not None:
                out.append(f)
            _METRICS.coalesce_chunks_out.inc()
            out.append(c)
            return out
        self._buf.append(c)
        self._rows += t
        if self._rows >= self.target_rows or \
                len(self._buf) >= self.max_chunks:
            out.append(self.flush())
        return out

    def push_watermark(self, wm) -> List[Message]:
        """Re-sequence a watermark to the next flush point. With an
        empty buffer it passes straight through; otherwise it is held
        (latest per column wins — watermarks are monotone) and
        released by drain_watermarks() right after the buffered rows.
        Sound because held rows PRECEDED the watermark and rows that
        arrive later already satisfy the (monotone) bound."""
        if not self._buf:
            return [wm]
        self._held_wms[wm.col_idx] = wm
        return []

    def drain_watermarks(self) -> List[Message]:
        """Held watermarks, to emit right after a flushed batch (and
        always before a barrier)."""
        if not self._held_wms:
            return []
        out = list(self._held_wms.values())
        self._held_wms.clear()
        return out

    def flush(self) -> Optional[StreamChunk]:
        if not self._buf:
            return None
        merged = merge_chunks(self._buf)
        self._buf = []
        self._rows = 0
        _METRICS.coalesce_chunks_out.inc()
        return merged


class CoalesceExecutor(Executor):
    """Executor-chain coalescing in front of keyed executors.

    Every device dispatch downstream (hash_join/hash_agg kernels) then
    carries a dense, right-sized batch. Control messages flush the
    buffer FIRST and are never delayed — a dedicated test
    (tests/test_coalesce.py) proves a barrier cannot be held back."""

    def __init__(self, input_: Executor,
                 target_rows: int = DEFAULT_TARGET_ROWS,
                 max_chunks: int = DEFAULT_MAX_CHUNKS):
        self.input = input_
        self.target_rows = int(target_rows)
        self.max_chunks = int(max_chunks)
        super().__init__(ExecutorInfo(
            input_.schema, list(input_.pk_indices), "CoalesceExecutor"))

    async def execute(self) -> AsyncIterator[Message]:
        from risingwave_tpu.stream.message import Watermark
        co = ChunkCoalescer(self.target_rows, self.max_chunks)
        async for msg in self.input.execute():
            if is_chunk(msg):
                outs = co.push(msg)
                for out in outs:
                    yield out
                if outs:
                    # a flush happened: release watermarks that were
                    # re-sequenced behind the buffered rows
                    for wm in co.drain_watermarks():
                        yield wm
            elif isinstance(msg, Watermark):
                for out in co.push_watermark(msg):
                    yield out
            else:
                # barrier-bound invariant: whatever lingers goes out
                # BEFORE the barrier (same epoch, same order)
                f = co.flush()
                if f is not None:
                    yield f
                for wm in co.drain_watermarks():
                    yield wm
                yield msg
        # upstream ended without a trailing barrier (bounded source /
        # test pipeline): buffered rows are data, not linger — flush
        f = co.flush()
        if f is not None:
            yield f
        for wm in co.drain_watermarks():
            yield wm
