"""Actors and the local barrier manager.

Reference parity: src/stream/src/executor/actor.rs:36,121,153 (an actor is
one spawned task driving an executor chain into its DispatchExecutor,
reporting barrier completion) and src/stream/src/task/barrier_manager.rs:103,
119 (LocalBarrierManager: sends injected barriers to source actors via
registered senders, collects per-actor completion per epoch).

TPU re-design: asyncio tasks stand in for tokio tasks. Barrier *collection*
is the device sync point — an actor reports collected only after its
executors have flushed device state for the epoch (kernels launched between
barriers are free to run async until then).
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Dict, List, Optional, Sequence, Set

from risingwave_tpu.stream.dispatch import Dispatcher
from risingwave_tpu.stream.exchange import ChannelClosed, Receiver, Sender
from risingwave_tpu.stream.executor import Executor, executor_children
from risingwave_tpu.stream.message import Barrier, is_barrier, is_chunk
from risingwave_tpu.utils.metrics import STREAMING as _METRICS


def _remove_actor_series(actor_id: int) -> None:
    """Drop every stream_actor_count series for one actor id (the
    label set may carry a fragment the teardown path doesn't know)."""
    sid = str(actor_id)
    for labels, _v in _METRICS.actor_count.series():
        if labels.get("actor") == sid:
            _METRICS.actor_count.remove(**labels)


def close_receivers(ex, attrs=("rx", "barrier_rx")) -> None:
    """Release the exchange Receivers an executor tree owns.
    Deterministic teardown: the generators' own finally blocks only
    run when the abandoned async-generator chain is GC-finalized —
    one event-loop tick per nesting level — which would leave dead
    edges' queue-depth series in the registry for an unbounded number
    of ticks after a drop. The actor exit path closes only its own
    barrier channels (`barrier_rx`); chain-input receivers (`rx`)
    close after the upstream dispatcher detached the edge — closing
    them at actor exit would race a still-live upstream's dispatch
    into a ChannelClosed failure."""
    for attr in attrs:
        r = getattr(ex, attr, None)
        if isinstance(r, Receiver):
            r.close()
    for _attr, _i, child in executor_children(ex):
        close_receivers(child, attrs)


class Actor:
    """One dataflow task: executor chain → dispatchers (actor.rs:36)."""

    def __init__(self, actor_id: int, consumer: Executor,
                 dispatchers: Sequence[Dispatcher],
                 barrier_manager: Optional["LocalBarrierManager"] = None,
                 fragment: str = ""):
        self.actor_id = actor_id
        self.consumer = consumer
        self.dispatchers = list(dispatchers)
        self.barrier_manager = barrier_manager
        self.fragment = fragment
        self.failure: Optional[BaseException] = None
        # task-scoped backpressure meter: dispatch sends that park for
        # exchange credits BETWEEN executor pulls charge here; the
        # monitor's root wrapper folds it into the actor's utilization
        # tricolor at each barrier (stream/exchange.py accounting)
        self.bp_meter = [0.0]

    async def run(self) -> None:
        from risingwave_tpu.stream.exchange import set_actor_meter
        _METRICS.actor_count.set(1, actor=str(self.actor_id),
                                 fragment=self.fragment)
        mtok = set_actor_meter(self.bp_meter)
        try:
            await self._run_consumer()
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # noqa: BLE001 — report, don't swallow
            self.failure = e
            if self.barrier_manager is not None:
                self.barrier_manager.notify_failure(self.actor_id, e)
            else:
                raise
        finally:
            # restore the outer context's meter binding (no-op for
            # spawned tasks; matters when run() is awaited inline)
            import contextlib
            from risingwave_tpu.stream import exchange as _xchg
            with contextlib.suppress(ValueError):
                _xchg._METER.reset(mtok)
            _remove_actor_series(self.actor_id)
            close_receivers(self.consumer, attrs=("barrier_rx",))
            from risingwave_tpu.stream.monitor import TOPOLOGY
            TOPOLOGY.drop_actor(self.actor_id)

    async def _run_consumer(self) -> None:
        async for msg in self.consumer.execute():
            if is_chunk(msg):
                for d in self.dispatchers:
                    await d.dispatch_data(msg)
            elif is_barrier(msg):
                barrier = msg.with_passed(self.actor_id)
                for d in self.dispatchers:
                    await d.dispatch_barrier(barrier)
                # collected := barrier fully left this actor; device state
                # for the epoch is flushed (executors flush before yielding
                # the barrier downstream)
                if self.barrier_manager is not None:
                    self.barrier_manager.collect(self.actor_id, barrier)
                if barrier.is_stop(self.actor_id):
                    break
                # yield so the barrier loop observes the collect NOW:
                # without this the actor task runs straight into the
                # next epoch's first chunk (often the heaviest pull —
                # lazy kernel init, a fresh batch) before the loop's
                # waiter ever wakes, and that work lands inside the
                # COLLECTED barrier's measured interval while the phase
                # ledger attributes it to the next epoch — a systematic
                # conservation hole on the first post-deploy barriers
                await asyncio.sleep(0)
            else:
                for d in self.dispatchers:
                    await d.dispatch_watermark(msg)
        for d in self.dispatchers:
            d.close()

    def spawn(self) -> asyncio.Task:
        return asyncio.ensure_future(self.run())


class LocalBarrierManager:
    """Collects per-actor barrier completions (barrier_manager.rs:119).

    Barrier-domain scoping (ISSUE 13): ``send_barrier`` optionally
    targets a SUBSET of senders and expects a SUBSET of actors — one
    alignment domain's slice of the deployed graph. The expected set is
    captured PER EPOCH at send time, so concurrently-flowing barriers
    of different domains collect independently (their epoch values are
    globally unique — the shared EpochAllocator mints them). With no
    scope arguments the behavior is exactly the historical global
    alignment."""

    def __init__(self):
        self._barrier_senders: Dict[int, List[Sender]] = {}
        self._expected_actors: Set[int] = set()
        # epoch -> the actor set THAT barrier waits on (None: use the
        # global expected set — the unscoped legacy path). Popped with
        # the epoch's teardown state, so scoped epochs never leak.
        self._epoch_expected: Dict[int, Optional[Set[int]]] = {}
        self._collected: Dict[int, Set[int]] = {}   # epoch -> actor ids
        self._complete: Dict[int, asyncio.Event] = {}
        self._barriers: Dict[int, Barrier] = {}
        self._failed: Optional[BaseException] = None
        # epoch -> actor -> wall time of its collect() (epoch-profiler
        # input: the spread attributes a slow barrier to its straggler).
        # Bounded: entries move to the single _last_collect slot at
        # epoch completion — worker processes have no BarrierLoop to
        # drain them, and an unpopped per-epoch dict would leak one
        # entry per barrier for the life of the process.
        self._collect_times: Dict[int, Dict[int, float]] = {}
        self._last_collect: tuple = (None, {})

    # -- wiring --------------------------------------------------------
    def register_sender(self, actor_id: int, sender: Sender) -> None:
        """Source-like actors receive injected barriers via these senders."""
        self._barrier_senders.setdefault(actor_id, []).append(sender)

    def has_remote_participants(self) -> bool:
        """True when any registered sender proxies another process
        (WorkerBarrierSender.remote) — the phase ledger then defers
        conservation to the worker-ledger merge."""
        return any(getattr(s, "remote", False)
                   for senders in self._barrier_senders.values()
                   for s in senders)

    def set_expected_actors(self, actor_ids: Sequence[int]) -> None:
        self._expected_actors = set(actor_ids)

    # -- inject/collect (the InjectBarrier/BarrierComplete analog) -----
    def _expected_for(self, epoch: int) -> Set[int]:
        exp = self._epoch_expected.get(epoch)
        return self._expected_actors if exp is None else exp

    async def send_barrier(self, barrier: Barrier,
                           sender_ids: Optional[Sequence[int]] = None,
                           expected: Optional[Sequence[int]] = None
                           ) -> None:
        """Send one barrier; with ``sender_ids``/``expected`` it flows
        only through that domain's senders and completes when that
        domain's actors collected it."""
        epoch = barrier.epoch.curr.value
        self._collected.setdefault(epoch, set())
        ev = self._complete.setdefault(epoch, asyncio.Event())
        self._barriers[epoch] = barrier
        exp = None if expected is None else set(expected)
        self._epoch_expected[epoch] = exp
        if sender_ids is None:
            targets = list(self._barrier_senders.values())
        else:
            targets = [self._barrier_senders[a] for a in sender_ids
                       if a in self._barrier_senders]
        for senders in targets:
            for s in senders:
                await s.send(barrier)
        exp_now = self._expected_for(epoch)
        if not exp_now:
            ev.set()        # zero actors: the epoch completes trivially
        elif self._collected.get(epoch, set()) >= exp_now:
            # in-band collections can OUTRUN the inject RPC on a busy
            # worker: a downstream actor whose barrier arrived over the
            # exchange collected against the process-default expected
            # set before this send installed the domain's scoped one —
            # re-check completion against the scoped set, or a barrier
            # that is already fully collected wedges forever
            ev.set()

    def collect(self, actor_id: int, barrier: Barrier) -> None:
        epoch = barrier.epoch.curr.value
        got = self._collected.setdefault(epoch, set())
        got.add(actor_id)
        self._collect_times.setdefault(epoch, {})[actor_id] = \
            time.monotonic()
        ev = self._complete.setdefault(epoch, asyncio.Event())
        exp = self._expected_for(epoch)
        if exp and got >= exp:
            ev.set()

    def take_collect_times(self, epoch: int) -> Dict[int, float]:
        """Pop the per-actor collect timestamps for one epoch."""
        e, times = self._last_collect
        if e == epoch:
            self._last_collect = (None, {})
            return times
        return self._collect_times.pop(epoch, {})

    def notify_failure(self, actor_id: int, err: BaseException) -> None:
        self._failed = err
        _remove_actor_series(actor_id)
        for ev in self._complete.values():
            ev.set()

    async def await_epoch_complete(self, epoch: int) -> Barrier:
        """Block until every expected actor collected `epoch`.

        Cancellation-safe: the barrier loop's collect path races this
        wait against an async-checkpoint failure and cancels the loser
        — all bookkeeping mutation happens strictly AFTER the wait, so
        a cancelled call leaves the epoch collectible by a retry. The
        failure path cleans up its epoch's teardown state too: a wedged
        pipeline must not pin barriers/collect-times forever."""
        ev = self._complete.setdefault(epoch, asyncio.Event())
        await ev.wait()
        if self._failed is not None:
            self._collected.pop(epoch, None)
            self._complete.pop(epoch, None)
            self._collect_times.pop(epoch, None)
            self._barriers.pop(epoch, None)
            self._epoch_expected.pop(epoch, None)
            raise RuntimeError(
                f"actor failure during epoch {epoch:#x}") from self._failed
        self._collected.pop(epoch, None)
        self._complete.pop(epoch, None)
        self._epoch_expected.pop(epoch, None)
        self._last_collect = (epoch, self._collect_times.pop(epoch, {}))
        return self._barriers.pop(epoch)

    def drop_actor(self, actor_id: int) -> None:
        self._expected_actors.discard(actor_id)
        self._barrier_senders.pop(actor_id, None)
        _remove_actor_series(actor_id)
        for exp in self._epoch_expected.values():
            if exp is not None:
                exp.discard(actor_id)
        for epoch, got in self._collected.items():
            exp = self._expected_for(epoch)
            if exp and got >= exp:
                self._complete[epoch].set()
