"""BackfillExecutor: MV-on-MV via snapshot read + upstream merge.

Reference parity: src/stream/src/executor/backfill/no_shuffle_backfill.rs:68
and chain.rs:28. The algorithm is the reference's:

  per epoch, read a bounded slice of the upstream MV's COMMITTED
  snapshot in pk order from the current progress position, emitting the
  rows as Inserts; forward live upstream deltas only for pks at or
  before the progress position (later pks will be seen by the advancing
  snapshot, which re-reads at each barrier's fresh committed epoch);
  when the snapshot is exhausted, mark done and become a passthrough.

Progress is a persisted (vnode-ordered) encoded pk position, so an
interrupted backfill resumes where it stopped instead of double-feeding
downstream operators. Ordering across vnodes follows the 2-byte
big-endian vnode prefix of the state-table key encoding — byte order of
the full encoded key IS the backfill scan order.

TPU note: the snapshot rows flow as ordinary host chunks; stateful
downstream operators batch them into device steps exactly like live
traffic — backfill needs no kernel support.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Optional

import numpy as np

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.state.keycodec import encode_memcomparable
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, is_barrier, is_chunk, is_watermark,
)

# progress row: (pk=0, done flag, encoded position)
PROGRESS_SCHEMA = Schema([Field("pk", DataType.INT16),
                          Field("done", DataType.BOOLEAN),
                          Field("pos", DataType.BYTEA)])


class BackfillExecutor(Executor):
    """Snapshot-read an upstream MV, then switch to its live stream."""

    def __init__(self, upstream: Executor, mv_table: StateTable,
                 progress: StateTable,
                 snapshot_rows_per_epoch: int = 8192,
                 identity: str = "BackfillExecutor"):
        super().__init__(ExecutorInfo(
            upstream.schema, list(mv_table.pk_indices), identity))
        self.upstream = upstream
        self.mv_table = mv_table
        self.progress = progress
        self.rows_per_epoch = snapshot_rows_per_epoch
        self.done = False
        self.pos: Optional[bytes] = None    # last emitted encoded key

    # -- progress persistence --------------------------------------------
    def _load_progress(self) -> None:
        row = self.progress.get_row((0,))
        if row is not None:
            self.done = bool(row[1])
            self.pos = bytes(row[2]) if row[2] else None

    def _save_progress(self) -> None:
        old = self.progress.get_row((0,))
        new = (0, self.done, self.pos or b"")
        if old is None:
            self.progress.insert(new)
        elif tuple(old) != new:
            self.progress.update(tuple(old), new)

    # -- snapshot reading -------------------------------------------------
    def _read_snapshot_slice(self) -> List[tuple]:
        """Up to rows_per_epoch committed rows strictly after `pos`."""
        start = self.pos + b"\x00" if self.pos is not None else None
        out: List[tuple] = []
        last_key = None
        for key, row in self.mv_table.iter_encoded_range(start):
            out.append(row)
            last_key = key
            if len(out) >= self.rows_per_epoch:
                break
        if last_key is not None:
            self.pos = last_key
        if len(out) < self.rows_per_epoch:
            self.done = True
        return out

    def _snapshot_chunk(self, rows: List[tuple]) -> StreamChunk:
        cols = {f.name: [r[i] for r in rows]
                for i, f in enumerate(self.schema)}
        return StreamChunk.from_pydict(self.schema, cols)

    def _row_key(self, row: tuple) -> bytes:
        pk = tuple(row[i] for i in self.mv_table.pk_indices)
        return self.mv_table._encode_pk(pk)

    def _filter_live(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        """Forward only rows already covered by the snapshot scan."""
        if self.done:
            return chunk
        if self.pos is None:
            return None
        vis = np.asarray(chunk.visibility)
        idx, rows, _ops = chunk.to_physical_records()
        keep = np.zeros(chunk.capacity, dtype=bool)
        for i, row in zip(idx.tolist(), rows):
            if self._row_key(row) <= self.pos:
                keep[i] = True
        new_vis = vis & keep
        if not new_vis.any():
            return None
        return StreamChunk(chunk.schema, chunk.columns, new_vis,
                           chunk.ops)

    # -- main loop --------------------------------------------------------
    async def execute(self) -> AsyncIterator[Message]:
        it = self.upstream.execute()
        # The attach happens mid-epoch from the upstream's perspective:
        # operators emit their barrier-flush chunks BEFORE forwarding
        # the barrier, so the first messages may be epoch-N data. They
        # are covered by the first snapshot (read at N's committed
        # state) — drop until the subscription's first barrier.
        first = await it.__anext__()
        while not is_barrier(first):
            first = await it.__anext__()
        self.progress.init_epoch(first.epoch)
        self.mv_table.init_epoch(first.epoch)
        self._load_progress()
        yield first
        async for msg in it:
            if is_chunk(msg):
                out = self._filter_live(msg)
                if out is not None:
                    yield out
            elif is_barrier(msg):
                if not self.done:
                    # the snapshot advances to this barrier's committed
                    # epoch: rows changed since the last slice are read
                    # in their newest committed version
                    self.mv_table.init_epoch(msg.epoch)
                    rows = self._read_snapshot_slice()
                    if rows:
                        yield self._snapshot_chunk(rows)
                    self._save_progress()
                self.progress.commit(msg.epoch)
                yield msg
            elif is_watermark(msg):
                if self.done:
                    yield msg
                # during backfill watermarks are dropped: snapshot rows
                # below them are still in flight (reference buffers the
                # pending watermark; parity increment)
