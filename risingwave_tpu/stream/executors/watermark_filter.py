"""WatermarkFilterExecutor: generate event-time watermarks, drop late rows.

Reference parity: src/stream/src/executor/watermark_filter.rs:48 — the
watermark is max(event_time) - delay, monotonically advanced; rows with
event_time < current watermark are filtered out; the watermark value is
persisted in a state table at checkpoints and restored on recovery
(reference stores one row per vnode; a single-shard executor persists
one row — the vnode split returns with the dispatch layer).

TPU notes: the max() reduction and the lateness mask are one fused
vectorized pass over the padded chunk.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

import numpy as np

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import DataType, Field, Interval, Schema
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, Watermark, is_barrier, is_chunk,
)

WATERMARK_STATE_SCHEMA = Schema([Field("pk", DataType.INT16),
                                 Field("watermark", DataType.TIMESTAMP)])


class WatermarkRuntime:
    """The watermark value + its persistence alone — the runtime of a
    `watermark_filter` stage absorbed into a fused run (ops/fused.py).
    WatermarkFilterExecutor IS one (plus the executor loop); worker-
    side IR rebuilds construct the bare runtime."""

    def __init__(self, state: Optional[StateTable] = None):
        self.state = state
        self.current: Optional[int] = None

    def _persist(self) -> None:
        if self.state is None or self.current is None:
            return
        old = self.state.get_row((0,))
        row = (0, int(self.current))
        if old is None:
            self.state.insert(row)
        elif tuple(old) != row:
            self.state.update(tuple(old), row)


class WatermarkFilterExecutor(WatermarkRuntime, Executor):
    """Event-time watermark generator + late-row filter."""

    def __init__(self, input_: Executor, time_col: int, delay: Interval,
                 state: Optional[StateTable] = None):
        Executor.__init__(self, ExecutorInfo(
            input_.schema, list(input_.pk_indices),
            "WatermarkFilterExecutor"))
        WatermarkRuntime.__init__(self, state)
        self.input = input_
        self.time_col = time_col
        self.delay = delay.usecs

    async def execute(self) -> AsyncIterator[Message]:
        first_seen = False
        async for msg in self.input.execute():
            if is_barrier(msg):
                if not first_seen:
                    first_seen = True
                    if self.state is not None:
                        self.state.init_epoch(msg.epoch)
                        row = self.state.get_row((0,))
                        if row is not None:
                            self.current = int(row[1])
                    yield msg
                    if self.current is not None:
                        yield Watermark(self.time_col, DataType.TIMESTAMP,
                                        self.current)
                    continue
                self._persist()
                if self.state is not None:
                    self.state.commit(msg.epoch)
                yield msg
            elif is_chunk(msg):
                out = self._apply(msg)
                if out is not None:
                    yield out
                    wm = self.current
                    if wm is not None:
                        yield Watermark(self.time_col, DataType.TIMESTAMP,
                                        wm)
            elif isinstance(msg, Watermark):
                # upstream watermarks on other columns pass through
                if msg.col_idx != self.time_col:
                    yield msg

    def _apply(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        c = chunk.columns[self.time_col]
        ts = np.asarray(c.values).astype(np.int64)
        vis = np.asarray(chunk.visibility)
        ok = vis if c.validity is None else \
            vis & np.asarray(c.validity)
        # a row is late only relative to the watermark already EMITTED
        # (before this chunk) — filtering against the watermark derived
        # from this very chunk's max would drop every in-chunk row that
        # precedes the max, i.e. nearly everything under a small delay
        prev_wm = self.current
        if ok.any():
            mx = int(ts[ok].max()) - self.delay
            if self.current is None or mx > self.current:
                self.current = mx
        if prev_wm is None:
            return chunk
        late = ok & (ts < prev_wm)
        if not late.any():
            return chunk
        new_vis = vis & ~late
        if not new_vis.any():
            return None
        return StreamChunk(chunk.schema, chunk.columns, new_vis, chunk.ops)
