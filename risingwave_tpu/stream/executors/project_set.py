"""ProjectSetExecutor: set-returning functions in the SELECT list.

Reference parity: src/stream/src/executor/project_set.rs — each input
row expands into the rows its table function(s) return, with a hidden
``_projected_row_id`` ordinal so duplicate output rows from different
elements stay distinguishable in downstream state (the reference
prepends projected_row_id for exactly the same reason). Multiple
set-returning items zip with NULL padding (PostgreSQL ≥10 semantics);
a row whose functions all return zero rows vanishes.

Stateless: expansion is a deterministic function of the row, so a
DELETE re-expands to the matching per-element deletes. Update pairs
demote to Delete+Insert — the old and new rows may expand to
different cardinalities, so pairing cannot be preserved.

TPU note: expansion is host-side by construction (variable per-row
cardinality is a dynamic shape XLA cannot tile); the expanded chunk
re-enters the device path downstream.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import DataChunk, Op, StreamChunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.expr import Expression, InputRef
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, Watermark, is_chunk

# item kinds: ("scalar", Expression)
#             ("series", (start, stop, step) int64 Expressions)
Item = Tuple[str, object]


class ProjectSetExecutor(Executor):
    """Row expansion by table functions (project_set.rs analog)."""

    def __init__(self, input_: Executor, items: Sequence[Item],
                 names: Sequence[str], pass_pk: Sequence[int] = ()):
        assert len(items) == len(names)
        if not any(kind != "scalar" for kind, _ in items):
            raise ValueError("ProjectSet needs ≥1 set-returning item")
        fields = []
        for (kind, payload), name in zip(items, names):
            if kind == "scalar":
                fields.append(Field(name, payload.return_type))
            elif kind == "series":
                fields.append(Field(name, DataType.INT64))
            else:
                raise ValueError(f"unknown item kind {kind!r}")
        self.pass_pk = list(pass_pk)
        for j, c in enumerate(self.pass_pk):
            fields.append(Field(f"_ps_pk{j}",
                                input_.schema[c].data_type))
        fields.append(Field("_projected_row_id", DataType.INT64))
        n_items = len(items)
        pk = list(range(n_items, n_items + len(self.pass_pk) + 1))
        super().__init__(ExecutorInfo(Schema(fields), pk,
                                      "ProjectSetExecutor"))
        self.input = input_
        self.items = list(items)
        self.names = list(names)

    async def execute(self) -> AsyncIterator[Message]:
        schema = self.schema
        # positional build: output names may collide (two unaliased
        # generate_series items are both named so), and a name-keyed
        # from_pydict would silently collapse them
        tmp_schema = Schema([Field(f"_c{i}", f.data_type)
                             for i, f in enumerate(schema)])
        async for msg in self.input.execute():
            if isinstance(msg, Watermark):
                # a watermark survives only through a scalar passthrough
                for j, (kind, payload) in enumerate(self.items):
                    if kind == "scalar" and \
                            isinstance(payload, InputRef) and \
                            payload.index == msg.col_idx:
                        yield Watermark(j, msg.data_type, msg.value)
                        break
                continue
            if not is_chunk(msg):
                yield msg
                continue
            rows, ops = self._expand(msg)
            if not rows:
                continue
            data = {f"_c{i}": [r[i] for r in rows]
                    for i in range(len(schema))}
            out = StreamChunk.from_pydict(tmp_schema, data, ops=ops)
            yield StreamChunk(schema, out.columns, out.visibility,
                              out.ops)

    def _expand(self, msg: StreamChunk):
        # evaluate every needed expression once per chunk, then pull
        # the host values through one temporary DataChunk
        eval_cols, eval_fields = [], []

        def add(expr: Expression):
            eval_cols.append(expr.eval(msg))
            eval_fields.append(
                Field(f"_e{len(eval_fields)}", expr.return_type))

        for kind, payload in self.items:
            if kind == "scalar":
                add(payload)
            else:
                for a in payload:
                    add(a)
        for c in self.pass_pk:
            eval_cols.append(msg.columns[c])
            eval_fields.append(Field(f"_e{len(eval_fields)}",
                                     msg.schema[c].data_type))
        tmp = DataChunk(Schema(eval_fields), eval_cols,
                        msg.visibility)
        vals = tmp.to_pylist(compact=False)
        vis = np.asarray(msg.visibility)
        in_ops = np.asarray(msg.ops)

        out_rows: List[tuple] = []
        out_ops: List[int] = []
        for i, row in enumerate(vals):
            if not vis[i]:
                continue
            # old/new rows may expand to different cardinalities, so
            # update pairs cannot stay paired
            op = Op(int(in_ops[i]))
            op = Op.DELETE if op == Op.UPDATE_DELETE else (
                Op.INSERT if op == Op.UPDATE_INSERT else op)
            pos = 0
            cells: List[object] = []      # per item: value or list
            n = 0
            for kind, payload in self.items:
                if kind == "scalar":
                    cells.append(("s", row[pos]))
                    pos += 1
                else:
                    start, stop, step = row[pos], row[pos + 1], \
                        row[pos + 2]
                    pos += 3
                    if start is None or stop is None or step is None \
                            or step == 0:
                        series: List[int] = []
                    else:
                        s, e, st = int(start), int(stop), int(step)
                        series = list(range(
                            s, e + (1 if st > 0 else -1), st))
                    cells.append(("f", series))
                    n = max(n, len(series))
            if n == 0:
                continue                  # all functions empty: no row
            pk_vals = tuple(row[pos:])
            for k in range(n):
                out = []
                for tag, v in cells:
                    if tag == "s":
                        out.append(v)
                    else:
                        out.append(v[k] if k < len(v) else None)
                out_rows.append(tuple(out) + pk_vals + (k,))
                out_ops.append(int(op))
        return out_rows, out_ops
