"""HopWindowExecutor: expand rows into their sliding (hop) windows.

Reference parity: src/stream/src/executor/hop_window.rs:91 — with
`units = window_size / window_slide` (must divide exactly), each input
chunk yields `units` output chunks; copy i carries the i-th covering
window's [window_start, window_end]. Window starts covering ts are
  floor(ts / slide) * slide - i * slide,   i in 0..units-1
(one tumble by `slide`, then shifted copies) — all vectorized.

Rows whose timestamp is NULL are dropped (reference behavior: the window
expression evaluates to NULL and downstream grouping would discard them;
we mask them out up front).
"""

from __future__ import annotations

from typing import AsyncIterator, List

import numpy as np

from risingwave_tpu.common.chunk import Column, StreamChunk
from risingwave_tpu.common.types import DataType, Field, Interval, Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, Watermark, is_chunk


class HopWindowExecutor(Executor):
    """Sliding-window expansion (hop_window.rs:91 analog)."""

    def __init__(self, input_: Executor, time_col: int,
                 window_slide: Interval, window_size: Interval,
                 pk_indices: List[int] = ()):
        slide, size = window_slide.usecs, window_size.usecs
        if slide <= 0 or size % slide != 0:
            raise ValueError(
                f"window_size {size}us not divisible by slide {slide}us")
        self.units = size // slide
        self.slide = slide
        self.size = size
        self.time_col = time_col
        fields = [Field(f.name, f.data_type) for f in input_.schema]
        fields.append(Field("window_start", DataType.TIMESTAMP))
        fields.append(Field("window_end", DataType.TIMESTAMP))
        super().__init__(ExecutorInfo(Schema(fields), list(pk_indices),
                                      "HopWindowExecutor"))
        self.input = input_

    async def execute(self) -> AsyncIterator[Message]:
        ws_idx = len(self.input.schema)
        async for msg in self.input.execute():
            if isinstance(msg, Watermark):
                if msg.col_idx == self.time_col:
                    # a bound on ts is a bound on the last window's start
                    base = (int(msg.value) // self.slide) * self.slide
                    yield Watermark(ws_idx, DataType.TIMESTAMP,
                                    base - (self.units - 1) * self.slide)
                continue
            if not is_chunk(msg):
                yield msg
                continue
            c = msg.columns[self.time_col]
            ts = np.asarray(c.values)
            vis = np.asarray(msg.visibility)
            if c.validity is not None:
                vis = vis & np.asarray(c.validity)
            base = (ts.astype(np.int64) // self.slide) * self.slide
            for i in range(self.units):
                start = base - i * self.slide
                cols = list(msg.columns)
                cols.append(Column(DataType.TIMESTAMP, start, None))
                cols.append(Column(DataType.TIMESTAMP, start + self.size,
                                   None))
                yield StreamChunk(self.schema, cols, vis, msg.ops)
