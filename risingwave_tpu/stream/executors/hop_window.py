"""HopWindowExecutor: expand rows into their sliding (hop) windows.

Reference parity: src/stream/src/executor/hop_window.rs:91 — with
`units = window_size / window_slide` (must divide exactly), each input
chunk yields `units` output chunks; copy i carries the i-th covering
window's [window_start, window_end]. Window starts covering ts are
  floor(ts / slide) * slide - i * slide,   i in 0..units-1
(one tumble by `slide`, then shifted copies) — all vectorized.

Rows whose timestamp is NULL are dropped (reference behavior: the window
expression evaluates to NULL and downstream grouping would discard them;
we mask them out up front).
"""

from __future__ import annotations

from typing import AsyncIterator, List

import numpy as np

from risingwave_tpu.common.chunk import Column, StreamChunk
from risingwave_tpu.common.types import DataType, Field, Interval, Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, Watermark, is_chunk


class HopWindowExecutor(Executor):
    """Sliding-window expansion (hop_window.rs:91 analog)."""

    def __init__(self, input_: Executor, time_col: int,
                 window_slide: Interval, window_size: Interval,
                 pk_indices: List[int] = ()):
        slide, size = window_slide.usecs, window_size.usecs
        if slide <= 0 or size % slide != 0:
            raise ValueError(
                f"window_size {size}us not divisible by slide {slide}us")
        self.units = size // slide
        self.slide = slide
        self.size = size
        self.time_col = time_col
        fields = [Field(f.name, f.data_type) for f in input_.schema]
        fields.append(Field("window_start", DataType.TIMESTAMP))
        fields.append(Field("window_end", DataType.TIMESTAMP))
        super().__init__(ExecutorInfo(Schema(fields), list(pk_indices),
                                      "HopWindowExecutor"))
        self.input = input_

    async def execute(self) -> AsyncIterator[Message]:
        ws_idx = len(self.input.schema)
        async for msg in self.input.execute():
            if isinstance(msg, Watermark):
                if msg.col_idx == self.time_col:
                    # a bound on ts is a bound on the last window's start
                    base = (int(msg.value) // self.slide) * self.slide
                    yield Watermark(ws_idx, DataType.TIMESTAMP,
                                    base - (self.units - 1) * self.slide)
                continue
            if not is_chunk(msg):
                yield msg
                continue
            c = msg.columns[self.time_col]
            ts = np.asarray(c.values)
            vis = np.asarray(msg.visibility)
            if c.validity is not None:
                vis = vis & np.asarray(c.validity)
            base = (ts.astype(np.int64) // self.slide) * self.slide
            # Batched expansion (ISSUE 12): pow2 GROUPS of copy-major
            # replicas — ⌈log2⌉ chunks per input chunk instead of
            # `units` (5 windows → one 4×-copy chunk + one 1×-copy
            # chunk), so the downstream spine (exchange frames,
            # coalescer, monitor, join ingest) pays ~2 chunks of
            # overhead instead of 5 while every emitted capacity stays
            # a power of two — kernel backlogs (BATCH_ROWS slabs) keep
            # packing tight, which a single `units`×-cap chunk broke.
            # Copy-major tiling keeps U-/U+ pairs adjacent inside every
            # copy, group boundaries land exactly on copy boundaries,
            # and a well-formed chunk never ends with a dangling U-,
            # so pair scans never marry rows across copies.
            host_cols = [(np.asarray(c.values),
                          None if c.validity is None
                          else np.asarray(c.validity))
                         for c in msg.columns]
            ops = np.asarray(msg.ops)
            i = 0
            units = self.units
            while i < units:
                g = 1 << ((units - i).bit_length() - 1)
                starts = base - i * self.slide if g == 1 else \
                    np.concatenate([base - (i + j) * self.slide
                                    for j in range(g)])
                cols = [Column(c.data_type,
                               vals if g == 1 else np.tile(vals, g),
                               ok if ok is None or g == 1
                               else np.tile(ok, g))
                        for c, (vals, ok) in zip(msg.columns,
                                                 host_cols)]
                cols.append(Column(DataType.TIMESTAMP, starts, None))
                cols.append(Column(DataType.TIMESTAMP,
                                   starts + self.size, None))
                yield StreamChunk(
                    self.schema, cols,
                    vis if g == 1 else np.tile(vis, g),
                    ops if g == 1 else np.tile(ops, g))
                i += g
