"""HashJoinExecutor: streaming two-sided equi-join (inner, q8 kernel).

Reference parity: src/stream/src/executor/hash_join.rs:227 (executor),
:697 (main loop over barrier-aligned sides), :990 (``eq_join_oneside``);
state layout managed_state/join/mod.rs:228 (JoinHashMap). TPU re-design
(ops/hash_join.py): the device owns the MATCH structure — key table +
row chains probed as whole-batch kernels; the host owns row payloads
(typed column arenas; varchar never ships to HBM) and materializes
output chunks with vectorized gathers.

Chunk lifecycle on side S (probing side O), mirroring eq_join_oneside
but ASYNC (sequence-versioned state, see ops/hash_join.py):
  1. dispatch: submit the fused probe against O at the chunk's message
     sequence (DMA starts; nothing blocks) and apply the chunk to S's
     own state at the same sequence (inserts allocate arena refs and
     front-link; deletes tombstone)
  2. barrier (or a watermark that must trail the data): collect every
     in-flight probe in message order — each result is exact for its
     sequence no matter how much state advanced — and emit: matched
     pairs (S columns from the chunk, O columns from O's arena), outer
     NULL-padding, semi/anti rows, and degree-transition flips. Update
     pairs degrade to Delete+Insert, as the reference degrades split
     pairs.
  3. both sides' StateTables commit; watermark expiry and compaction
     run AFTER the sweep (they rewrite device state that a re-
     dispatched probe would need); recovery rebuilds arena + chains
     and recomputes degrees with one batch probe.

Inner-join NULL semantics: rows whose join key contains NULL can never
match and are not stored (the reference's null-safe flag is per-column;
non-null-safe is the SQL default).
"""

from __future__ import annotations

import enum
from typing import AsyncIterator, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


class JoinType(enum.Enum):
    """The 8 streaming join types (hash_join.rs:61-71 const generics).

    Outer sides track per-stored-row match DEGREES. The reference
    persists degree state tables (managed_state/join/mod.rs:228); here
    degrees are a host int64 array parallel to the arena, recomputed on
    recovery by ONE batch probe of the recovered keys against the other
    side — the degree is a pure function of both sides' state, so
    persisting it buys nothing but write amplification.
    """

    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"
    LEFT_SEMI = "left_semi"
    LEFT_ANTI = "left_anti"
    RIGHT_SEMI = "right_semi"
    RIGHT_ANTI = "right_anti"

    @property
    def is_semi_or_anti(self) -> bool:
        return self in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
                        JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI)

    @property
    def is_anti(self) -> bool:
        return self in (JoinType.LEFT_ANTI, JoinType.RIGHT_ANTI)

    @property
    def subject(self) -> Optional[int]:
        """Side whose rows a semi/anti join emits (0=left, 1=right)."""
        if self in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            return 0
        if self in (JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI):
            return 1
        return None

    @property
    def tracked_sides(self) -> tuple:
        """Sides whose stored rows need degree maintenance."""
        if self == JoinType.LEFT_OUTER:
            return (0,)
        if self == JoinType.RIGHT_OUTER:
            return (1,)
        if self == JoinType.FULL_OUTER:
            return (0, 1)
        if self.is_semi_or_anti:
            return (self.subject,)
        return ()

    def outer_on(self, side: int) -> bool:
        """Does `side` emit NULL-padded rows when unmatched?"""
        if self == JoinType.FULL_OUTER:
            return True
        return (self == JoinType.LEFT_OUTER and side == 0) or \
            (self == JoinType.RIGHT_OUTER and side == 1)

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.ops.hash_join import JoinSideKernel
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.merge import barrier_align_2
from risingwave_tpu.stream.executors.keys import (
    LANES_PER_KEY, KeyCodec,
)
from risingwave_tpu.stream.message import Message, Watermark, is_barrier
from risingwave_tpu.stream.trace_ctx import dispatch_span
from risingwave_tpu.stream import hotkeys as _hotkeys
from risingwave_tpu.utils.metrics import STREAMING as _METRICS


class _Arena:
    """Host row store: typed column arrays indexed by device row refs."""

    def __init__(self, schema: Schema, capacity: int = 1024):
        self.schema = schema
        self.cap = capacity
        self.cols: List[np.ndarray] = []
        self.valid: List[np.ndarray] = []
        for f in schema:
            dt = f.data_type
            self.cols.append(
                np.zeros(capacity, dtype=dt.np_dtype) if dt.is_device
                else np.empty(capacity, dtype=object))
            self.valid.append(np.ones(capacity, dtype=bool))

    def ensure(self, max_ref: int) -> None:
        if max_ref < self.cap:
            return
        new_cap = self.cap
        while new_cap <= max_ref:
            new_cap *= 2
        for i, c in enumerate(self.cols):
            grown = np.zeros(new_cap, dtype=c.dtype) if c.dtype != object \
                else np.empty(new_cap, dtype=object)
            grown[:self.cap] = c
            self.cols[i] = grown
            v = np.ones(new_cap, dtype=bool)
            v[:self.cap] = self.valid[i]
            self.valid[i] = v
        self.cap = new_cap

    def store(self, refs: np.ndarray, chunk: StreamChunk,
              row_idx: np.ndarray) -> None:
        if not len(refs):
            return
        self.ensure(int(refs.max()))
        for i, c in enumerate(chunk.columns):
            vals = np.asarray(c.values)[row_idx]
            self.cols[i][refs] = vals
            self.valid[i][refs] = True if c.validity is None else \
                np.asarray(c.validity)[row_idx]

    def gather(self, refs: np.ndarray, out_cap: int
               ) -> List[Column]:
        return [self.gather_col(i, refs, out_cap)
                for i in range(len(self.schema))]

    def gather_col(self, i: int, refs: np.ndarray,
                   out_cap: int) -> Column:
        f, c, v = self.schema[i], self.cols[i], self.valid[i]
        vals = np.zeros(out_cap, dtype=c.dtype) if c.dtype != object \
            else np.empty(out_cap, dtype=object)
        vals[:len(refs)] = c[refs]
        ok = np.ones(out_cap, dtype=bool)
        ok[:len(refs)] = v[refs]
        return Column(f.data_type, vals, None if ok.all() else ok)


class _JoinSide:
    """One side's state: device matcher + host arena + durability.

    With a mesh, the matcher is the vnode-sharded SPMD kernel
    (parallel/join.ShardedJoinKernel) — same API, rows routed to their
    key's owner shard by an in-program all_to_all (the reference's
    hash dispatch to N parallel join actors, dispatch.rs:582)."""

    def __init__(self, schema: Schema, key_indices: Sequence[int],
                 pk_indices: Sequence[int], table: StateTable,
                 key_codec: KeyCodec, mesh=None,
                 shard_opts: Optional[dict] = None,
                 device_payload: bool = True):
        self.schema = schema
        self.key_indices = list(key_indices)
        self.pk_indices = list(pk_indices)
        self.key_types = [schema[i].data_type for i in self.key_indices]
        # SHARED with the other side: equal values must get equal
        # interned ids or varchar keys would never match
        self.key_codec = key_codec
        self.table = table
        # device-resident payload lanes (ops/hash_join.py): every
        # device-typed column of a stored row lives in HBM as a
        # (hi, lo, valid) int32 triple indexed by row ref, written in
        # the same dispatch that links chains and gathered ON DEVICE
        # by the probe's emit walk. Varchar/host-typed columns can
        # never ship to HBM — they stay arena-gathered by ref from the
        # same packed header. Single-chip epoch path only (the sharded
        # kernel keeps the per-chunk host-gather shape).
        self.device_payload = bool(device_payload) and mesh is None
        self.pay_indices: List[int] = [
            i for i, f in enumerate(schema) if f.data_type.is_device
        ] if self.device_payload else []
        self.pay_pos: Dict[int, int] = {
            c: k for k, c in enumerate(self.pay_indices)}
        # fused input run (frontend/opt/fusion.py try_fuse_join):
        # `schema` above is the run's OUTPUT space; chunks arrive raw,
        # the composed chain runs once on numpy for host bookkeeping,
        # and the device prelude re-derives the upload lanes inside
        # the epoch dispatches (ops/fused.build_join_prelude)
        self.fused_input = None
        self._prelude = None
        self._prelude_cache_key = None
        # device kernel is built LAZILY (first data touch): building it
        # here would initialize the JAX backend — and claim the TPU —
        # in processes that only PLAN (the distributed frontend
        # serializes the executor tree to IR and discards it)
        self._mesh = mesh
        self._shard_opts = dict(shard_opts or {})
        self._kernel = None
        self.arena = _Arena(schema)
        self.pk_to_ref: Dict[tuple, int] = {}
        self.free: List[int] = []
        self.next_ref = 0
        # cold-state tier (managed_state/join/mod.rs:379-420 LRU-over-
        # StateTable analog, driven by state/tier.py): the tier's sweep
        # hands this side the coldest keys — their rows leave the arena
        # + device (see evict_keys) but stay durable in the state
        # table; a later probe of an evicted key reloads it first (see
        # HashJoinExecutor._reload_cold). cold_keys: key LANES tuple →
        # key VALUES tuple (the values drive the state-table prefix
        # scan on reload)
        self.state_cap: Optional[int] = None
        self.cold_keys: Dict[tuple, tuple] = {}
        # lanes of keys watermark-expiry dropped (resident AND cold) —
        # the executor drains these into tier.forget after each sweep
        self.expired_lanes: List[tuple] = []
        # per-ref match degree (outer/semi/anti bookkeeping; see
        # JoinType docstring). On the single-chip epoch path the
        # AUTHORITATIVE copy is the kernel's device array, maintained
        # inside the probe dispatches (ops/hash_join.epoch_probe) —
        # this host array then stays empty and emission replays
        # per-chunk transitions from the packed matrix's old-degree
        # column. The sharded per-chunk path keeps the host array.
        self.dev_degrees = mesh is None
        self.track_degrees = False      # set by the executor (tracked
        self.degrees = np.zeros(         # sides only)
            0 if self.dev_degrees else self.arena.cap, dtype=np.int64)

    @property
    def prelude(self):
        """Traced lane builder for the fused input run (lazy — builds
        against the jnp expression layer on first dispatch)."""
        if self._prelude is None and self.fused_input is not None:
            from risingwave_tpu.ops.fused import build_join_prelude
            self._prelude = build_join_prelude(
                self.fused_input, self.key_indices, self.pay_indices)
        return self._prelude

    @property
    def kernel(self):
        if self._kernel is None:
            if self._mesh is not None:
                from risingwave_tpu.parallel.join import ShardedJoinKernel
                self._kernel = ShardedJoinKernel(
                    self._mesh,
                    key_width=LANES_PER_KEY * len(self.key_indices),
                    **self._shard_opts)
            else:
                # capacity presize hints ride in shard_opts for the
                # single-chip kernel too: every growth doubling costs
                # a rehash + a fresh XLA trace/compile of the epoch
                # programs, so a builder that knows its cardinality
                # should say so
                opts = {k: v for k, v in self._shard_opts.items()
                        if k in ("key_capacity", "row_capacity",
                                 "probe_capacity")}
                self._kernel = JoinSideKernel(
                    key_width=LANES_PER_KEY * len(self.key_indices),
                    payload_width=3 * len(self.pay_indices),
                    **opts)
        return self._kernel

    def _row_key_lanes(self, chunk: StreamChunk, r: int
                       ) -> Optional[tuple]:
        """One row's join-key lanes tuple (the cold_keys key), or None
        when any key column is NULL — null keys are never stored, so
        they cannot be cold. Miss-path only (rare)."""
        vals = []
        for i in self.key_indices:
            c = chunk.columns[i]
            v = np.asarray(c.values)[r]
            if c.validity is not None and \
                    not bool(np.asarray(c.validity)[r]):
                return None
            vals.append(v.item() if hasattr(v, "item") else v)
        if any(v is None for v in vals):
            return None
        return tuple(self.key_codec.lanes_of_values(vals).tolist())

    def ensure_degrees(self, max_ref: int) -> None:
        if self.dev_degrees or max_ref < len(self.degrees):
            return
        grown = np.zeros(self.arena.cap, dtype=np.int64)
        grown[:len(self.degrees)] = self.degrees
        self.degrees = grown

    def nbytes(self) -> int:
        """Accounted host state (EstimateSize analog): arena columns,
        degree array, pk→ref map."""
        arena = sum(
            c.nbytes if c.dtype != object else c.size * 8
            for c in self.arena.cols)
        return arena + self.degrees.nbytes + 120 * len(self.pk_to_ref)

    def host_arena_bytes(self) -> int:
        """The residency metric's host half (arena columns only)."""
        return sum(c.nbytes if c.dtype != object else c.size * 8
                   for c in self.arena.cols)

    # -- device payload lanes (ops/lanes.py payload codecs) ---------------
    def payload_rows(self, chunk: StreamChunk) -> np.ndarray:
        """int32[cap, 3*len(pay_indices)] payload lanes for every slot
        (the device scatter masks non-inserted rows itself)."""
        from risingwave_tpu.ops.lanes import payload_lanes
        return payload_lanes(
            [(np.asarray(chunk.columns[i].values),
              None if chunk.columns[i].validity is None
              else np.asarray(chunk.columns[i].validity))
             for i in self.pay_indices])

    def payload_from_arena(self, refs: np.ndarray) -> np.ndarray:
        """Payload lanes of stored rows (recovery / compaction /
        cold-tier reload rebuild the device store from the durable
        host copy)."""
        from risingwave_tpu.ops.lanes import payload_lanes
        return payload_lanes(
            [(self.arena.cols[i][refs], self.arena.valid[i][refs])
             for i in self.pay_indices])

    def cols_from_payload(self, pay_rows: np.ndarray,
                          refs: np.ndarray, out_cap: int
                          ) -> List[Column]:
        """Materialize matched rows from the packed probe matrix:
        device-typed columns decode from the device-gathered payload
        lanes; varchar/host-typed columns gather from the arena by ref
        (the only host gathers left on the emit path)."""
        from risingwave_tpu.ops import lanes as _lanes
        t = len(refs)
        out: List[Column] = []
        for i, f in enumerate(self.schema):
            k = self.pay_pos.get(i)
            if k is None:
                out.append(self.arena.gather_col(i, refs, out_cap))
                continue
            hi = pay_rows[:, 3 * k].astype(np.int64)
            lo = pay_rows[:, 3 * k + 1]
            v64 = (hi << np.int64(32)) | \
                lo.view(np.uint32).astype(np.int64)
            dt = np.dtype(f.data_type.np_dtype)
            vals = np.zeros(out_cap, dtype=dt)
            vals[:t] = _lanes.decode_payload_i64(v64, dt)
            ok = np.ones(out_cap, dtype=bool)
            ok[:t] = pay_rows[:, 3 * k + 2] != 0
            out.append(Column(f.data_type, vals,
                              None if ok.all() else ok))
        return out

    def row_tuple(self, ref: int) -> tuple:
        return tuple(
            None if not self.arena.valid[i][ref]
            else (self.arena.cols[i][ref].item()
                  if self.schema[i].data_type.is_device
                  else self.arena.cols[i][ref])
            for i in range(len(self.schema)))

    def alloc_refs(self, k: int) -> np.ndarray:
        """Bump allocation ONLY: a tombstoned ref stays linked in its
        chain (deletes unlink lazily), so reusing it would splice its
        node into a second chain and create cycles. Dead refs are
        reclaimed wholesale when the arena is rebuilt (recovery /
        future compaction); `self.free` tracks the reclaimable count."""
        out = np.arange(self.next_ref, self.next_ref + k, dtype=np.int32)
        self.next_ref += k
        return out

    def key_nonnull_mask(self, chunk: StreamChunk) -> np.ndarray:
        m = np.ones(chunk.capacity, dtype=bool)
        for i in self.key_indices:
            c = chunk.columns[i]
            if c.validity is not None:
                m &= np.asarray(c.validity)
            if not c.data_type.is_device:
                # host-typed columns carry NULL as the None object
                vals = np.asarray(c.values)
                m &= np.fromiter(
                    (isinstance(v, (str, bytes)) for v in vals.tolist()),
                    dtype=bool, count=chunk.capacity)
        return m

    def apply_chunk_host(self, chunk: StreamChunk,
                         nonnull: Optional[np.ndarray] = None
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                    np.ndarray, np.ndarray, np.ndarray]:
        """HOST half of a chunk apply: pk→ref/arena bookkeeping only.
        Returns (ins_idx, ins_refs, full_refs, ins_mask, del_refs,
        del_mask) for ONE fused device dispatch (ops/hash_join.py
        apply_and_probe) — per-chunk device calls are the TPU hot-path
        cost, so the executor batches them all into one.

        pk→ref bookkeeping runs in ROW ORDER (a delete refers to the
        latest same-pk version, which may be an insert earlier in this
        very chunk — update pairs land as [U-, U+] with one pk); an
        all-insert chunk (append-only sources — the common case) takes
        a bulk dict.update instead of the per-row loop."""
        vis = np.asarray(chunk.visibility)
        if nonnull is None:
            nonnull = self.key_nonnull_mask(chunk)
        storable = vis & nonnull
        ops = np.asarray(chunk.ops)
        is_ins = (ops == int(Op.INSERT)) | (ops == int(Op.UPDATE_INSERT))
        ins_idx = np.flatnonzero(storable & is_ins)
        # pk extraction: one vectorized pass (tolist + zip run in C;
        # a per-row generator here dominated the q8 host profile)
        st_idx = np.flatnonzero(storable)
        pk_lists = []
        for i in self.pk_indices:
            c = chunk.columns[i]
            vals = np.asarray(c.values)[st_idx]
            col = vals.tolist()
            if c.validity is not None:
                okv = np.asarray(c.validity)[st_idx]
                col = [None if not o else v
                       for v, o in zip(col, okv.tolist())]
            pk_lists.append(col)
        pk_tuples = list(zip(*pk_lists)) if pk_lists \
            else [()] * len(st_idx)

        ins_refs = self.alloc_refs(len(ins_idx))
        del_refs = np.zeros(chunk.capacity, dtype=np.int32)
        del_mask = np.zeros(chunk.capacity, dtype=bool)
        if len(ins_idx) == len(st_idx):
            # append-only fast path: no deletes, refs align with pks
            self.pk_to_ref.update(zip(pk_tuples, ins_refs.tolist()))
        else:
            pks = dict(zip(st_idx.tolist(), pk_tuples))
            ins_pos = {int(r): j for j, r in enumerate(ins_idx)}
            for r in st_idx.tolist():
                if r in ins_pos:
                    self.pk_to_ref[pks[r]] = int(ins_refs[ins_pos[r]])
                else:
                    ref = self.pk_to_ref.pop(pks[r], None)
                    if ref is None:
                        # unseen pk: either an inconsistent delete
                        # (ignore, reference behavior) or — with the
                        # cold tier on — a retraction for an EVICTED
                        # key, whose device bookkeeping cannot be
                        # applied. The planner only enables state_cap
                        # on provably append-only inputs; failing loud
                        # here beats leaving already-emitted join
                        # outputs permanently stale (ADVICE r5 high).
                        if self.cold_keys and \
                                self._row_key_lanes(chunk, r) \
                                in self.cold_keys:
                            raise RuntimeError(
                                "join cold-state tier got a retraction "
                                "for an evicted key — state_cap "
                                "requires append-only inputs (the "
                                "planner disables the cap when it "
                                "cannot prove them)")
                        continue
                    del_refs[r] = ref
                    del_mask[r] = True
                    self.free.append(ref)
        full_refs = np.zeros(chunk.capacity, dtype=np.int32)
        ins_mask = np.zeros(chunk.capacity, dtype=bool)
        if len(ins_idx):
            self.arena.store(ins_refs, chunk, ins_idx)
            self.ensure_degrees(int(ins_refs.max()))
            full_refs[ins_idx] = ins_refs
            ins_mask[ins_idx] = True
        # append-only epochs stage past the memtable (ISSUE 12): join
        # state pks are upstream row identities, distinct per epoch by
        # the changelog contract; mixed-op chunks spill and merge
        self.table.write_chunk(chunk, defer=True)
        return ins_idx, ins_refs, full_refs, ins_mask, del_refs, del_mask

    # dead-ref fraction of the arena that triggers a compaction; dead
    # refs cannot be recycled in place (see alloc_refs), so churn-heavy
    # streams (update pairs every epoch) reclaim them wholesale here
    COMPACT_DEAD_RATIO = 0.5
    COMPACT_MIN_REFS = 4096

    def maybe_compact(self) -> bool:
        if (self.next_ref < self.COMPACT_MIN_REFS
                or len(self.free) < self.COMPACT_DEAD_RATIO * self.next_ref):
            return False
        self.compact()
        return True

    def compact(self) -> None:
        """Rebuild arena + device state with only live rows (dense refs)."""
        live = np.fromiter(self.pk_to_ref.values(), dtype=np.int64,
                           count=len(self.pk_to_ref))
        n = len(live)
        # degrees survive a pure compaction: snapshot before the device
        # rebuild resets them (device mode reads the kernel array; the
        # cold-tier evict path recomputes on reload instead, but a
        # stale value at a never-again-probed ref is unobservable)
        if self.dev_degrees:
            live_deg = self.kernel.read_degrees(live) \
                if (self.track_degrees and n) else None
        new_arena = _Arena(self.schema,
                           capacity=max(1024, next_pow2(max(n, 1))))
        for i in range(len(self.schema)):
            new_arena.cols[i][:n] = self.arena.cols[i][live]
            new_arena.valid[i][:n] = self.arena.valid[i][live]
        if not self.dev_degrees:
            new_degrees = np.zeros(new_arena.cap, dtype=np.int64)
            new_degrees[:n] = self.degrees[live]
            self.degrees = new_degrees
        self.arena = new_arena
        new_refs = np.arange(n, dtype=np.int32)
        self.pk_to_ref = dict(zip(self.pk_to_ref.keys(), new_refs.tolist()))
        self.free = []
        self.next_ref = n
        if n:
            key_cols = [(self.arena.cols[i][:n], self.arena.valid[i][:n])
                        for i in self.key_indices]
            kw = {"payload": self.payload_from_arena(new_refs)} \
                if self.pay_indices else {}
            self.kernel.rebuild(
                self.key_codec.build_arrays(key_cols), new_refs, **kw)
        else:
            self.kernel.rebuild(
                np.zeros((0, LANES_PER_KEY * len(self.key_indices)),
                         dtype=np.int32),
                new_refs)
        if self.dev_degrees and live_deg is not None:
            self.kernel.write_degrees(new_refs, live_deg)

    def expire_below(self, key_pos: int, wm_physical,
                     seq: int = 0) -> int:
        """Watermark state expiry (hash_join.rs:860-945 analog): drop
        every stored row whose ``key_pos``-th join-key column is below
        the watermark. Host side: vectorized scan of live refs → dead
        pks removed from the map, rows deleted from the state table,
        refs tombstoned on device (the existing compaction reclaims the
        arena/chain slots when the dead ratio crosses its threshold).
        Cost is O(live) per call — the executor only calls this when the
        combined watermark actually advances. Cold (evicted) keys below
        the watermark expire too: their durable rows delete and the
        cold marker drops — otherwise the state table would grow
        without bound on exactly the keys-drift workloads the cold
        tier exists for."""
        n_cold = 0
        if self.cold_keys:
            dead_cold = [
                (lt, vt) for lt, vt in self.cold_keys.items()
                if vt[key_pos] is not None
                and int(vt[key_pos]) < int(wm_physical)]
            for lt, vt in dead_cold:
                del self.cold_keys[lt]
                self.expired_lanes.append(lt)
                dead_rows = [tuple(row) for _pk, row
                             in self.table.iter_prefix(list(vt))]
                if dead_rows:
                    self.table.delete_rows(dead_rows)
                    n_cold += len(dead_rows)
        if not self.pk_to_ref:
            return n_cold
        col = self.key_indices[key_pos]
        refs = np.fromiter(self.pk_to_ref.values(), dtype=np.int64,
                           count=len(self.pk_to_ref))
        vals = self.arena.cols[col][refs]
        ok = self.arena.valid[col][refs]
        dead = ok & (vals.astype(np.int64) < int(wm_physical))
        n_dead = int(dead.sum())
        if n_dead == 0:
            return n_cold
        dead_refs = refs[dead].astype(np.int32)
        pks = list(self.pk_to_ref.keys())
        dead_pks = [pks[i] for i in np.flatnonzero(dead).tolist()]
        for pk, ref in zip(dead_pks, dead_refs.tolist()):
            del self.pk_to_ref[pk]
            self.free.append(ref)
        self.table.delete_rows([self.row_tuple(r)
                                for r in dead_refs.tolist()])
        cap = next_pow2(n_dead)
        del_refs = np.zeros(cap, dtype=np.int32)
        del_refs[:n_dead] = dead_refs
        mask = np.zeros(cap, dtype=bool)
        mask[:n_dead] = True
        # key lanes of the dead refs: the sharded kernel routes the
        # tombstone to the key's owner shard (single-chip ignores them)
        key_cols = [(self.arena.cols[i][dead_refs],
                     self.arena.valid[i][dead_refs])
                    for i in self.key_indices]
        dead_lanes = self.key_codec.build_arrays(key_cols)
        if self.state_cap is not None:
            # tier bookkeeping only: an uncapped side must not grow
            # this list forever (the executor drains it per barrier,
            # but only tiered sides have anything to forget)
            self.expired_lanes.extend(
                map(tuple, np.unique(dead_lanes, axis=0).tolist()))
        lanes_ = np.zeros((cap, LANES_PER_KEY * len(self.key_indices)),
                          dtype=np.int32)
        lanes_[:n_dead] = dead_lanes
        self.kernel.delete(del_refs, mask, seq=seq, key_lanes=lanes_)
        return n_dead + n_cold

    def evict_keys(self, lanes_ts: Sequence[tuple]
                   ) -> Tuple[int, int]:
        """Targeted cold-tier eviction (state/tier.py sweep callback):
        every row of each given key leaves the arena + device together
        — a probe must see all or none — but stays durable in the
        state table. Returns (keys evicted, rows evicted): the tier's
        counters are in KEYS; the join_rows_evicted metric wants rows.
        Caller (the tier, at this executor's own checkpoint barrier)
        guarantees no in-flight probes."""
        want = set(lanes_ts)
        if not want or not self.pk_to_ref:
            return 0, 0
        pks = list(self.pk_to_ref.keys())
        refs = np.fromiter(self.pk_to_ref.values(), dtype=np.int64,
                           count=len(pks))
        key_cols = [(self.arena.cols[i][refs],
                     self.arena.valid[i][refs])
                    for i in self.key_indices]
        lane_rows = list(map(tuple,
                             self.key_codec.build_arrays(key_cols)
                             .tolist()))
        evicted = 0
        vt_by_lane: Dict[tuple, tuple] = {}
        for j, lt in enumerate(lane_rows):
            if lt not in want:
                continue
            if lt not in vt_by_lane:
                vt = tuple(
                    None if not ok[j] else
                    (v[j].item() if hasattr(v[j], "item") else v[j])
                    for v, ok in key_cols)
                if any(x is None for x in vt):
                    continue       # null-key rows are never stored
                vt_by_lane[lt] = vt
            ref = self.pk_to_ref.pop(pks[j])
            self.free.append(ref)
            evicted += 1
        self.cold_keys.update(vt_by_lane)
        if evicted:
            # compaction rebuilds arena + device from the survivors —
            # evicted rows leave the kernel wholesale (degree state of
            # evicted rows drops too: a degree is a pure function of
            # both sides' durable state, recomputed on reload)
            self.compact()
        return len(vt_by_lane), evicted

    def reload_keys(self, need: Dict[tuple, tuple]) -> tuple:
        """Reload evicted keys' rows from the state table (arena +
        pk_to_ref + a batched device insert at seq 0, visible to every
        probe). Returns (lanes, aux, n, max_ref) for the device apply,
        or None when nothing reloaded."""
        from risingwave_tpu.ops.hash_join import FLAG_INS

        rows: List[tuple] = []
        lanes_rows: List[tuple] = []
        for lanes_t, values_t in need.items():
            if lanes_t not in self.cold_keys:
                continue
            del self.cold_keys[lanes_t]
            for _pk, row in self.table.iter_prefix(list(values_t)):
                row = tuple(row)
                if tuple(row[i] for i in self.pk_indices) \
                        in self.pk_to_ref:
                    # a row inserted AFTER the key went cold is already
                    # resident — re-adding it would double its matches
                    continue
                rows.append(row)
                lanes_rows.append(lanes_t)
        if not rows:
            return None
        n = len(rows)
        refs = self.alloc_refs(n)
        self.arena.ensure(int(refs.max()))
        for i, f in enumerate(self.schema):
            col_vals = [r[i] for r in rows]
            if f.data_type.is_device:
                ok = np.asarray([v is not None for v in col_vals])
                vals = np.asarray(
                    [0 if v is None else v for v in col_vals],
                    dtype=f.data_type.np_dtype)
                self.arena.cols[i][refs] = vals
                self.arena.valid[i][refs] = ok
            else:
                self.arena.cols[i][refs] = np.asarray(col_vals,
                                                      dtype=object)
                self.arena.valid[i][refs] = True
        self.ensure_degrees(int(refs.max()))
        for row, ref in zip(rows, refs.tolist()):
            self.pk_to_ref[tuple(row[i] for i in self.pk_indices)] = ref
        cap = next_pow2(n)
        w = LANES_PER_KEY * len(self.key_indices)
        up = np.zeros((cap, w + 3 * len(self.pay_indices)),
                      dtype=np.int32)
        up[:n, :w] = np.asarray(lanes_rows, dtype=np.int32)
        if self.pay_indices:
            # reloaded rows' payload lanes rebuild from the arena copy
            # just stored above — the same scatter shape as a live
            # insert
            up[:n, w:] = self.payload_from_arena(refs)
        aux = np.zeros((cap, 4), dtype=np.int32)
        aux[:n, 0] = refs
        aux[:n, 2] = FLAG_INS
        # seq 0: reloaded rows predate every live sequence, so every
        # probe of this epoch sees them
        return up, aux, n, int(refs.max())

    def recover(self) -> None:
        keys_l, refs_l = [], []
        rows: List[tuple] = []
        for _pk, row in self.table.iter_rows():
            rows.append(row)
        if not rows:
            return
        n = len(rows)
        refs = self.alloc_refs(n)
        self.arena.ensure(int(refs.max()))
        for i, f in enumerate(self.schema):
            col_vals = [r[i] for r in rows]
            if f.data_type.is_device:
                ok = np.asarray([v is not None for v in col_vals])
                vals = np.asarray(
                    [0 if v is None else v for v in col_vals],
                    dtype=f.data_type.np_dtype)
                self.arena.cols[i][refs] = vals
                self.arena.valid[i][refs] = ok
            else:
                self.arena.cols[i][refs] = np.asarray(col_vals,
                                                      dtype=object)
        for row, ref in zip(rows, refs.tolist()):
            pk = tuple(row[i] for i in self.pk_indices)
            self.pk_to_ref[pk] = ref
            keys_l.append(self.key_codec.lanes_of_values(
                [row[i] for i in self.key_indices]))
        # rows with NULL join keys were never stored on device
        keep = [j for j, row in enumerate(rows)
                if all(row[i] is not None for i in self.key_indices)]
        if keep:
            # device payload lanes rebuild exactly where the chains
            # rebuild — from the recovered arena rows (the sharded
            # kernel has no payload store; don't pass the kwarg)
            kw = {"payload": self.payload_from_arena(refs[keep])} \
                if self.pay_indices else {}
            self.kernel.rebuild(np.stack([keys_l[j] for j in keep]),
                                refs[keep], **kw)


class HashJoinExecutor(Executor):
    """Streaming inner equi-join (hash_join.rs:227, device matcher)."""

    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 left_table: StateTable, right_table: StateTable,
                 actor_id: int = 0,
                 output_names: Optional[Sequence[str]] = None,
                 join_type: JoinType = JoinType.INNER,
                 mesh=None, shard_opts: Optional[dict] = None,
                 state_cap: Optional[int] = None,
                 device_payload: bool = True,
                 epoch_batch: Optional[bool] = None):
        assert len(left_keys) == len(right_keys)
        self.left_in, self.right_in = left, right
        self.join_type = join_type
        # rebuild recipe for plan rewrites (frontend/opt): the
        # column-pruning rule reconstructs the join over narrowed
        # inputs and must reproduce this exact configuration
        self.rebuild_opts = {"actor_id": actor_id, "mesh": mesh,
                             "shard_opts": shard_opts,
                             "state_cap": state_cap,
                             "device_payload": device_payload,
                             "epoch_batch": epoch_batch}
        key_codec = KeyCodec(
            [left.schema[i].data_type for i in left_keys])
        # device_payload=False forces the host-gather emit path (the
        # bit-identity oracle's off arm; also exposed for debugging)
        self.sides = (
            _JoinSide(left.schema, left_keys, left_table.pk_indices,
                      left_table, key_codec, mesh=mesh,
                      shard_opts=shard_opts,
                      device_payload=device_payload),
            _JoinSide(right.schema, right_keys, right_table.pk_indices,
                      right_table, key_codec, mesh=mesh,
                      shard_opts=shard_opts,
                      device_payload=device_payload),
        )
        for i, side in enumerate(self.sides):
            side.track_degrees = i in join_type.tracked_sides
        n_left = len(left.schema)
        names = list(output_names) if output_names else None
        subj = join_type.subject
        if subj is not None:
            # semi/anti: output is the subject side's schema alone
            src = (left if subj == 0 else right).schema
            fields = [Field(names[i] if names else f.name, f.data_type)
                      for i, f in enumerate(src)]
            pk = list((left_table if subj == 0
                       else right_table).pk_indices)
        else:
            fields = []
            k = 0
            for sch in (left.schema, right.schema):
                for f in sch:
                    fields.append(Field(names[k] if names else f.name,
                                        f.data_type))
                    k += 1
            # output pk: both sides' pks (joined row identity)
            pk = list(left_table.pk_indices) + \
                [n_left + i for i in right_table.pk_indices]
        out_schema = Schema(fields)
        super().__init__(ExecutorInfo(
            out_schema, pk,
            f"HashJoinExecutor({join_type.value}, actor={actor_id})"))
        self.n_left = n_left
        # join-key watermarks (hash_join.rs:860-945): per side, latest
        # watermark per key POSITION; the forwarded/cleaning watermark
        # is the min across sides, monotone
        self._side_wm: List[Dict[int, int]] = [{}, {}]
        self._combined_wm: Dict[int, int] = {}
        self._expired_wm: Dict[int, int] = {}
        # message sequence (sequence-versioned device state; see
        # ops/hash_join.py) + per-epoch in-flight probe list
        self._seq = 1
        self._pending: List[tuple] = []
        # epoch batching (ISSUE 10: now BOTH kernel shapes): chunks
        # buffer host-side and the whole epoch ships as 2 uploads + 2
        # dispatches per side at the barrier — through the tunnel (and
        # through the sharded path's ~100ms-per-shard_map host
        # dispatch, BENCH_r09), per-barrier dispatch count bounds
        # throughput (ops/hash_join.py AUX_*; parallel/join.py epoch
        # twins). epoch_batch=False is the sharded oracle's per-chunk
        # off arm — single-chip kernels dropped that path in PR 9
        # (device degrees live in the epoch dispatches).
        # derived WITHOUT touching .kernel: the lazy property exists so
        # plan-only processes never build device state
        if epoch_batch is None:
            epoch_batch = True
        elif not epoch_batch and mesh is None:
            raise ValueError(
                "epoch_batch=False is the sharded per-chunk oracle "
                "arm — the single-chip kernel is epoch-only")
        self._epoch_batch = bool(epoch_batch)
        self._tier = None
        self._tier_parts: Tuple = (None, None)
        self._tier_seq = 0
        if state_cap is not None:
            # cold-state tier prerequisites: epoch-batched single-chip
            # path (reload hooks the epoch dispatch), a non-semi/anti
            # join (semi/anti emission depends on degree TRANSITIONS
            # whose history an eviction would lose; outer degrees are
            # pure functions of both sides' durable state and recompute
            # on reload — see _reload_cold), and key-prefixed
            # state-table pks (reload prefix-scans by key)
            if join_type.is_semi_or_anti or mesh is not None:
                raise ValueError(
                    "state_cap needs an INNER or OUTER join on the "
                    "single-chip epoch-batched path (semi/anti "
                    "degree-transition history cannot be evicted)")
            for side in self.sides:
                k = len(side.key_indices)
                if side.table.pk_indices[:k] != side.key_indices:
                    raise ValueError(
                        "state_cap needs state-table pks prefixed by "
                        "the join keys (reload prefix-scans by key): "
                        f"pk={side.table.pk_indices} "
                        f"keys={side.key_indices}")
                side.state_cap = int(state_cap)
            # tier participation (state/tier.py): one participant per
            # side; the sweep at this executor's checkpoint barrier
            # picks the least-recently-touched keys. Registration is
            # DEFERRED to execute() — plan-only executors (EXPLAIN,
            # distributed CREATEs that serialize to IR and discard)
            # must leave no ghost entries in the global registry.
            from risingwave_tpu.state import tier as _tier_mod
            self._tier = _tier_mod.GLOBAL
            self._tier_cap = int(state_cap)
        self._epoch_buf: tuple = ([], [])
        self._epoch_rows = [0, 0]
        # host-state accounting (memory_manager.rs analog): weakref so
        # a dropped executor unregisters itself on the next tick
        import weakref

        from risingwave_tpu.utils import memory as _mem
        name = f"{self.identity}#{id(self)}"
        ref = weakref.ref(self)

        def _nbytes() -> int:
            s = ref()
            if s is None:
                _mem.GLOBAL.unregister(name)
                return 0
            return sum(sd.nbytes() for sd in s.sides) + \
                s.sides[0].key_codec.interner_nbytes()

        _mem.GLOBAL.register(name, _nbytes)

    # -- emission ---------------------------------------------------------
    @staticmethod
    def _chunk_cols(schema: Schema, chunk: StreamChunk,
                    idx: np.ndarray, cap: int) -> List[Column]:
        """Columns gathered from incoming-chunk rows `idx`."""
        t = len(idx)
        out: List[Column] = []
        for f, c in zip(schema, chunk.columns):
            src = np.asarray(c.values)[idx]
            vals = np.zeros(cap, dtype=src.dtype) if src.dtype != object \
                else np.empty(cap, dtype=object)
            vals[:t] = src
            ok = np.ones(cap, dtype=bool)
            if c.validity is not None:
                ok[:t] = np.asarray(c.validity)[idx]
            out.append(Column(f.data_type, vals,
                              None if ok.all() else ok))
        return out

    @staticmethod
    def _null_cols(schema: Schema, cap: int) -> List[Column]:
        out: List[Column] = []
        for f in schema:
            dt = f.data_type
            vals = np.zeros(cap, dtype=dt.np_dtype) if dt.is_device \
                else np.empty(cap, dtype=object)
            out.append(Column(dt, vals, np.zeros(cap, dtype=bool)))
        return out

    def _compose(self, side_idx: int, my_cols: List[Column],
                 other_cols: List[Column], ops: np.ndarray,
                 t: int, cap: int) -> StreamChunk:
        columns = my_cols + other_cols if side_idx == 0 \
            else other_cols + my_cols
        out_vis = np.zeros(cap, dtype=bool)
        out_vis[:t] = True
        full_ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
        full_ops[:t] = ops[:t]
        return StreamChunk(self.schema, columns, out_vis, full_ops)

    @staticmethod
    def _ops_of(chunk: StreamChunk, idx: np.ndarray) -> np.ndarray:
        """Degrade update pairs (split halves) to Delete/Insert — the
        reference degrades split pairs the same way."""
        in_ops = np.asarray(chunk.ops)[idx]
        is_ins = (in_ops == int(Op.INSERT)) | \
            (in_ops == int(Op.UPDATE_INSERT))
        return np.where(is_ins, int(Op.INSERT),
                        int(Op.DELETE)).astype(np.int8)

    # -- fragment fusion (frontend/opt/fusion.py mutates a copy) ----------
    def drain_stage_metrics(self):
        """Per-logical-stage attribution of the fused input runs for
        the monitor (side-tagged — both sides may absorb a same-kind
        stage)."""
        out = []
        for tag, side in (("L", self.sides[0]), ("R", self.sides[1])):
            if side.fused_input is not None:
                out.extend(
                    (f"{tag}:{ident}", rows, chunks)
                    for ident, rows, chunks
                    in side.fused_input.drain_stage_metrics())
        return out

    def adopt_fused_input(self, side_idx: int, fs, base) -> None:
        """Absorb a filter/project/row_id_gen run on one input side:
        ``base`` becomes the direct input and ``fs`` (whose out_schema
        must equal the side schema this join was planned against)
        runs as a numpy composed pass for host bookkeeping plus a
        traced prelude inside the side's epoch dispatches. Only valid
        on the single-chip epoch path before any data flows."""
        from risingwave_tpu.frontend.opt.fusion import (
            join_side_fusable_reason,
        )
        r = join_side_fusable_reason(self, side_idx)
        if r is not None:
            raise ValueError(f"join side is not fusion-eligible: {r}")
        side = self.sides[side_idx]
        got = [f.data_type for f in fs.out_schema]
        want = [f.data_type for f in side.schema]
        if got != want:
            raise ValueError(
                f"fused input run emits {got}, join side planned on "
                f"{want}")
        side.fused_input = fs
        if side_idx == 0:
            self.left_in = base
        else:
            self.right_in = base

    def _run_fused_input(self, side_idx: int, chunk: StreamChunk):
        """The host half of a fused input side: augment (runtime
        columns), run the composed chain ONCE on numpy (the same
        implementation the device prelude traces — no drifting twin),
        reattach host passthrough columns, and encode the raw matrix
        the epoch dispatches consume. Returns (post_chunk, raw) or
        None when every row filtered out (empty-suppression
        contract)."""
        from risingwave_tpu.ops.fused import encode_raw_chunk
        fs = self.sides[side_idx].fused_input
        aug = fs.augment(chunk)
        host_same = fs.host_noop_eq(aug)
        out_cols, vis2, ops2, stage_rows = fs.chain_body(
            list(aug.columns), np.asarray(aug.visibility),
            np.asarray(aug.ops), np, host_same=host_same)
        fs.note_stage_rows(np.asarray(stage_rows), 1)
        if not vis2.any():
            return None
        cols: List[Column] = []
        for j, f in enumerate(fs.out_schema):
            host_src = fs.host_out.get(j)
            if host_src is not None:
                src = aug.columns[host_src]
                cols.append(Column(f.data_type, src.values,
                                   src.validity))
            else:
                cols.append(out_cols[j])
        post = StreamChunk(fs.out_schema, cols, vis2, ops2)
        return post, encode_raw_chunk(aug, fs.ref_cols)

    def _pairs_chunk(self, side_idx: int, chunk: StreamChunk,
                     probe_idx: np.ndarray, refs: np.ndarray,
                     pay: Optional[np.ndarray] = None) -> StreamChunk:
        t = len(probe_idx)
        cap = next_pow2(t)
        me = self.sides[side_idx]
        other = self.sides[1 - side_idx]
        # matched stored rows: device columns decode from the payload
        # lanes the probe's emit walk gathered ON DEVICE (one packed
        # fetch); only varchar/host columns still gather from the
        # arena by ref. pay is None on the sharded per-chunk path and
        # with device_payload off — full arena gather as before.
        if pay is not None and other.pay_indices:
            other_cols = other.cols_from_payload(pay, refs, cap)
        else:
            other_cols = other.arena.gather(refs, cap)
        return self._compose(
            side_idx, self._chunk_cols(me.schema, chunk, probe_idx, cap),
            other_cols,
            self._ops_of(chunk, probe_idx), t, cap)

    def _padded_from_chunk(self, side_idx: int, chunk: StreamChunk,
                           idx: np.ndarray) -> StreamChunk:
        """(row, NULLs) for unmatched rows of an outer incoming side."""
        t = len(idx)
        cap = next_pow2(t)
        me = self.sides[side_idx]
        other = self.sides[1 - side_idx]
        return self._compose(
            side_idx, self._chunk_cols(me.schema, chunk, idx, cap),
            self._null_cols(other.schema, cap),
            self._ops_of(chunk, idx), t, cap)

    def _padded_from_arena(self, side_idx: int, refs: np.ndarray,
                           op: Op) -> StreamChunk:
        """(stored row, NULLs) for degree transitions of an outer side."""
        t = len(refs)
        cap = next_pow2(t)
        me = self.sides[side_idx]
        other = self.sides[1 - side_idx]
        ops = np.full(cap, int(op), dtype=np.int8)
        return self._compose(
            side_idx, me.arena.gather(refs, cap),
            self._null_cols(other.schema, cap), ops, t, cap)

    def _subject_from_chunk(self, chunk: StreamChunk,
                            idx: np.ndarray) -> StreamChunk:
        t = len(idx)
        cap = next_pow2(t)
        cols = self._chunk_cols(
            self.sides[self.join_type.subject].schema, chunk, idx, cap)
        vis = np.zeros(cap, dtype=bool)
        vis[:t] = True
        ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
        ops[:t] = self._ops_of(chunk, idx)
        return StreamChunk(self.schema, cols, vis, ops)

    def _subject_from_arena(self, refs: np.ndarray, op: Op
                            ) -> StreamChunk:
        subj = self.join_type.subject
        t = len(refs)
        cap = next_pow2(t)
        cols = self.sides[subj].arena.gather(refs, cap)
        vis = np.zeros(cap, dtype=bool)
        vis[:t] = True
        ops = np.full(cap, int(op), dtype=np.int8)
        return StreamChunk(self.schema, cols, vis, ops)

    def _ingest_chunk(self, side_idx: int, chunk: StreamChunk,
                      key_lanes, nonnull: np.ndarray,
                      raw: Optional[np.ndarray] = None) -> None:
        """Ingest side: host bookkeeping per chunk; device work either
        dispatches per chunk (sharded kernel) or buffers for the ONE
        epoch dispatch at the barrier (single-chip; sequence versioning
        makes the batched probes exact per-row)."""
        me = self.sides[side_idx]
        other = self.sides[1 - side_idx]
        seq = self._seq
        self._seq += 1
        probe_vis = np.asarray(chunk.visibility) & nonnull
        if _hotkeys.ENABLED:
            # heavy-hitter sketch per join input ("/0" build, "/1"
            # probe): unfused sides already built the lanes for the
            # kernel — the sketch adds one hash+unique pass; a fused
            # input side derives lanes in-kernel, so the sketch builds
            # its own host copy from the post-filter chunk
            sk_lanes = key_lanes if key_lanes is not None \
                else me.key_codec.build(chunk, me.key_indices)
            _hotkeys.HOTKEYS.observe(f"{self.identity}/{side_idx}",
                                     sk_lanes, probe_vis,
                                     me.key_codec)
        if self._tier is not None and key_lanes is not None:
            rows = np.flatnonzero(probe_vis)
            if len(rows):
                uniq = list(map(tuple, np.unique(
                    np.asarray(key_lanes)[rows], axis=0).tolist()))
                # stored here → full touch; the probe only REFRESHES
                # the other side's recency (insert=False: a probed key
                # the other side never stored must not mint a phantom)
                self._tier.touch(self._tier_parts[side_idx], uniq,
                                 self._tier_seq)
                self._tier.touch(self._tier_parts[1 - side_idx], uniq,
                                 self._tier_seq, insert=False)
        (ins_idx, ins_refs, full_refs, ins_mask, del_refs,
         del_mask) = me.apply_chunk_host(chunk, nonnull)
        if not self._epoch_batch:
            # ins/del entries only exist at storable (= probe-visible)
            # rows, so one mask decides both dispatch and collect.
            # key_lanes stay HOST arrays end-to-end: the kernels upload
            # them once; a jnp round-trip here would block on the tunnel.
            handle = None
            if probe_vis.any():
                # one fused apply+probe = one device dispatch; the
                # sharded kernel counts it at its own jit site under
                # kernel="sharded_join" (real-launch granularity)
                with dispatch_span(self.identity,
                                   float(probe_vis.sum()),
                                   site="apply_and_probe"):
                    handle = me.kernel.apply_and_probe(
                        other.kernel, key_lanes, probe_vis,
                        full_refs, ins_mask, del_refs, del_mask, seq)
            self._pending.append(
                (side_idx, chunk, nonnull, handle, ins_idx, ins_refs,
                 0, chunk.capacity))
            return
        from risingwave_tpu.ops.hash_join import (
            FLAG_DEL, FLAG_INS, FLAG_NEG, FLAG_PROBE,
        )
        n = chunk.capacity
        # dense-prefix slice (ISSUE 12): compacted chunks stamp their
        # visible-row count — buffering only the dense prefix keeps
        # chunk PADDING out of the epoch's concatenated row space (a
        # 62%-full hop-expanded chunk was inflating every routed epoch
        # shape by ~1.6×); rows past the prefix are invisible and
        # contribute nothing but routed zeros
        dn = chunk.dense_rows if chunk.dense_rows is not None else n
        ops = np.asarray(chunk.ops)
        neg = (ops != int(Op.INSERT)) & (ops != int(Op.UPDATE_INSERT))
        aux = np.zeros((dn, 4), dtype=np.int32)
        aux[:, 0] = full_refs[:dn]
        aux[:, 1] = del_refs[:dn]
        aux[:, 2] = (probe_vis[:dn] * FLAG_PROBE
                     + ins_mask[:dn] * FLAG_INS
                     + del_mask[:dn] * FLAG_DEL + neg[:dn] * FLAG_NEG)
        aux[:, 3] = seq
        off = self._epoch_rows[side_idx]
        self._pending.append(
            (side_idx, chunk, nonnull, None, ins_idx, ins_refs, off,
             dn))
        if raw is not None:
            # fused input side: the RAW int64 matrix is the upload —
            # the side's prelude rebuilds [key | payload] lanes inside
            # the epoch dispatches
            up = raw[:dn]
        elif me.pay_indices:
            # [key lanes | payload lanes]: ONE upload matrix per side
            # per epoch carries both — the apply scatter writes the
            # payload rows where it links the chains
            up = np.concatenate(
                [np.asarray(key_lanes)[:dn],
                 me.payload_rows(chunk)[:dn]], axis=1)
        else:
            up = np.asarray(key_lanes)[:dn]
        owners = None
        if me._mesh is not None:
            # per-row owner shards for the skew-exact routing bucket
            # (parallel/join.stage_epoch): the fused path derives key
            # lanes from the POST chunk here — the raw matrix only
            # carries them in-trace
            lanes_o = np.asarray(key_lanes) if key_lanes is not None \
                else me.key_codec.build(chunk, me.key_indices)
            owners = me.kernel.owners_of(lanes_o[:dn])
        self._epoch_buf[side_idx].append(
            (up, aux, int(ins_refs.max()) if len(ins_refs) else -1,
             owners))
        self._epoch_rows[side_idx] = off + dn

    def _dispatch_epoch(self) -> Dict[int, tuple]:
        """Ship each side's buffered epoch as 2 uploads + 1 apply + 1
        probe dispatch, then collect both probes (overlapped DMAs).
        Returns {side: (deg|None, probe_idx, refs, pay, old_deg)} in
        the CONCATENATED row space; _emit_pending slices per chunk by
        offset."""
        self._reload_cold()
        devs: Dict[int, tuple] = {}
        for s in (0, 1):
            buf = self._epoch_buf[s]
            if not buf:
                continue
            total = self._epoch_rows[s]
            cap = next_pow2(total)
            w = buf[0][0].shape[1]
            # fused input sides buffer int64 RAW matrices; direct
            # sides buffer int32 [key | payload] lanes
            up = np.zeros((cap, w), dtype=buf[0][0].dtype)
            aux = np.zeros((cap, 4), dtype=np.int32)
            owners = None if buf[0][3] is None else \
                np.zeros(cap, dtype=np.int64)
            at = 0
            max_ref = -1
            for lan, a, mr, ow in buf:
                up[at:at + lan.shape[0]] = lan
                aux[at:at + a.shape[0]] = a
                if owners is not None:
                    owners[at:at + lan.shape[0]] = ow
                at += lan.shape[0]
                max_ref = max(max_ref, mr)
            # staging is the kernel's job: the sharded kernel pads to
            # the mesh width, runs its growth guards, computes the
            # skew-exact routing bucket and row-shards the upload; a
            # single chip device_puts (bucket None)
            up_dev, aux_dev, bucket = self.sides[s].kernel.stage_epoch(
                up, aux, total, max_ref, owners=owners)
            devs[s] = (up_dev, aux_dev, total, max_ref, bucket)

        def _prelude_kw(s: int) -> dict:
            """The UPLOADING side's fused-input prelude (if any),
            for both its apply and its probe of the other side. The
            key is STRUCTURAL (FusedStages.trace_key + the lane
            positions): equal runs trace equal programs, so jit caches
            keyed by it survive session restarts and shared shapes."""
            side = self.sides[s]
            if side.fused_input is None:
                return {}
            if side._prelude_cache_key is None:
                side._prelude_cache_key = (
                    f"{side.fused_input.trace_key()}"
                    f"|k={side.key_indices}|p={side.pay_indices}")
            return {"prelude": side.prelude,
                    "prelude_key": side._prelude_cache_key}

        # both applies land before either probe dispatches: a probe at
        # seq s must see the other side's same-epoch rows with seq < s
        for s, (ld, ad, total, max_ref, bkt) in devs.items():
            # apply + probe below = 2 device dispatches per side/epoch,
            # each carrying the epoch's rows (observe twice so the
            # histogram's count matches the dispatch counter and
            # sum/count stays the true per-dispatch density). Sharded
            # kernels count at their own jit sites (kernel="sharded_
            # join") — counting here too would double the totals.
            if self.sides[s]._mesh is None:
                _METRICS.device_dispatch.inc(2, executor=self.identity)
                for _ in range(2):
                    _METRICS.rows_per_dispatch.observe(
                        float(total), executor=self.identity)
            with dispatch_span(self.identity, float(total),
                               site="epoch_apply", side=s):
                self.sides[s].kernel.apply_epoch(ld, ad, total,
                                                 max_ref, bucket=bkt,
                                                 **_prelude_kw(s))
        with_deg = self.join_type != JoinType.INNER
        if not with_deg:
            # inner (the hot path): both probes dispatch before either
            # collects, so the two d2h DMAs overlap
            probes = {s: self.sides[1 - s].kernel.probe_epoch(
                ld, ad, False, sink=self.sides[s].kernel,
                bucket=bkt, **_prelude_kw(s))
                for s, (ld, ad, _t, _m, bkt) in devs.items()}
            return {s: p.collect() for s, p in probes.items()}
        # degree-tracked joins: each probe updates BOTH sides' device
        # degree arrays (transitions on the probed side, inserted-row
        # inits on the probing side), and a pair-buffer overflow
        # truncates the first dispatch's adds — so probe 2 must only
        # dispatch after probe 1's collect has installed its final
        # arrays. One sync point per epoch, tracked joins only.
        out: Dict[int, tuple] = {}
        for s, (ld, ad, _t, _m, bkt) in devs.items():
            out[s] = self.sides[1 - s].kernel.probe_epoch(
                ld, ad, True, sink=self.sides[s].kernel,
                bucket=bkt, **_prelude_kw(s)).collect()
        return out

    def _tier_register(self) -> None:
        """Register both sides with the global tier at execute() start
        — only executors that actually RUN appear in the registry."""
        import weakref
        sref = weakref.ref(self)
        parts = []
        for i in (0, 1):
            def _evict_cb(keys, _i=i):
                s = sref()
                if s is None:
                    return 0
                n_keys, n_rows = s.sides[_i].evict_keys(keys)
                if n_rows:
                    _METRICS.join_rows_evicted.inc(
                        n_rows, executor=s.identity)
                return n_keys

            def _nbytes_cb(_i=i):
                s = sref()
                return 0 if s is None else s.sides[_i].nbytes()

            parts.append(self._tier.register(
                f"{self.identity}/side{i}#{id(self)}", _evict_cb,
                cap=self._tier_cap, nbytes=_nbytes_cb))
        self._tier_parts = tuple(parts)

    def _reload_cold(self) -> None:
        """Reload evicted keys this epoch's probes will need, BEFORE
        the epoch's applies/probes dispatch (managed_state/join reload-
        on-miss, batched per barrier). The reload insert applies at
        seq 0 so every probe of the epoch sees the reloaded rows.

        Tracked (outer) joins reload a needed key on BOTH sides: the
        reloaded rows' degrees recompute by probing the opposite
        kernel, and a cold twin there would undercount. The recompute
        runs after both sides' reload applies, against pre-epoch state
        — this epoch's own chunks then layer their degree deltas on
        top in message order (_emit_one step 3), exactly as if the
        rows had never left."""
        from risingwave_tpu.ops.hash_join import FLAG_PROBE
        kw = LANES_PER_KEY * len(self.sides[0].key_indices)
        need: List[Dict[tuple, tuple]] = [{}, {}]
        for s in (0, 1):
            other = self.sides[1 - s]
            if not other.cold_keys or not self._epoch_buf[s]:
                continue
            for lan, aux, _mr, _ow in self._epoch_buf[s]:
                rows = np.flatnonzero(aux[:, 2] & FLAG_PROBE)
                # the buffered upload matrix is [key lanes | payload
                # lanes]: cold-key lookups read the key slice only
                for t in map(tuple, lan[rows, :kw].tolist()):
                    v = other.cold_keys.get(t)
                    if v is not None:
                        need[1 - s][t] = v
        if self.join_type.tracked_sides:
            for s in (0, 1):
                twin = self.sides[s]
                if not twin.cold_keys:
                    continue
                for t in need[1 - s]:
                    v = twin.cold_keys.get(t)
                    if v is not None:
                        need[s][t] = v
        reloaded: List[Optional[tuple]] = [None, None]
        for s in (0, 1):
            if not need[s]:
                continue
            loaded = self.sides[s].reload_keys(need[s])
            if loaded is not None:
                up, aux2, n, max_ref = loaded
                from risingwave_tpu.utils import jaxtools as _jt
                self.sides[s].kernel.apply_epoch(
                    _jt.upload(up, kernel="hash_join"),
                    _jt.upload(aux2, kernel="hash_join"), n,
                    max_ref)
                reloaded[s] = (up, aux2, n)
                if self._tier is not None:
                    part = self._tier_parts[s]
                    uniq = np.unique(up[:n, :kw], axis=0)
                    self._tier.touch(part,
                                     map(tuple, uniq.tolist()),
                                     self._tier_seq)
                    # units contract: reload counters are in KEYS
                    self._tier.note_reload(part, len(uniq))
        for t_side in self.join_type.tracked_sides:
            rl = reloaded[t_side]
            if rl is None:
                continue
            up, aux2, n = rl
            refs = aux2[:n, 0].astype(np.int64)
            deg, _pi, _refs = self.sides[1 - t_side].kernel.probe(
                up[:n, :kw], np.ones(n, dtype=bool))
            side = self.sides[t_side]
            if side.dev_degrees:
                # reloaded rows' degrees recompute by one batch probe
                # and scatter straight into the device degree array
                side.kernel.write_degrees(
                    refs.astype(np.int32), deg[:n])
            else:
                side.ensure_degrees(int(refs.max()))
                side.degrees[refs] = deg[:n]

    def _emit_pending(self) -> List[StreamChunk]:
        """Barrier sweep: collect the epoch's probes and run emission
        in message order. Degree bookkeeping happens here, in the same
        order the chunks were applied — on the epoch path it replays
        from the packed matrix's old-degree column (the device array
        is the store; see _emit_one)."""
        outs: List[StreamChunk] = []
        results = self._dispatch_epoch() if self._epoch_batch \
            and (self._epoch_buf[0] or self._epoch_buf[1]) else {}
        # per-epoch replay of stored-row degrees, per side: a value
        # array + written mask indexed by ref (ISSUE 12 — the dict it
        # replaces cost a python get/set per matched pair), seeded
        # lazily from the matrix old column, written through by
        # inserted-row inits and per-chunk transition deltas
        self._deg_replay = [None, None]
        for (side_idx, chunk, nonnull, handle, ins_idx,
             ins_refs, off, dn) in self._pending:
            n = chunk.capacity
            deg = None
            probe_idx = np.zeros(0, dtype=np.int32)
            refs = np.zeros(0, dtype=np.int32)
            pay = None
            old = None
            if handle is not None:
                deg_p, probe_idx, refs = handle.collect()
                deg = np.zeros(n, dtype=np.int64)
                deg[:len(deg_p)] = deg_p
            elif side_idx in results:
                d_s, p_s, r_s, pay_s, old_s = results[side_idx]
                # the buffered epoch carries only this chunk's dense
                # prefix (dn rows at offset off); degrees re-pad to
                # the chunk's capacity for the chunk-relative masks
                lo = np.searchsorted(p_s, off)
                hi = np.searchsorted(p_s, off + dn)
                probe_idx = (p_s[lo:hi] - off).astype(np.int32)
                refs = r_s[lo:hi]
                if pay_s is not None:
                    pay = pay_s[lo:hi]
                if old_s is not None:
                    old = old_s[lo:hi].astype(np.int64)
                if d_s is not None:
                    deg = np.zeros(n, dtype=np.int64)
                    deg[:dn] = d_s[off:off + dn]
            outs.extend(self._emit_one(side_idx, chunk, nonnull, deg,
                                       probe_idx, refs, ins_idx,
                                       ins_refs, pay, old))
        self._pending.clear()
        self._epoch_buf = ([], [])
        self._epoch_rows = [0, 0]
        self._deg_replay = [None, None]
        return outs

    def _deg_replay_arrays(self, side_idx: int, max_ref: int):
        """(values, written) replay arrays for `side_idx`, grown to
        cover `max_ref` — the vectorized stand-in for the old
        (side, ref)→degree dict."""
        pair = self._deg_replay[side_idx]
        need = max_ref + 1
        if pair is None:
            cap = max(next_pow2(need), 1024)
            pair = (np.zeros(cap, dtype=np.int64),
                    np.zeros(cap, dtype=bool))
            self._deg_replay[side_idx] = pair
        elif len(pair[0]) < need:
            cap = next_pow2(need)
            vals = np.zeros(cap, dtype=np.int64)
            wr = np.zeros(cap, dtype=bool)
            vals[:len(pair[0])] = pair[0]
            wr[:len(pair[1])] = pair[1]
            pair = (vals, wr)
            self._deg_replay[side_idx] = pair
        return pair

    def _emit_one(self, side_idx: int, chunk: StreamChunk,
                  nonnull: np.ndarray, deg: Optional[np.ndarray],
                  probe_idx: np.ndarray, refs: np.ndarray,
                  ins_idx: np.ndarray, ins_refs: np.ndarray,
                  pay: Optional[np.ndarray] = None,
                  old: Optional[np.ndarray] = None
                  ) -> List[StreamChunk]:
        """Emission per eq_join_oneside (hash_join.rs:990) generalized
        to the degree-transition rule: a stored outer row flips its
        NULL-padded emission exactly when its match degree crosses zero
        (net per-chunk delta vs the old degree — intermediate flips
        within one chunk cancel, leaving the same multiset).

        `deg` is None exactly when the join is INNER (the slim probe
        skips degrees; no emission rule below reads them). On the
        epoch path `pay` carries the matched refs' device-gathered
        payload lanes and `old` their pre-epoch degrees — the replay
        dict in _emit_pending reconstructs each chunk's old/new
        exactly as the host degrees array used to."""
        jt = self.join_type
        me = self.sides[side_idx]
        other = self.sides[1 - side_idx]
        vis = np.asarray(chunk.visibility)
        n = chunk.capacity
        if deg is None and jt != JoinType.INNER:
            deg = np.zeros(n, dtype=np.int64)
        outs: List[StreamChunk] = []
        # 1) matched pairs (all types except semi/anti)
        if jt.subject is None and len(probe_idx):
            outs.append(self._pairs_chunk(side_idx, chunk, probe_idx,
                                          refs, pay))
        # 2) incoming-row direct emissions
        if jt.outer_on(side_idx):
            # NULL-key rows of an outer side always emit padded
            unmatched = np.flatnonzero(vis & ((deg == 0) | ~nonnull))
            if len(unmatched):
                outs.append(self._padded_from_chunk(side_idx, chunk,
                                                    unmatched))
        elif jt.subject == side_idx:
            if jt.is_anti:
                sel = np.flatnonzero(vis & ((deg == 0) | ~nonnull))
            else:
                sel = np.flatnonzero(vis & nonnull & (deg > 0))
            if len(sel):
                outs.append(self._subject_from_chunk(chunk, sel))
        # 3) stored-row degree transitions on the other side
        if (1 - side_idx) in jt.tracked_sides and len(refs):
            sgn = np.where(self._ops_of(chunk, probe_idx)
                           == int(Op.INSERT), 1, -1)
            uref, inv = np.unique(refs, return_inverse=True)
            delta = np.zeros(len(uref), dtype=np.int64)
            np.add.at(delta, inv, sgn)
            if other.dev_degrees:
                # seed from the matrix's pre-epoch value on first
                # touch; later chunks read the replay arrays (exactly
                # the running value the host array used to hold) —
                # whole-column gathers/scatters, no per-pair python
                seed = np.zeros(len(uref), dtype=np.int64)
                if old is not None and len(old):
                    first = np.zeros(len(uref), dtype=np.int64)
                    # inv maps pair → uref slot; any pair of the ref
                    # carries the same old value
                    first[inv] = old
                    seed = first
                vals, wr = self._deg_replay_arrays(
                    1 - side_idx, int(uref.max()))
                cur = np.where(wr[uref], vals[uref], seed)
                new = cur + delta
                vals[uref] = new
                wr[uref] = True
                old_v = cur
            else:
                old_v = other.degrees[uref]
                new = old_v + delta
                other.degrees[uref] = new
            flip_on = uref[(old_v == 0) & (new > 0)]
            flip_off = uref[(old_v > 0) & (new == 0)]
            if jt.subject is not None:       # semi/anti subject = other
                on_op = Op.DELETE if jt.is_anti else Op.INSERT
                off_op = Op.INSERT if jt.is_anti else Op.DELETE
                if len(flip_on):
                    outs.append(self._subject_from_arena(flip_on, on_op))
                if len(flip_off):
                    outs.append(self._subject_from_arena(flip_off,
                                                         off_op))
            else:                            # outer side: padded flips
                if len(flip_on):
                    outs.append(self._padded_from_arena(
                        1 - side_idx, flip_on, Op.DELETE))
                if len(flip_off):
                    outs.append(self._padded_from_arena(
                        1 - side_idx, flip_off, Op.INSERT))
        # 4) initial degrees for the rows this chunk stored (the state
        # apply already ran at dispatch; deg is the probe-time count;
        # the device array already took the same init via the probe's
        # scatter-add — only the replay dict needs the values here)
        if side_idx in jt.tracked_sides and len(ins_idx):
            if me.dev_degrees:
                vals, wr = self._deg_replay_arrays(
                    side_idx, int(ins_refs.max()))
                vals[ins_refs] = deg[ins_idx]
                wr[ins_refs] = True
            else:
                # degrees array already grown by apply_chunk at dispatch
                me.degrees[ins_refs] = deg[ins_idx]
        return outs

    # -- watermarks -------------------------------------------------------
    def _on_watermark(self, side_idx: int, msg: "Watermark"):
        """Join-key watermarks combine as min across sides and forward
        for BOTH output columns of the key pair (they are equal by the
        join predicate); non-key watermarks are dropped (reference
        behavior). The combined watermark also drives state expiry at
        the next barrier."""
        me = self.sides[side_idx]
        if msg.col_idx not in me.key_indices:
            return
        pos = me.key_indices.index(msg.col_idx)
        self._side_wm[side_idx][pos] = msg.value
        other_wm = self._side_wm[1 - side_idx].get(pos)
        if other_wm is None:
            return
        combined = min(msg.value, other_wm)
        prev = self._combined_wm.get(pos)
        if prev is not None and combined <= prev:
            return
        self._combined_wm[pos] = combined
        subj = self.join_type.subject
        if subj is not None:
            # semi/anti output is the subject schema alone: one
            # watermark at the subject's key column index
            yield Watermark(self.sides[subj].key_indices[pos],
                            msg.data_type, combined)
        else:
            left_col = self.sides[0].key_indices[pos]
            right_col = self.n_left + self.sides[1].key_indices[pos]
            yield Watermark(left_col, msg.data_type, combined)
            yield Watermark(right_col, msg.data_type, combined)

    def _expire_state(self) -> None:
        for pos, wm in self._combined_wm.items():
            done = self._expired_wm.get(pos)
            if done is not None and wm <= done:
                continue
            dt = np.dtype(
                self.sides[0].key_types[pos].np_dtype)
            if not np.issubdtype(dt, np.integer):
                continue       # float keys: no order-safe expiry
            for side in self.sides:
                side.expire_below(pos, int(wm), seq=self._seq)
            # bump: visibility is del_seq >= probe_seq, so the NEXT
            # chunk's sequence must exceed the tombstones' del_seq
            self._seq += 1
            self._expired_wm[pos] = wm

    # interner GC gate: skip below this many entries, and skip while
    # entries ≤ 2× live refs (GC cost is O(live), so only run it when
    # at least half the entries are provably dead)
    INTERNER_GC_MIN = 4096

    def _maybe_gc_interner(self) -> None:
        """Retire interner entries no stored row references (bounded-
        by-live-state contract, VERDICT r3 weak #6). Runs at barriers,
        gated so amortized cost stays O(churn)."""
        codec = self.sides[0].key_codec
        if not codec.interners:
            return
        total = codec.interner_entries()
        # COLD keys count as live in the gate: their values are pinned
        # below, so running GC while they dominate would scan O(cold)
        # every barrier to retire almost nothing
        live_refs = sum(len(s.pk_to_ref) + len(s.cold_keys)
                        for s in self.sides)
        if total < self.INTERNER_GC_MIN or \
                total <= 2 * live_refs * len(codec.interners):
            return
        for pos, it in codec.interners.items():
            vals: List[object] = []
            for side in self.sides:
                col = side.key_indices[pos]
                if not side.pk_to_ref:
                    continue
                refs = np.fromiter(side.pk_to_ref.values(),
                                   dtype=np.int64,
                                   count=len(side.pk_to_ref))
                ok = side.arena.valid[col][refs]
                vals.extend(side.arena.cols[col][refs][ok].tolist())
            for side in self.sides:
                # COLD keys pin their interned values: retiring an id
                # a cold marker holds would dangle it (a re-intern
                # under a new id misses reload; id reuse cross-matches
                # unrelated keys). vt is ordered by key position, like
                # the codec's interners.
                for vt in side.cold_keys.values():
                    if vt[pos] is not None:
                        vals.append(vt[pos])
            it.gc(vals)

    def _recover_degrees(self) -> None:
        """Degrees are a pure function of both sides' recovered state:
        ONE batch probe of the tracked side's keys against the other
        side's matcher (instead of persisting degree tables — see
        JoinType docstring)."""
        for t in self.join_type.tracked_sides:
            side = self.sides[t]
            other = self.sides[1 - t]
            if not side.pk_to_ref:
                continue
            refs = np.fromiter(side.pk_to_ref.values(), dtype=np.int64,
                               count=len(side.pk_to_ref))
            key_cols = [(side.arena.cols[i][refs],
                         side.arena.valid[i][refs])
                        for i in side.key_indices]
            lanes_ = side.key_codec.build_arrays(key_cols)
            nonnull = np.ones(len(refs), dtype=bool)
            for _vals, ok in key_cols:
                nonnull &= ok
            deg, _pi, _refs = other.kernel.probe(lanes_, nonnull)
            if side.dev_degrees:
                side.kernel.write_degrees(
                    refs.astype(np.int32), np.where(nonnull, deg, 0))
            else:
                side.ensure_degrees(int(refs.max()))
                side.degrees[refs] = np.where(nonnull, deg, 0)
        # NOTE: host-typed arena key cols may contain None for NULL keys
        # — build_arrays handles them (interner sanitization)

    # -- main loop --------------------------------------------------------
    async def execute(self) -> AsyncIterator[Message]:
        lit = self.left_in.execute()
        rit = self.right_in.execute()
        first_l = await lit.__anext__()
        first_r = await rit.__anext__()
        assert is_barrier(first_l) and is_barrier(first_r)
        assert first_l.epoch == first_r.epoch
        if self._tier is not None:
            self._tier_register()
        for i, side in enumerate(self.sides):
            side.table.init_epoch(first_l.epoch)
            side.recover()
            if self._tier_parts[i] is not None and side.pk_to_ref:
                # recovery rebuilds everything RESIDENT (cold markers
                # do not survive a crash); seed the tier clock so the
                # first checkpoint sweep re-applies the cap
                refs = np.fromiter(side.pk_to_ref.values(),
                                   dtype=np.int64,
                                   count=len(side.pk_to_ref))
                key_cols = [(side.arena.cols[j][refs],
                             side.arena.valid[j][refs])
                            for j in side.key_indices]
                lanes_all = side.key_codec.build_arrays(key_cols)
                self._tier.touch(
                    self._tier_parts[i],
                    map(tuple, np.unique(lanes_all, axis=0).tolist()),
                    self._tier_seq)
        self._recover_degrees()
        yield first_l
        try:
            async for tag, msg in barrier_align_2(lit, rit):
                if tag == "barrier":
                    # consume pending probes FIRST — expiry/compaction
                    # rebuild device state and would invalidate a
                    # re-dispatched probe's sequence view
                    for out in self._emit_pending():
                        yield out
                    self._expire_state()
                    self._tier_seq += 1
                    for i, side in enumerate(self.sides):
                        side.table.commit(msg.epoch)
                        swept = 0
                        part = self._tier_parts[i]
                        if side.expired_lanes:
                            if part is not None:
                                self._tier.forget(part,
                                                  side.expired_lanes)
                            side.expired_lanes = []
                        if part is not None:
                            if msg.kind.is_checkpoint:
                                # sweep at checkpoints only, after the
                                # commit above: evicted rows are durable
                                # and no probe is in flight (tier.py
                                # epoch-sequencing argument)
                                swept = self._tier.sweep(part,
                                                         self._tier_seq)
                        if not swept:
                            side.maybe_compact()
                    self._maybe_gc_interner()
                    # payload residency: device lane bytes vs host
                    # arena bytes, refreshed once per barrier (the
                    # auditable half of "ship refs, not rows")
                    dev_b = sum(
                        s.kernel.device_payload_bytes
                        for s in self.sides
                        if s._kernel is not None and s._mesh is None)
                    _METRICS.join_device_bytes.set(
                        dev_b, executor=self.identity)
                    _METRICS.join_host_bytes.set(
                        sum(s.host_arena_bytes() for s in self.sides),
                        executor=self.identity)
                    for side in self.sides:
                        if side.fused_input is not None:
                            # absorbed-runtime barrier work (row-id
                            # counters rebase to the epoch floor; join
                            # runs carry no watermark stages)
                            side.fused_input.on_barrier(msg)
                    if self._seq > (1 << 30):
                        # int32 sequence headroom: with no probes in
                        # flight, rebase every finite seq to 0 and restart
                        # (a wrap would blank every probe's visibility)
                        for side in self.sides:
                            side.kernel.rebase_seq()
                        self._seq = 1
                    yield msg
                elif tag in ("left", "right"):
                    i = 0 if tag == "left" else 1
                    side = self.sides[i]
                    if isinstance(msg, StreamChunk):
                        if side.fused_input is not None:
                            # fused input run: composed numpy pass for
                            # bookkeeping, raw matrix buffered for the
                            # in-dispatch prelude
                            r = self._run_fused_input(i, msg)
                            if r is None:
                                continue
                            post, raw = r
                            self._ingest_chunk(
                                i, post, None,
                                side.key_nonnull_mask(post), raw=raw)
                            continue
                        # one host→device upload of the key lanes (inside
                        # the kernel's fused dispatch), shared by the probe
                        # and this side's insert; the nonnull mask falls
                        # out of the same pass
                        lanes_np, nonnull = \
                            side.key_codec.build_with_mask(
                                msg, side.key_indices)
                        self._ingest_chunk(i, msg, lanes_np, nonnull)
                    elif isinstance(msg, Watermark):
                        # a fused input side receives watermarks in the
                        # RUN's input space — derive them through the
                        # absorbed projection stages first
                        derived = [msg] if side.fused_input is None \
                            else side.fused_input.derive_watermarks(msg)
                        wms: List = []
                        for one in derived:
                            wms.extend(self._on_watermark(i, one))
                        if wms:
                            # buffered join outputs must precede any
                            # watermark that could close windows over them
                            for out in self._emit_pending():
                                yield out
                        for wm in wms:
                            yield wm
        finally:
            if self._tier is not None:
                for p in self._tier_parts:
                    if p is not None:
                        self._tier.unregister(p)
