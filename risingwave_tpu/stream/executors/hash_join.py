"""HashJoinExecutor: streaming two-sided equi-join (inner, q8 kernel).

Reference parity: src/stream/src/executor/hash_join.rs:227 (executor),
:697 (main loop over barrier-aligned sides), :990 (``eq_join_oneside``);
state layout managed_state/join/mod.rs:228 (JoinHashMap). TPU re-design
(ops/hash_join.py): the device owns the MATCH structure — key table +
row chains probed as whole-batch kernels; the host owns row payloads
(typed column arenas; varchar never ships to HBM) and materializes
output chunks with vectorized gathers.

Chunk lifecycle on side S (probing side O), mirroring eq_join_oneside:
  1. probe every visible row of the chunk against O's current state
     (two device passes: degrees, then pair emission at cumsum offsets)
  2. emit matched rows: S columns gathered from the chunk, O columns
     gathered from O's arena; Insert rows emit Insert matches, Delete
     rows emit Delete matches (update pairs degrade to Delete+Insert —
     the reference degrades split pairs the same way)
  3. apply the chunk to S's own state: inserts allocate arena refs and
     front-link into the device chains; deletes tombstone
  4. barrier: both sides' StateTables commit (rows were written through
     write_chunk as they flowed); recovery rebuilds arena + chains

Inner-join NULL semantics: rows whose join key contains NULL can never
match and are not stored (the reference's null-safe flag is per-column;
non-null-safe is the SQL default). Degree tables for outer joins are the
next increment.
"""

from __future__ import annotations

from typing import AsyncIterator, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.ops.hash_join import JoinSideKernel
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.merge import barrier_align_2
from risingwave_tpu.stream.executors.keys import (
    LANES_PER_KEY, build_key_lanes, build_key_lanes_arrays,
    key_lanes_of_values,
)
from risingwave_tpu.stream.message import Message, Watermark, is_barrier


class _Arena:
    """Host row store: typed column arrays indexed by device row refs."""

    def __init__(self, schema: Schema, capacity: int = 1024):
        self.schema = schema
        self.cap = capacity
        self.cols: List[np.ndarray] = []
        self.valid: List[np.ndarray] = []
        for f in schema:
            dt = f.data_type
            self.cols.append(
                np.zeros(capacity, dtype=dt.np_dtype) if dt.is_device
                else np.empty(capacity, dtype=object))
            self.valid.append(np.ones(capacity, dtype=bool))

    def ensure(self, max_ref: int) -> None:
        if max_ref < self.cap:
            return
        new_cap = self.cap
        while new_cap <= max_ref:
            new_cap *= 2
        for i, c in enumerate(self.cols):
            grown = np.zeros(new_cap, dtype=c.dtype) if c.dtype != object \
                else np.empty(new_cap, dtype=object)
            grown[:self.cap] = c
            self.cols[i] = grown
            v = np.ones(new_cap, dtype=bool)
            v[:self.cap] = self.valid[i]
            self.valid[i] = v
        self.cap = new_cap

    def store(self, refs: np.ndarray, chunk: StreamChunk,
              row_idx: np.ndarray) -> None:
        if not len(refs):
            return
        self.ensure(int(refs.max()))
        for i, c in enumerate(chunk.columns):
            vals = np.asarray(c.values)[row_idx]
            self.cols[i][refs] = vals
            self.valid[i][refs] = True if c.validity is None else \
                np.asarray(c.validity)[row_idx]

    def gather(self, refs: np.ndarray, out_cap: int
               ) -> List[Column]:
        out = []
        for f, c, v in zip(self.schema, self.cols, self.valid):
            vals = np.zeros(out_cap, dtype=c.dtype) if c.dtype != object \
                else np.empty(out_cap, dtype=object)
            vals[:len(refs)] = c[refs]
            ok = np.ones(out_cap, dtype=bool)
            ok[:len(refs)] = v[refs]
            out.append(Column(f.data_type, vals,
                              None if ok.all() else ok))
        return out


class _JoinSide:
    """One side's state: device matcher + host arena + durability."""

    def __init__(self, schema: Schema, key_indices: Sequence[int],
                 pk_indices: Sequence[int], table: StateTable):
        self.schema = schema
        self.key_indices = list(key_indices)
        self.pk_indices = list(pk_indices)
        self.key_types = [schema[i].data_type for i in self.key_indices]
        for dt in self.key_types:
            if not dt.is_device:
                raise TypeError(f"join key type {dt} not device-hashable")
        self.table = table
        self.kernel = JoinSideKernel(
            key_width=LANES_PER_KEY * len(self.key_indices))
        self.arena = _Arena(schema)
        self.pk_to_ref: Dict[tuple, int] = {}
        self.free: List[int] = []
        self.next_ref = 0

    def alloc_refs(self, k: int) -> np.ndarray:
        """Bump allocation ONLY: a tombstoned ref stays linked in its
        chain (deletes unlink lazily), so reusing it would splice its
        node into a second chain and create cycles. Dead refs are
        reclaimed wholesale when the arena is rebuilt (recovery /
        future compaction); `self.free` tracks the reclaimable count."""
        out = np.arange(self.next_ref, self.next_ref + k, dtype=np.int32)
        self.next_ref += k
        return out

    def key_nonnull_mask(self, chunk: StreamChunk) -> np.ndarray:
        m = np.ones(chunk.capacity, dtype=bool)
        for i in self.key_indices:
            c = chunk.columns[i]
            if c.validity is not None:
                m &= np.asarray(c.validity)
        return m

    def apply_chunk(self, chunk: StreamChunk,
                    key_lanes: np.ndarray) -> None:
        """Update this side's state with the chunk's inserts/deletes.

        pk→ref bookkeeping runs in ROW ORDER (a delete refers to the
        latest same-pk version, which may be an insert earlier in this
        very chunk — update pairs land as [U-, U+] with one pk). The
        device calls stay whole-batch: tombstoning and front-linking
        commute once each delete has resolved to the right ref."""
        vis = np.asarray(chunk.visibility)
        storable = vis & self.key_nonnull_mask(chunk)
        ops = np.asarray(chunk.ops)
        is_ins = (ops == int(Op.INSERT)) | (ops == int(Op.UPDATE_INSERT))
        ins_idx = np.flatnonzero(storable & is_ins)
        # pk extraction: one vectorized pass (tolist + zip run in C;
        # a per-row generator here dominated the q8 host profile)
        st_idx = np.flatnonzero(storable)
        pk_lists = []
        for i in self.pk_indices:
            c = chunk.columns[i]
            vals = np.asarray(c.values)[st_idx]
            col = vals.tolist()
            if c.validity is not None:
                okv = np.asarray(c.validity)[st_idx]
                col = [None if not o else v
                       for v, o in zip(col, okv.tolist())]
            pk_lists.append(col)
        pks = dict(zip(st_idx.tolist(), zip(*pk_lists))) \
            if pk_lists else {int(r): () for r in st_idx.tolist()}

        ins_refs = self.alloc_refs(len(ins_idx))
        ins_pos = {int(r): j for j, r in enumerate(ins_idx)}
        del_refs = np.zeros(chunk.capacity, dtype=np.int32)
        del_mask = np.zeros(chunk.capacity, dtype=bool)
        for r in st_idx.tolist():
            if r in ins_pos:
                self.pk_to_ref[pks[r]] = int(ins_refs[ins_pos[r]])
            else:
                ref = self.pk_to_ref.pop(pks[r], None)
                if ref is None:
                    continue   # delete of unseen row (inconsistent op)
                del_refs[r] = ref
                del_mask[r] = True
                self.free.append(ref)
        if len(ins_idx):
            self.arena.store(ins_refs, chunk, ins_idx)
            full_refs = np.zeros(chunk.capacity, dtype=np.int32)
            full_refs[ins_idx] = ins_refs
            mask = np.zeros(chunk.capacity, dtype=bool)
            mask[ins_idx] = True
            self.kernel.insert(jnp.asarray(key_lanes), full_refs,
                               jnp.asarray(mask))
        if del_mask.any():
            self.kernel.delete(del_refs, jnp.asarray(del_mask))
        self.table.write_chunk(chunk)

    # dead-ref fraction of the arena that triggers a compaction; dead
    # refs cannot be recycled in place (see alloc_refs), so churn-heavy
    # streams (update pairs every epoch) reclaim them wholesale here
    COMPACT_DEAD_RATIO = 0.5
    COMPACT_MIN_REFS = 4096

    def maybe_compact(self) -> bool:
        if (self.next_ref < self.COMPACT_MIN_REFS
                or len(self.free) < self.COMPACT_DEAD_RATIO * self.next_ref):
            return False
        self.compact()
        return True

    def compact(self) -> None:
        """Rebuild arena + device state with only live rows (dense refs)."""
        live = np.fromiter(self.pk_to_ref.values(), dtype=np.int64,
                           count=len(self.pk_to_ref))
        n = len(live)
        new_arena = _Arena(self.schema,
                           capacity=max(1024, next_pow2(max(n, 1))))
        for i in range(len(self.schema)):
            new_arena.cols[i][:n] = self.arena.cols[i][live]
            new_arena.valid[i][:n] = self.arena.valid[i][live]
        self.arena = new_arena
        new_refs = np.arange(n, dtype=np.int32)
        self.pk_to_ref = dict(zip(self.pk_to_ref.keys(), new_refs.tolist()))
        self.free = []
        self.next_ref = n
        if n:
            key_cols = [(self.arena.cols[i][:n], self.arena.valid[i][:n])
                        for i in self.key_indices]
            self.kernel.rebuild(build_key_lanes_arrays(key_cols), new_refs)
        else:
            self.kernel.rebuild(
                np.zeros((0, LANES_PER_KEY * len(self.key_indices)),
                         dtype=np.int32),
                new_refs)

    def expire_below(self, key_pos: int, wm_physical) -> int:
        """Watermark state expiry (hash_join.rs:860-945 analog): drop
        every stored row whose ``key_pos``-th join-key column is below
        the watermark. Host side: vectorized scan of live refs → dead
        pks removed from the map, rows deleted from the state table,
        refs tombstoned on device (the existing compaction reclaims the
        arena/chain slots when the dead ratio crosses its threshold).
        Cost is O(live) per call — the executor only calls this when the
        combined watermark actually advances."""
        if not self.pk_to_ref:
            return 0
        col = self.key_indices[key_pos]
        refs = np.fromiter(self.pk_to_ref.values(), dtype=np.int64,
                           count=len(self.pk_to_ref))
        vals = self.arena.cols[col][refs]
        ok = self.arena.valid[col][refs]
        dead = ok & (vals.astype(np.int64) < int(wm_physical))
        n_dead = int(dead.sum())
        if n_dead == 0:
            return 0
        dead_refs = refs[dead].astype(np.int32)
        pks = list(self.pk_to_ref.keys())
        dead_pks = [pks[i] for i in np.flatnonzero(dead).tolist()]
        for pk, ref in zip(dead_pks, dead_refs.tolist()):
            del self.pk_to_ref[pk]
            self.free.append(ref)
            row = tuple(
                None if not self.arena.valid[i][ref]
                else (self.arena.cols[i][ref].item()
                      if self.schema[i].data_type.is_device
                      else self.arena.cols[i][ref])
                for i in range(len(self.schema)))
            self.table.delete(row)
        cap = next_pow2(n_dead)
        del_refs = np.zeros(cap, dtype=np.int32)
        del_refs[:n_dead] = dead_refs
        mask = np.zeros(cap, dtype=bool)
        mask[:n_dead] = True
        self.kernel.delete(del_refs, jnp.asarray(mask))
        return n_dead

    def recover(self) -> None:
        keys_l, refs_l = [], []
        rows: List[tuple] = []
        for _pk, row in self.table.iter_rows():
            rows.append(row)
        if not rows:
            return
        n = len(rows)
        refs = self.alloc_refs(n)
        self.arena.ensure(int(refs.max()))
        for i, f in enumerate(self.schema):
            col_vals = [r[i] for r in rows]
            if f.data_type.is_device:
                ok = np.asarray([v is not None for v in col_vals])
                vals = np.asarray(
                    [0 if v is None else v for v in col_vals],
                    dtype=f.data_type.np_dtype)
                self.arena.cols[i][refs] = vals
                self.arena.valid[i][refs] = ok
            else:
                self.arena.cols[i][refs] = np.asarray(col_vals,
                                                      dtype=object)
        for row, ref in zip(rows, refs.tolist()):
            pk = tuple(row[i] for i in self.pk_indices)
            self.pk_to_ref[pk] = ref
            keys_l.append(key_lanes_of_values(
                [row[i] for i in self.key_indices], self.key_types))
        # rows with NULL join keys were never stored on device
        keep = [j for j, row in enumerate(rows)
                if all(row[i] is not None for i in self.key_indices)]
        if keep:
            self.kernel.rebuild(np.stack([keys_l[j] for j in keep]),
                                refs[keep])


class HashJoinExecutor(Executor):
    """Streaming inner equi-join (hash_join.rs:227, device matcher)."""

    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 left_table: StateTable, right_table: StateTable,
                 actor_id: int = 0,
                 output_names: Optional[Sequence[str]] = None):
        assert len(left_keys) == len(right_keys)
        self.left_in, self.right_in = left, right
        self.sides = (
            _JoinSide(left.schema, left_keys, left_table.pk_indices,
                      left_table),
            _JoinSide(right.schema, right_keys, right_table.pk_indices,
                      right_table),
        )
        fields: List[Field] = []
        names = list(output_names) if output_names else None
        k = 0
        for sch in (left.schema, right.schema):
            for f in sch:
                name = names[k] if names else f.name
                fields.append(Field(name, f.data_type))
                k += 1
        out_schema = Schema(fields)
        # output pk: both sides' pks (joined row identity)
        n_left = len(left.schema)
        pk = list(left_table.pk_indices) + \
            [n_left + i for i in right_table.pk_indices]
        super().__init__(ExecutorInfo(
            out_schema, pk, f"HashJoinExecutor(actor={actor_id})"))
        self.n_left = n_left
        # join-key watermarks (hash_join.rs:860-945): per side, latest
        # watermark per key POSITION; the forwarded/cleaning watermark
        # is the min across sides, monotone
        self._side_wm: List[Dict[int, int]] = [{}, {}]
        self._combined_wm: Dict[int, int] = {}
        self._expired_wm: Dict[int, int] = {}

    # -- emission ---------------------------------------------------------
    def _emit(self, side_idx: int, chunk: StreamChunk,
              key_lanes: np.ndarray) -> Optional[StreamChunk]:
        """Probe the OTHER side and build the matched output chunk."""
        me = self.sides[side_idx]
        other = self.sides[1 - side_idx]
        vis = np.asarray(chunk.visibility) & me.key_nonnull_mask(chunk)
        if not vis.any():
            return None
        _deg, probe_idx, refs = other.kernel.probe(
            jnp.asarray(key_lanes), jnp.asarray(vis))
        t = len(probe_idx)
        if t == 0:
            return None
        cap = next_pow2(t)
        # my columns: gathered from the incoming chunk
        my_cols: List[Column] = []
        for f, c in zip(me.schema, chunk.columns):
            src = np.asarray(c.values)[probe_idx]
            vals = np.zeros(cap, dtype=src.dtype) if src.dtype != object \
                else np.empty(cap, dtype=object)
            vals[:t] = src
            ok = np.ones(cap, dtype=bool)
            if c.validity is not None:
                ok[:t] = np.asarray(c.validity)[probe_idx]
            my_cols.append(Column(f.data_type, vals,
                                  None if ok.all() else ok))
        other_cols = other.arena.gather(refs, cap)
        columns = my_cols + other_cols if side_idx == 0 \
            else other_cols + my_cols
        # ops: degrade update pairs (split halves) to Delete/Insert
        in_ops = np.asarray(chunk.ops)[probe_idx]
        is_ins = (in_ops == int(Op.INSERT)) | \
            (in_ops == int(Op.UPDATE_INSERT))
        ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
        ops[:t] = np.where(is_ins, int(Op.INSERT), int(Op.DELETE))
        out_vis = np.zeros(cap, dtype=bool)
        out_vis[:t] = True
        return StreamChunk(self.schema, columns, out_vis, ops)

    # -- watermarks -------------------------------------------------------
    def _on_watermark(self, side_idx: int, msg: "Watermark"):
        """Join-key watermarks combine as min across sides and forward
        for BOTH output columns of the key pair (they are equal by the
        join predicate); non-key watermarks are dropped (reference
        behavior). The combined watermark also drives state expiry at
        the next barrier."""
        me = self.sides[side_idx]
        if msg.col_idx not in me.key_indices:
            return
        pos = me.key_indices.index(msg.col_idx)
        self._side_wm[side_idx][pos] = msg.value
        other_wm = self._side_wm[1 - side_idx].get(pos)
        if other_wm is None:
            return
        combined = min(msg.value, other_wm)
        prev = self._combined_wm.get(pos)
        if prev is not None and combined <= prev:
            return
        self._combined_wm[pos] = combined
        left_col = self.sides[0].key_indices[pos]
        right_col = self.n_left + self.sides[1].key_indices[pos]
        yield Watermark(left_col, msg.data_type, combined)
        yield Watermark(right_col, msg.data_type, combined)

    def _expire_state(self) -> None:
        for pos, wm in self._combined_wm.items():
            done = self._expired_wm.get(pos)
            if done is not None and wm <= done:
                continue
            dt = np.dtype(
                self.sides[0].key_types[pos].np_dtype)
            if not np.issubdtype(dt, np.integer):
                continue       # float keys: no order-safe expiry
            for side in self.sides:
                side.expire_below(pos, int(wm))
            self._expired_wm[pos] = wm

    # -- main loop --------------------------------------------------------
    async def execute(self) -> AsyncIterator[Message]:
        lit = self.left_in.execute()
        rit = self.right_in.execute()
        first_l = await lit.__anext__()
        first_r = await rit.__anext__()
        assert is_barrier(first_l) and is_barrier(first_r)
        assert first_l.epoch == first_r.epoch
        for side in self.sides:
            side.table.init_epoch(first_l.epoch)
            side.recover()
        yield first_l
        async for tag, msg in barrier_align_2(lit, rit):
            if tag == "barrier":
                self._expire_state()
                for side in self.sides:
                    side.table.commit(msg.epoch)
                    side.maybe_compact()
                yield msg
            elif tag in ("left", "right"):
                i = 0 if tag == "left" else 1
                if isinstance(msg, StreamChunk):
                    # one host→device upload of the key lanes, shared by
                    # the probe and this side's insert
                    lanes_dev = jnp.asarray(build_key_lanes(
                        msg, self.sides[i].key_indices))
                    out = self._emit(i, msg, lanes_dev)
                    if out is not None:
                        yield out
                    self.sides[i].apply_chunk(msg, lanes_dev)
                elif isinstance(msg, Watermark):
                    for wm in self._on_watermark(i, msg):
                        yield wm
