"""Stateless single-input executors: Receiver, Project, Filter.

Reference parity:
- ReceiverExecutor: src/stream/src/executor/receiver.rs (single upstream
  channel as an executor).
- ProjectExecutor: src/stream/src/executor/project.rs — eval expressions
  over the chunk, emit new columns; watermarks pass through with column
  remapping when derivable.
- FilterExecutor: src/stream/src/executor/filter.rs — predicate masks
  visibility; UpdateDelete/UpdateInsert pairs whose halves diverge under
  the predicate degrade to plain Delete/Insert (one half hidden).

TPU notes: both operators are pure vectorized passes over the padded chunk;
no per-row host work. Filter's pair-degradation is a shifted-mask trick,
one fused VPU pass.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Optional, Sequence

from risingwave_tpu.common.chunk import Op, StreamChunk, get_xp
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.expr.expr import Expression
from risingwave_tpu.stream.exchange import ChannelClosed, Receiver
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, Watermark, is_barrier, is_chunk,
)


class ReceiverExecutor(Executor):
    """Adapts one exchange Receiver into an Executor (receiver.rs)."""

    def __init__(self, info: ExecutorInfo, rx: Receiver, actor_id: int = 0):
        super().__init__(info)
        self.rx = rx
        self.actor_id = actor_id
        # wall time parked on the channel waiting for the next message
        # — idle, not processing; the monitor subtracts it from this
        # node's exclusive busy (same contract as SourceExecutor and
        # RemoteInput: a chain edge waiting out a slow upstream must
        # not read as the downstream chain's straggler)
        self.idle_wait_s = 0.0

    async def execute(self) -> AsyncIterator[Message]:
        import time as _time
        # NOTE: no rx.close() on teardown here — the chain edge may
        # still be attached to a live upstream dispatcher (a close
        # would turn its next dispatch into ChannelClosed and kill the
        # healthy upstream); the session's _stop_job closes the rx via
        # close_receivers AFTER detaching the edge
        while True:
            t0 = _time.monotonic()
            try:
                msg = await self.rx.recv()
            except ChannelClosed:
                return
            finally:
                self.idle_wait_s += _time.monotonic() - t0
            yield msg
            if is_barrier(msg) and msg.is_stop(self.actor_id):
                return


class ProjectExecutor(Executor):
    """Vectorized projection (project.rs analog)."""

    def __init__(self, input_: Executor, exprs: Sequence[Expression],
                 names: Optional[Sequence[str]] = None,
                 watermark_derivations: Optional[dict] = None):
        self.input = input_
        self.exprs = list(exprs)
        names = list(names) if names else [
            f"expr{i}" for i in range(len(exprs))]
        out_fields: List[Field] = []
        for name, e in zip(names, self.exprs):
            out_fields.append(Field(name, e.return_type))
        info = ExecutorInfo(Schema(out_fields), [], "ProjectExecutor")
        super().__init__(info)
        # input col idx -> output col idx OR (output col idx, transform)
        # for a monotone expression over the watermark column (the
        # reference derives output watermarks through monotone exprs,
        # watermark.rs::transform_with_expr — e.g. tumble_start maps a
        # date_time watermark to a window_start watermark)
        self.watermark_derivations = dict(watermark_derivations or {})

    @staticmethod
    def _drop_noop_updates(cols, vis, ops):
        """Mask out U-/U+ pairs whose halves are identical AFTER the
        projection (project.rs noop-update elimination): when a
        projection drops a changing column (e.g. a dedup agg's hidden
        _cnt), every duplicate otherwise becomes a full update churning
        join chains and state tables downstream. Dropping an identical
        pair is multiset-exact regardless of keys."""
        import numpy as np
        ud = np.flatnonzero(vis[:-1] & vis[1:]
                            & (ops[:-1] == int(Op.UPDATE_DELETE))
                            & (ops[1:] == int(Op.UPDATE_INSERT)))
        if not len(ud):
            return vis
        same = np.ones(len(ud), dtype=bool)
        for c in cols:
            v = np.asarray(c.values)
            eq = np.asarray(v[ud] == v[ud + 1], dtype=bool)
            if c.validity is not None:
                ok = np.asarray(c.validity)
                both_null = ~ok[ud] & ~ok[ud + 1]
                eq = (eq & ok[ud] & ok[ud + 1]) | both_null
            same &= eq
            if not same.any():
                return vis
        drop = ud[same]
        vis = vis.copy()
        vis[drop] = False
        vis[drop + 1] = False
        return vis

    async def execute(self) -> AsyncIterator[Message]:
        import numpy as np
        async for msg in self.input.execute():
            if is_chunk(msg):
                cols = [e.eval(msg) for e in self.exprs]
                vis = msg.visibility
                ops_np = np.asarray(msg.ops)
                if (ops_np == int(Op.UPDATE_DELETE)).any():
                    vis = self._drop_noop_updates(cols, np.asarray(vis),
                                                  ops_np)
                    if not np.asarray(vis).any():
                        continue   # all pairs were noops: emit nothing
                yield StreamChunk(self.schema, cols, vis, msg.ops)
            elif isinstance(msg, Watermark):
                d = self.watermark_derivations.get(msg.col_idx)
                # one input watermark may derive SEVERAL outputs (the
                # raw column plus a windowed image of it): list form
                for one in (d if isinstance(d, list)
                            else [] if d is None else [d]):
                    if isinstance(one, tuple):
                        out_idx, fn = one
                        yield Watermark(out_idx, msg.data_type,
                                        fn(msg.value))
                    else:
                        yield msg.with_idx(one)
                # underivable watermarks are dropped (reference behavior)
            else:
                yield msg


class FilterExecutor(Executor):
    """Visibility-mask filter with update-pair degradation (filter.rs)."""

    def __init__(self, input_: Executor, predicate: Expression):
        self.input = input_
        self.predicate = predicate
        info = ExecutorInfo(input_.schema, list(input_.pk_indices),
                            "FilterExecutor")
        super().__init__(info)

    async def execute(self) -> AsyncIterator[Message]:
        import numpy as np
        async for msg in self.input.execute():
            if is_chunk(msg):
                out = self._apply(msg)
                # a fully-filtered chunk is dead weight downstream
                # (empty-message suppression, end to end)
                if np.asarray(out.visibility).any():
                    yield out
            else:
                yield msg

    def _apply(self, chunk: StreamChunk) -> StreamChunk:
        return self.apply_predicate(chunk, self.predicate)

    @staticmethod
    def apply_predicate(chunk: StreamChunk,
                        predicate: Expression) -> StreamChunk:
        """THE filter transform — xp-generic, so the interpretive path
        (numpy) and the fused traced path (jit tracers, ops/fused.py)
        run the same implementation: visibility mask plus U-/U+ pair
        degradation by shifted compares."""
        pcol = predicate.eval(chunk)
        xp = get_xp(pcol.values, chunk.ops)
        pred = pcol.values.astype(bool)
        if pcol.validity is not None:  # NULL predicate = not satisfied
            pred = pred & pcol.validity
        ops = chunk.ops
        is_ud = ops == xp.int8(int(Op.UPDATE_DELETE))
        is_ui = ops == xp.int8(int(Op.UPDATE_INSERT))
        # pair (i, i+1): U- at i, U+ at i+1
        next_is_ui = xp.roll(is_ui, -1)
        prev_is_ud = xp.roll(is_ud, 1)
        next_pred = xp.roll(pred, -1)
        prev_pred = xp.roll(pred, 1)
        # U- whose U+ half fails the predicate → plain DELETE
        degrade_del = is_ud & next_is_ui & pred & ~next_pred
        # U+ whose U- half fails the predicate → plain INSERT
        degrade_ins = is_ui & prev_is_ud & pred & ~prev_pred
        new_ops = xp.where(degrade_del, xp.int8(int(Op.DELETE)), ops)
        new_ops = xp.where(degrade_ins, xp.int8(int(Op.INSERT)), new_ops)
        return StreamChunk(chunk.schema, chunk.columns,
                           chunk.visibility & pred, new_ops)
