"""MaterializeExecutor: sink a stream into its materialized-view table.

Reference parity: src/stream/src/executor/mview/materialize.rs:53 — apply
each StreamChunk to the MV's StateTable (pk-conflict handling per
ConflictBehavior), commit on barrier, forward messages downstream.

TPU notes: the MV table is the queryable result — batch `SELECT` reads the
committed snapshot (storage side of the same state store). Overwrite
conflict handling turns blind inserts into updates so the MV stays a
function of pk (materialize.rs `handle_conflict` analog).
"""

from __future__ import annotations

import enum
from typing import AsyncIterator

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import is_barrier, is_chunk, Message


class ConflictBehavior(enum.Enum):
    NO_CHECK = "no_check"        # trust upstream ops (MV over keyed stream)
    OVERWRITE = "overwrite"      # last write wins on pk conflict
    IGNORE = "ignore"            # first write wins


class MaterializeExecutor(Executor):
    """Materialize a changelog into a StateTable (materialize.rs:53)."""

    def __init__(self, input_: Executor, table: StateTable,
                 conflict: ConflictBehavior = ConflictBehavior.NO_CHECK,
                 mv_name: str = ""):
        self.input = input_
        self.table = table
        self.conflict = conflict
        # freshness accounting identity (stream/freshness.py): the MV
        # name readers know; empty = an unnamed/test pipeline whose
        # barrier passages still sample under the table id
        self.mv_name = mv_name or f"table-{table.table_id}"
        info = ExecutorInfo(input_.schema, list(table.pk_indices),
                            "MaterializeExecutor")
        super().__init__(info)

    async def execute(self) -> AsyncIterator[Message]:
        from risingwave_tpu.stream import freshness as _fresh
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first), "executor protocol: first message is the " \
            f"init barrier, got {first!r}"
        self.table.init_epoch(first.epoch)
        yield first
        async for msg in it:
            if is_chunk(msg):
                self._apply(msg)
                yield msg
            elif is_barrier(msg):
                self.table.commit(msg.epoch)
                if _fresh.enabled():
                    # everything ingested before this barrier is now
                    # applied (and commits with its collection): the
                    # MV's visible event frontier advances to the
                    # source frontiers recorded at the same barrier
                    _fresh.FRESHNESS.note_visible(
                        self.mv_name, msg.epoch.curr.value)
                yield msg
            else:
                yield msg

    def _apply(self, chunk: StreamChunk) -> None:
        if self.conflict == ConflictBehavior.NO_CHECK:
            # NO_CHECK trusts upstream ops by contract — all-insert
            # epochs stage past the memtable and land in the store as
            # one bulk ingest at the barrier (ISSUE 12 emit path)
            self.table.write_chunk(chunk, defer=True)
            return
        _idx, rows, ops = chunk.to_physical_records()
        for op, row in zip(ops.tolist(), rows):
            pk = self.table.pk_of(row)
            old = self.table.get_row(pk)
            if op in (int(Op.INSERT), int(Op.UPDATE_INSERT)):
                if old is None:
                    self.table.insert(row)
                elif self.conflict == ConflictBehavior.OVERWRITE:
                    self.table.update(old, row)
                # IGNORE: keep first write
            else:
                if old is not None:
                    self.table.delete(old)
