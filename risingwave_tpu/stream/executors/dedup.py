"""AppendOnlyDedupExecutor.

Reference parity: src/stream/src/executor/dedup/append_only_dedup.rs —
drop rows whose dedup key was already seen; seen keys persist through a
StateTable so recovery resumes without re-emitting.

TPU note: dedup keys ride the same interning/lane codec as group keys;
the membership test runs against a host set keyed by the int32 lane
tuples (exact, including interned varchar keys).
"""

from __future__ import annotations

from typing import AsyncIterator, List, Sequence, Set, Tuple

import numpy as np

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.executors.keys import KeyCodec
from risingwave_tpu.stream.message import (
    Message, is_barrier, is_chunk,
)


class AppendOnlyDedupExecutor(Executor):
    """Keep the FIRST row per dedup key of an append-only stream."""

    def __init__(self, input_: Executor, dedup_indices: Sequence[int],
                 state: StateTable,
                 identity: str = "AppendOnlyDedupExecutor"):
        super().__init__(ExecutorInfo(
            input_.schema, list(dedup_indices), identity))
        self.input = input_
        self.dedup_indices = list(dedup_indices)
        self.codec = KeyCodec(
            [input_.schema[i].data_type for i in dedup_indices])
        self.state = state
        self._seen: Set[Tuple[int, ...]] = set()

    def _apply(self, chunk: StreamChunk) -> StreamChunk | None:
        lanes = self.codec.build(chunk, self.dedup_indices)
        vis = np.asarray(chunk.visibility)
        keep = np.zeros(chunk.capacity, dtype=bool)
        idx, rows, _ops = chunk.to_physical_records()
        new_rows: List[tuple] = []
        for i, row in zip(idx.tolist(), rows):
            key = tuple(lanes[i].tolist())
            if key in self._seen:
                continue
            self._seen.add(key)
            keep[i] = True
            new_rows.append(row)
        for row in new_rows:
            self.state.insert(tuple(row[i] for i in self.dedup_indices))
        out_vis = vis & keep
        if not out_vis.any():
            return None
        return StreamChunk(chunk.schema, chunk.columns, out_vis,
                           chunk.ops)

    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first)
        self.state.init_epoch(first.epoch)
        for pk, _row in self.state.iter_rows():
            self._seen.add(
                tuple(self.codec.lanes_of_values(list(pk)).tolist()))
        yield first
        async for msg in it:
            if is_chunk(msg):
                out = self._apply(msg)
                if out is not None:
                    yield out
            elif is_barrier(msg):
                self.state.commit(msg.epoch)
                yield msg
            else:
                yield msg

