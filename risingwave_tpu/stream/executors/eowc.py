"""EowcGateExecutor: EMIT ON WINDOW CLOSE output gating.

Reference parity: src/stream/src/executor/sort_buffer.rs (the
watermark-keyed sort buffer) as used by hash_agg.rs:510
(AggGroup::create_eowc) and over_window/eowc.rs — under EMIT ON WINDOW
CLOSE a job emits each result row exactly ONCE, when the watermark
passes its window column, instead of the default emit-on-update
changelog. TPU re-design: a gate executor downstream of the (windowed)
aggregation holds the CURRENT version of every result row in a
StateTable keyed by (window col, pk suffix); a watermark advancing to
w releases — as plain INSERTs, in window order — every row whose
window column is strictly below w and forwards the watermark. Released
windows are final by the upstream's own watermark contract (the agg
retires state below the same watermark), so no tombstone set is
needed; a late change to a released window indicates an upstream
watermark violation and fails loudly.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Optional

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, Watermark, is_barrier, is_chunk,
)

MAX_OUT_CHUNK = 4096


class EowcGateExecutor(Executor):
    """Emit-once gate over a changelog (sort_buffer.rs analog)."""

    def __init__(self, input_: Executor, wm_col: int,
                 state: StateTable, actor_id: int = 0):
        self.input = input_
        self.wm_col = wm_col
        self.state = state
        # state pk must lead with the watermark column: releases are
        # ordered range scans + range deletes (delete_below_prefix)
        assert state.pk_indices[0] == wm_col, \
            "EOWC buffer pk must lead with the watermark column"
        super().__init__(ExecutorInfo(
            input_.schema, list(input_.pk_indices),
            f"EowcGateExecutor(actor={actor_id})"))
        self._released: Optional[int] = None

    def _apply(self, chunk: StreamChunk) -> None:
        if self._released is not None:
            vis = np.asarray(chunk.visibility)
            c = chunk.columns[self.wm_col]
            vals = np.asarray(c.values)
            late = vis & (vals.astype(np.int64) < self._released)
            if c.validity is not None:
                late &= np.asarray(c.validity)
            if late.any():
                raise RuntimeError(
                    "EMIT ON WINDOW CLOSE violation: upstream changed "
                    "a window already released at watermark "
                    f"{self._released}")
        self.state.write_chunk(chunk)

    def _release(self, wm: int) -> List[StreamChunk]:
        """Ordered RANGE scan of closed windows: the pk leads with the
        watermark column, so released rows are one bounded scan —
        O(released), not O(buffered) — starting ABOVE the NULL tag
        (a NULL window never closes; those rows stay buffered)."""
        from risingwave_tpu.state.keycodec import (
            encode_memcomparable, encode_vnode_prefix,
        )
        dt = self.schema[self.wm_col].data_type
        start = encode_vnode_prefix(0) + b"\x01"   # skip NULL windows
        end = encode_vnode_prefix(0) + encode_memcomparable([wm], [dt])
        rows = [row for _k, row in
                self.state.iter_encoded_range(start, end)]
        # NOT `max(self._released or 0, wm)`: pre-1970 windows are
        # negative, and clamping to 0 would fake violations
        self._released = wm if self._released is None \
            else max(self._released, wm)
        if not rows:
            return []
        self.state.delete_rows(rows)
        out = []
        for at in range(0, len(rows), MAX_OUT_CHUNK):
            batch = rows[at:at + MAX_OUT_CHUNK]
            t = len(batch)
            cap = next_pow2(t)
            cols = []
            for i, f in enumerate(self.schema):
                dt = f.data_type
                vals = [r[i] for r in batch]
                ok = np.ones(cap, dtype=bool)
                ok[:t] = [v is not None for v in vals]
                if dt.is_device:
                    arr = np.zeros(cap, dtype=dt.np_dtype)
                    arr[:t] = [0 if v is None else v for v in vals]
                else:
                    arr = np.empty(cap, dtype=object)
                    arr[:t] = vals
                cols.append(Column(dt, arr, None if ok.all() else ok))
            vis = np.zeros(cap, dtype=bool)
            vis[:t] = True
            ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
            out.append(StreamChunk(self.schema, cols, vis, ops))
        return out

    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first)
        self.state.init_epoch(first.epoch)
        yield first
        pending_wm: Optional[Watermark] = None
        async for msg in it:
            if is_chunk(msg):
                self._apply(msg)
            elif is_barrier(msg):
                # release at the barrier so the emitted rows and the
                # buffer deletion commit atomically
                if pending_wm is not None:
                    for out in self._release(int(pending_wm.value)):
                        yield out
                    yield pending_wm
                    pending_wm = None
                self.state.commit(msg.epoch)
                yield msg
            elif isinstance(msg, Watermark):
                if msg.col_idx == self.wm_col:
                    pending_wm = msg
                # non-window watermarks are meaningless post-gate
