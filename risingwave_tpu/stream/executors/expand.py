"""ExpandExecutor: replicate each chunk once per column subset
(grouping-sets / DISTINCT aggregate support).

Reference parity: src/stream/src/executor/expand.rs:27 — output schema is
[input fields (subset-masked), input fields (full copy), flag: int64];
for subset i every non-subset column of the first half is NULL and
`flag` is the subset ordinal. One output chunk per (input chunk, subset):
whole-chunk column masking, no per-row work — already the TPU-friendly
shape.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Sequence

import numpy as np

from risingwave_tpu.common.chunk import Column, StreamChunk, get_xp
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, is_chunk


def _null_column(dt: DataType, n: int) -> Column:
    if dt.is_device:
        vals = np.zeros(n, dtype=dt.np_dtype)
    else:
        vals = np.empty(n, dtype=object)
    return Column(dt, vals, np.zeros(n, dtype=bool))


class ExpandExecutor(Executor):
    """Grouping-sets expansion (expand.rs:27 analog)."""

    def __init__(self, input_: Executor,
                 column_subsets: Sequence[Sequence[int]],
                 pk_indices: Sequence[int] = ()):
        fields: List[Field] = []
        for f in input_.schema:
            fields.append(Field(f.name, f.data_type))
        for f in input_.schema:
            fields.append(Field(f.name, f.data_type))
        fields.append(Field("flag", DataType.INT64))
        super().__init__(ExecutorInfo(Schema(fields), list(pk_indices),
                                      "ExpandExecutor"))
        self.input = input_
        self.column_subsets = [set(s) for s in column_subsets]

    async def execute(self) -> AsyncIterator[Message]:
        async for msg in self.input.execute():
            if not is_chunk(msg):
                yield msg
                continue
            n = msg.capacity
            for i, subset in enumerate(self.column_subsets):
                cols: List[Column] = []
                for j, c in enumerate(msg.columns):
                    cols.append(c if j in subset
                                else _null_column(c.data_type, n))
                cols.extend(msg.columns)
                cols.append(Column(DataType.INT64,
                                   np.full(n, i, dtype=np.int64), None))
                yield StreamChunk(self.schema, cols, msg.visibility,
                                  msg.ops)
