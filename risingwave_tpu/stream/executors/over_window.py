"""OverWindowExecutor: window functions over a retractable stream.

Reference parity: src/stream/src/executor/over_window/general.rs:59
(OverWindowExecutor — state table pk = partition | order | input pk,
output = input + window columns), delta application per partition
(general.rs:295 apply_chunk, :443 build_changes_for_partition) and the
partition cache of over_window/over_partition.rs. The EOWC variant
(over_window/eowc.rs) is subsumed: append-only inputs simply never
produce retraction deltas here.

TPU re-design: the reference walks a delta BTreeMap row by row and
steps one incremental WindowState per function; here each TOUCHED
partition recomputes its window outputs as whole-column numpy passes
(expr/window.compute_window_outputs) and emits the DIFF against the
previous outputs. Deltas buffer per epoch and flush at the barrier —
one recompute per touched partition per epoch, not per chunk. Output
changes are a pure function of the partition's row set, so recovery
needs only the input rows (the reference persists outputs too; we
recompute on first touch).

Window order: ORDER BY columns encode to memcomparable bytes (DESC
inverts the bytes), then the input pk breaks ties — identical to the
reference's StateKey = memcmp(order) | pk (general.rs:130).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import AsyncIterator, Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.expr.window import (
    WindowCall, compute_window_outputs,
)
from risingwave_tpu.state.keycodec import encode_memcomparable
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, is_barrier, is_chunk

MAX_OUT_CHUNK = 4096
PARTITION_CACHE_CAP = 256


class _Partition:
    """One partition's rows in window order + last emitted outputs."""

    __slots__ = ("keys", "rows", "outs")

    def __init__(self):
        # sort keys: (memcmp order bytes, memcmp pk bytes) tuples
        self.keys: List[Tuple[bytes, bytes]] = []
        self.rows: List[tuple] = []
        self.outs: Optional[List[Tuple[np.ndarray, np.ndarray]]] = None


class OverWindowExecutor(Executor):
    """Adds window-function columns to a retractable stream."""

    def __init__(self, input_: Executor,
                 partition_indices: Sequence[int],
                 order_by: Sequence[Tuple[int, bool]],
                 calls: Sequence[WindowCall],
                 state: StateTable,
                 input_pk: Optional[Sequence[int]] = None,
                 output_names: Optional[Sequence[str]] = None,
                 actor_id: int = 0):
        self.input = input_
        self.partition_indices = list(partition_indices)
        self.order_by = [(i, bool(desc)) for i, desc in order_by]
        self.calls = list(calls)
        self.state = state
        in_schema = input_.schema
        self.n_in = len(in_schema)
        # full input pk (the OUTPUT identity — may overlap the
        # partition/order columns); the state pk tie-break suffix is
        # the part that does not. Defaults to the suffix (correct when
        # the pk is disjoint from partition/order keys — the planner
        # always passes the full pk explicitly).
        prefix = len(self.partition_indices) + len(self.order_by)
        self.pk_suffix = list(state.pk_indices[prefix:])
        self.input_pk = list(input_pk if input_pk is not None
                             else self.pk_suffix)
        assert state.pk_indices[:prefix] == \
            self.partition_indices + [i for i, _ in self.order_by], \
            "over-window state pk must be partition | order | suffix"
        names = list(output_names) if output_names else \
            [f"w{j}" for j in range(len(self.calls))]
        fields = list(in_schema) + [
            Field(names[j], c.output_type(in_schema))
            for j, c in enumerate(self.calls)]
        super().__init__(ExecutorInfo(
            Schema(fields), list(self.input_pk),
            f"OverWindowExecutor(actor={actor_id})"))
        self.order_types = [in_schema[i].data_type
                            for i, _ in self.order_by]
        self.pk_types = [in_schema[i].data_type for i in self.pk_suffix]
        # partition key tuple → _Partition (bounded LRU; a miss reloads
        # from the state table — over_partition.rs cache analog)
        self._cache: "OrderedDict[tuple, _Partition]" = OrderedDict()
        # epoch delta buffer: partition key → [(sort_key, row, is_ins)]
        self._delta: Dict[tuple, List[tuple]] = {}
        # accounting + eviction hook: the partition cache is a CLEAN
        # snapshot cache (reloadable from the state table), so it is
        # safely evictable under memory pressure
        import weakref

        from risingwave_tpu.utils import memory as _mem
        name = f"{self.identity}#{id(self)}"
        ref = weakref.ref(self)

        def _nbytes() -> int:
            s = ref()
            if s is None:
                _mem.GLOBAL.unregister(name)
                return 0
            return sum(
                120 * len(p.rows) + 64 * len(p.keys)
                + (0 if p.outs is None else
                   sum(o[0].nbytes + o[1].nbytes for o in p.outs))
                for p in s._cache.values())

        def _evict() -> int:
            s = ref()
            if s is None:
                return 0
            before = _nbytes()
            for k in [k for k in s._cache if k not in s._delta][:-8]:
                s._cache.pop(k)
            return before - _nbytes()

        _mem.GLOBAL.register(name, _nbytes, evict=_evict)

    # -- keys -------------------------------------------------------------
    def _sort_key(self, row: tuple) -> Tuple[bytes, bytes]:
        """(order bytes, pk bytes): sorts as the window order with pk
        tie-break; the order half alone decides ORDER BY peerage.

        NULL order values encode with tag 0x02 (> the 0x01 non-null
        tag) so ASC sorts NULLS LAST; DESC inverts the bytes, putting
        NULLS FIRST — both are PostgreSQL's defaults."""
        parts = []
        for (i, desc), dt in zip(self.order_by, self.order_types):
            b = b"\x02" if row[i] is None else \
                encode_memcomparable([row[i]], [dt])
            parts.append(bytes(255 - x for x in b) if desc else b)
        return (b"".join(parts), encode_memcomparable(
            [row[i] for i in self.pk_suffix], self.pk_types))

    def _partition_key(self, row: tuple) -> tuple:
        return tuple(row[i] for i in self.partition_indices)

    # -- partition load / recompute --------------------------------------
    def _load(self, pkey: tuple) -> _Partition:
        p = self._cache.get(pkey)
        if p is not None:
            self._cache.move_to_end(pkey)
            return p
        p = _Partition()
        pairs = []
        for _pk, row in self.state.iter_prefix(list(pkey)):
            pairs.append((self._sort_key(row), row))
        pairs.sort(key=lambda t: t[0])   # DESC order differs from pk order
        p.keys = [k for k, _ in pairs]
        p.rows = [r for _, r in pairs]
        self._cache[pkey] = p
        while len(self._cache) > PARTITION_CACHE_CAP:
            # never evict a partition with buffered deltas — or the one
            # just loaded (its delta registers right after this call):
            # a cached snapshot predates this epoch's state writes, so
            # a reload would see them in the memtable and double-apply
            for victim in self._cache:
                if victim not in self._delta and victim != pkey:
                    self._cache.pop(victim)
                    break
            else:
                break
        return p

    def _compute(self, p: _Partition
                 ) -> List[Tuple[np.ndarray, np.ndarray]]:
        n = len(p.rows)
        eq_prev = np.zeros(n, dtype=bool)
        if n > 1:
            eq_prev[1:] = [p.keys[j][0] == p.keys[j - 1][0]
                           for j in range(1, n)]
        inputs = []
        for c in self.calls:
            if c.input_idx is None:      # rank family + count(*)
                inputs.append(None)
                continue
            dt = self.input.schema[c.input_idx].data_type
            col = [r[c.input_idx] for r in p.rows]
            ok = np.asarray([v is not None for v in col])
            if dt.is_device:
                vals = np.asarray(
                    [0 if v is None else v for v in col],
                    dtype=dt.np_dtype)
            else:
                vals = np.asarray(col, dtype=object)
            inputs.append((vals, ok))
        return compute_window_outputs(self.calls, n, eq_prev, inputs)

    # -- delta application ------------------------------------------------
    def _buffer_chunk(self, chunk: StreamChunk) -> None:
        for op, row in chunk.to_records():
            pkey = self._partition_key(row)
            if pkey not in self._delta:
                # snapshot the partition BEFORE this epoch's state
                # writes land in the memtable (the delta will be
                # applied on top at flush — loading later would see
                # the rows twice)
                self._load(pkey)
                self._delta[pkey] = []
            self._delta[pkey].append(
                (self._sort_key(row), row, op.is_insert))
        self.state.write_chunk(chunk)

    def _flush(self) -> List[StreamChunk]:
        """Apply buffered deltas partition by partition; emit the diff
        of window outputs (general.rs build_changes_for_partition).

        All retractions emit BEFORE all insertions, across partitions:
        a row whose PARTITION KEY changed within the epoch appears as
        a delete in its old partition's diff and an insert in the
        new one's — a pk-keyed downstream must see D before I or the
        row nets to deleted. Update pairs split into plain D/I halves
        under this ordering (the reference degrades split pairs the
        same way)."""
        dels: List[Tuple[int, tuple]] = []
        inss: List[Tuple[int, tuple]] = []
        for pkey, deltas in self._delta.items():
            p = self._load(pkey)
            old_rows = p.rows
            old_outs = p.outs if p.outs is not None else \
                (self._compute(p) if old_rows else [])
            # apply deltas to the sorted row list
            import bisect
            keys, rows = list(p.keys), list(p.rows)
            for sk, row, is_ins in deltas:
                at = bisect.bisect_left(keys, sk)
                if is_ins:
                    keys.insert(at, sk)
                    rows.insert(at, row)
                elif at < len(keys) and keys[at] == sk:
                    keys.pop(at)
                    rows.pop(at)
                # else: delete of unseen row (inconsistent op) — skip
            p.keys, p.rows = keys, rows
            new_outs = self._compute(p)
            p.outs = new_outs
            # diff: old (row, outs) vs new (row, outs) as multisets
            # keyed by input pk — emit D/I for rows added/removed and
            # U-/U+ for rows whose window outputs changed
            old_map = {}
            for j, r in enumerate(old_rows):
                o = tuple(
                    (None if not old_outs[c][1][j]
                     else _pyval(old_outs[c][0][j]))
                    for c in range(len(self.calls)))
                old_map[tuple(r[i] for i in self.input_pk)] = (r, o)
            for j, r in enumerate(p.rows):
                o = tuple(
                    (None if not new_outs[c][1][j]
                     else _pyval(new_outs[c][0][j]))
                    for c in range(len(self.calls)))
                k = tuple(r[i] for i in self.input_pk)
                old = old_map.pop(k, None)
                if old is None:
                    inss.append((int(Op.INSERT), r + o))
                elif old[1] != o or old[0] != r:
                    dels.append((int(Op.DELETE), old[0] + old[1]))
                    inss.append((int(Op.INSERT), r + o))
            for r, o in old_map.values():
                dels.append((int(Op.DELETE), r + o))
        self._delta.clear()
        return self._build_chunks(dels + inss)

    def _build_chunks(self, records) -> List[StreamChunk]:
        out = []
        for at in range(0, len(records), MAX_OUT_CHUNK):
            batch = records[at:at + MAX_OUT_CHUNK]
            t = len(batch)
            cap = next_pow2(t)
            cols = []
            for i, f in enumerate(self.schema):
                dt = f.data_type
                vals = [r[i] for _op, r in batch]
                ok = np.ones(cap, dtype=bool)
                ok[:t] = [v is not None for v in vals]
                if dt.is_device:
                    arr = np.zeros(cap, dtype=dt.np_dtype)
                    arr[:t] = [0 if v is None else v for v in vals]
                else:
                    arr = np.empty(cap, dtype=object)
                    arr[:t] = vals
                cols.append(Column(dt, arr, None if ok.all() else ok))
            ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
            ops[:t] = [op for op, _r in batch]
            vis = np.zeros(cap, dtype=bool)
            vis[:t] = True
            out.append(StreamChunk(self.schema, cols, vis, ops))
        return out

    # -- main loop --------------------------------------------------------
    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first), f"expected init barrier, got {first!r}"
        self.state.init_epoch(first.epoch)
        yield first
        async for msg in it:
            if is_chunk(msg):
                self._buffer_chunk(msg)
            elif is_barrier(msg):
                for out in self._flush():
                    yield out
                self.state.commit(msg.epoch)
                yield msg
            # watermarks are dropped: windows over ordered history have
            # no per-column monotonicity to forward (reference behavior
            # for over-window is also conservative)


def _pyval(x):
    return x.item() if hasattr(x, "item") else x
