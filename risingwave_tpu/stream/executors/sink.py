"""SinkExecutor: deliver changelog streams to external systems.

Reference parity: src/stream/src/executor/sink.rs:39 + the Sink/
SinkWriter trait pair (src/connector/src/sink/mod.rs:156,171) and the
in-memory log-store decoupling (common/log_store/mod.rs) — collapsed:
the executor buffers deltas and hands them to the writer at CHECKPOINT
barriers only (`begin_epoch → write_batch* → commit(epoch)`), mirroring
sink.rs's `flush_current_epoch(.., is_checkpoint)`: non-checkpoint
epochs are not durable upstream, so committing them would write data a
crash can silently re-emit under fresh epochs. Committing only what is
checkpointed keeps the external system in lockstep with the recovery
point (at-least-once overall; the dedup window is one checkpoint).

Writers here: BlackholeSink (perf/testing), FileSink (newline-JSON
changelog with epoch markers), CollectSink (tests).
"""

from __future__ import annotations

import json
import os
from typing import AsyncIterator, List, Optional, Protocol, Tuple

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, StopMutation, is_barrier, is_chunk,
)


class SinkWriter(Protocol):
    """What the executor drives (sink/mod.rs:171 SinkWriter analog)."""

    def begin_epoch(self, epoch: int) -> None: ...

    def write_batch(self, records: List[Tuple[Op, tuple]]) -> None: ...

    def commit(self, epoch: int) -> None: ...


class BlackholeSink:
    """Swallow everything (sink/blackhole.rs analog); counts rows."""

    def __init__(self) -> None:
        self.rows = 0
        self.epochs = 0

    def begin_epoch(self, epoch: int) -> None:
        pass

    def write_batch(self, records) -> None:
        self.rows += len(records)

    def commit(self, epoch: int) -> None:
        self.epochs += 1


class CollectSink:
    """Test helper: keeps every committed record in memory."""

    def __init__(self) -> None:
        self.committed: List[Tuple[int, List[Tuple[Op, tuple]]]] = []
        self._pending: List[Tuple[Op, tuple]] = []
        self._epoch: Optional[int] = None

    def begin_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._pending = []

    def write_batch(self, records) -> None:
        self._pending.extend(records)

    def commit(self, epoch: int) -> None:
        self.committed.append((epoch, self._pending))
        self._pending = []


class FileSink:
    """Newline-JSON changelog with epoch frames.

    At-least-once: each commit appends a {"epoch": e} marker AFTER the
    epoch's records, and a replayed epoch ≤ the last marker is skipped —
    but epochs are wall-clock derived and NOT deterministic across
    restarts, so data re-emitted after a crash arrives under fresh
    (larger) epochs and is appended again. The duplicate window is
    bounded to one checkpoint because SinkExecutor only commits at
    checkpoint barriers; consumers needing exactly-once must dedup on a
    primary key."""

    def __init__(self, path: str):
        self.path = path
        self._buf: List[str] = []
        self._epoch: Optional[int] = None
        self._last_committed = 0
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if "epoch" in rec:
                        self._last_committed = max(
                            self._last_committed, rec["epoch"])

    def begin_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._buf = []

    def write_batch(self, records) -> None:
        if self._epoch is not None and \
                self._epoch <= self._last_committed:
            return                     # replayed epoch: drop
        for op, row in records:
            self._buf.append(json.dumps(
                {"op": op.name.lower(), "row": list(row)},
                default=str))

    def commit(self, epoch: int) -> None:
        if epoch <= self._last_committed:
            return
        with open(self.path, "a", encoding="utf-8") as f:
            for line in self._buf:
                f.write(line + "\n")
            f.write(json.dumps({"epoch": epoch}) + "\n")
        self._buf = []
        self._last_committed = epoch


class SinkExecutor(Executor):
    """Buffer deltas; flush through the writer at CHECKPOINT barriers.

    Non-checkpoint barriers only accumulate (sink.rs commits via
    flush_current_epoch(.., is_checkpoint)) — the external commit always
    corresponds to a durable recovery point."""

    def __init__(self, input_: Executor, writer: SinkWriter,
                 identity: str = "SinkExecutor",
                 state: Optional["StateTable"] = None):
        super().__init__(ExecutorInfo(
            input_.schema, list(input_.pk_indices), identity))
        self.input = input_
        self.writer = writer
        # schema-aware writers (FilelogSink field names) bind late:
        # the planner builds the writer before the chain exists
        if getattr(writer, "schema", "n/a") is None:
            writer.schema = input_.schema
        self._pending: List[Tuple[Op, tuple]] = []
        # durable stream-position counter (the sink coordinator's
        # epoch-log analog): committed with every checkpoint so a
        # restarted writer can reconcile what the EXTERNAL side
        # already has against what the replay will re-send — epoch
        # numbers are NOT stable across recovery, counts are (sources
        # replay deterministically from committed offsets)
        self.state = state
        self._count = 0

    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first)
        if self.state is not None:
            self.state.init_epoch(first.epoch)
            row = self.state.get_row((0,))
            self._count = int(row[1]) if row is not None else 0
            reconcile = getattr(self.writer, "reset_stream_position",
                                None)
            if reconcile is not None:
                reconcile(self._count,
                          claim=str(self.state.table_id))
        yield first
        async for msg in it:
            if is_chunk(msg):
                self._pending.extend(msg.to_records())
                yield msg
            elif is_barrier(msg):
                # a stop barrier ends this pipeline: flush even if the
                # scheduler made it a plain barrier, else the records
                # since the last checkpoint are dropped forever (no
                # recovery run will replay a graceful shutdown)
                stopping = isinstance(msg.mutation, StopMutation)
                if msg.kind.is_checkpoint or stopping:
                    # commit the epoch that just ENDED: its state is
                    # durable once this checkpoint completes upstream
                    epoch = msg.epoch.prev.value
                    self.writer.begin_epoch(epoch)
                    if self._pending:
                        self.writer.write_batch(self._pending)
                    self.writer.commit(epoch)
                    self._count += len(self._pending)
                    self._pending = []
                    if self.state is not None:
                        old = self.state.get_row((0,))
                        new = (0, self._count)
                        if old is None:
                            self.state.insert(new)
                        elif tuple(old) != new:
                            self.state.update(tuple(old), new)
                if self.state is not None:
                    # every barrier advances the table epoch (commit
                    # asserts continuity); only checkpoints buffered
                    # a counter write above
                    self.state.commit(msg.epoch)
                yield msg
            else:
                yield msg


def _jsonable(v):
    """Physical value → JSON-safe, recursively (Decimal → str).
    Bytes ride an explicit ``{"__b": hex}`` envelope — a bare hex
    string would be indistinguishable from a real string that merely
    looks like hex on the consuming side."""
    if isinstance(v, bytes):
        return {"__b": v.hex()}
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)                           # Decimal and friends


class CoordinatedSinkExecutor(Executor):
    """One of N exactly-once sink writers (ISSUE 20).

    Buffers the interval's delta records and, at every CHECKPOINT
    barrier, hands the payload off under ``epoch = msg.epoch.prev``
    (the epoch that just ended — the one this checkpoint makes
    durable upstream). Two handoff modes:

      deferred (in-process; ``coordinator`` set) — the payload goes
        to the engine's SinkCoordinator as a cheap list append;
        encoding + staging run in the checkpoint uploader's async
        tail, so the barrier path never carries serialization cost.
      inline (worker processes; no coordinator) — the writer stages
        synchronously BEFORE its barrier is collected, so the
        cross-process floor can only advance past durable staging;
        the meta-side coordinator commits manifests from the listing.

    The executor is deliberately STATELESS: visibility is manifest-
    existence, staged epochs above the recovery floor are truncated,
    and replayed rows re-stage under fresh epochs — exactly-once
    needs no durable counter here. A STOP that is not a checkpoint
    discards the buffer: those rows are not durable upstream, and a
    manifest may never outrun the checkpoint floor."""

    def __init__(self, input_: Executor, sink_name: str, encoder,
                 writer: int = 0, n_writers: int = 1,
                 coordinator=None):
        super().__init__(ExecutorInfo(
            input_.schema, list(input_.pk_indices),
            f"CoordinatedSink({sink_name})"))
        self.input = input_
        self.sink_name = sink_name
        self.encoder = encoder          # Append/UpsertSegmentSink
        self.writer = int(writer)
        self.n_writers = int(n_writers)
        self.coordinator = coordinator
        self._pending: List[Tuple[Op, tuple]] = []
        if getattr(encoder.target, "field_names", None) is None:
            encoder.target.field_names = [
                f.name for f in input_.schema]

    def _stage(self, epoch: int) -> None:
        from risingwave_tpu.meta.sink_coordinator import note_staged
        if not self._pending:
            return
        if self.coordinator is not None:
            self.coordinator.submit(self.sink_name, epoch,
                                    self.writer, self._pending)
        else:
            handle = self.encoder.stage(epoch, self.writer,
                                        self._pending)
            note_staged(self.sink_name, self.encoder.mode,
                        handle["rows"], handle["bytes"])
        self._pending = []

    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first)
        yield first
        async for msg in it:
            if is_chunk(msg):
                self._pending.extend(msg.to_records())
            elif is_barrier(msg):
                if msg.kind.is_checkpoint:
                    # the epoch that just ENDED: durable upstream once
                    # this checkpoint commits — the manifest follows
                    # the floor, never leads it
                    self._stage(msg.epoch.prev.value)
                elif isinstance(msg.mutation, StopMutation):
                    # graceful stop without a checkpoint: the buffer
                    # is not durable upstream; committing it would let
                    # a manifest outrun the floor (rescale stops use
                    # force_checkpoint and never reach this branch)
                    self._pending = []
            yield msg


class FilelogSink:
    """EXACTLY-ONCE external sink: one immutable segment per
    checkpoint epoch, published by atomic rename.

    Reference parity: the coordinated/two-phase sink commit
    (src/connector/src/sink/mod.rs:156 + the sink coordinator's
    epoch-aligned commits). PREPARE writes the epoch's records to a
    staging file; COMMIT is one atomic rename to
    ``<topic>-<part>.seg-<epoch>.log``.

    Exactly-once rests on STREAM POSITIONS, not epoch numbers (epochs
    are not stable across recovery). Segments are NAMED by the stream
    position of their first record, so ordering is monotone by
    construction and the published total reads from the LAST segment
    alone (its start + its record count). The SinkExecutor checkpoints
    a durable record counter C and calls ``reset_stream_position(C)``
    on recovery; the sink silently drops the first P - C replayed
    records (P = published total) — the crash window between segment
    publication and the meta checkpoint can therefore never duplicate,
    and every published segment starts exactly where the previous one
    ended. Output is a segmented filelog topic for
    SegmentedFileLogReader (records carry ``__op`` so retractions
    survive the wire).
    """

    def __init__(self, path: str, topic: str, partition: int = 0,
                 schema: Optional[Schema] = None):
        from risingwave_tpu.connectors.filelog import (
            list_segments, segment_path,
        )
        self._segment_path = segment_path
        self._list_segments = list_segments
        self.path = path
        self.topic = topic
        self.partition = int(partition)
        self.schema = schema
        os.makedirs(path, exist_ok=True)
        self._staging: Optional[str] = None
        self._f = None
        self._epoch: Optional[int] = None
        self._rows_in_epoch = 0
        self._skip = 0
        # orphaned staging files from a crash mid-prepare are garbage
        # (never published): sweep them at construction
        for name in os.listdir(path):
            if name.startswith(f".{topic}-{self.partition}.staging-"):
                os.unlink(os.path.join(path, name))
        self._published = self._published_total()

    def _published_total(self) -> int:
        """Stream position after the last published record — O(one
        segment): the name carries the start, only its lines count."""
        segs = self._list_segments(self.path, self.topic,
                                   self.partition)
        if not segs:
            return 0
        last = segs[-1]
        start = int(os.path.basename(last).rsplit("seg-", 1)[1]
                    .split(".")[0], 16)
        with open(last, "rb") as f:
            n = sum(1 for line in f if line.endswith(b"\n"))
        return start + n

    def reset_stream_position(self, committed_count: int,
                              claim: Optional[str] = None) -> None:
        """Recovery reconciliation: the replay resumes at stream
        position `committed_count`; the first P - committed_count
        incoming records are already published.

        `claim` disambiguates the one case (C=0, P>0) that positions
        alone cannot: a crash between the FIRST segment publish and
        the first counter checkpoint looks identical to a fresh sink
        pointed at another sink's topic. The claim token (the sink's
        state-table id — stable across recovery, fresh per CREATE
        SINK) is written beside the topic on first contact; a
        mismatch refuses the topic instead of silently skipping or
        duplicating."""
        if claim is not None:
            cpath = os.path.join(
                self.path, f".{self.topic}-{self.partition}.claim")
            if os.path.exists(cpath):
                holder = open(cpath).read().strip()
                if holder != str(claim):
                    raise ValueError(
                        f"topic {self.topic!r} is claimed by sink "
                        f"{holder!r} (this sink: {claim!r}) — use a "
                        "fresh topic directory")
            else:
                if self._published > 0:
                    raise ValueError(
                        f"topic {self.topic!r} already holds "
                        f"{self._published} unclaimed records — "
                        "refusing to silently skip or duplicate")
                tmp = cpath + ".tmp"
                with open(tmp, "w") as f:
                    f.write(str(claim))
                os.replace(tmp, cpath)
        self._skip = max(0, self._published - committed_count)

    def begin_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._rows_in_epoch = 0
        self._staging = None
        self._f = None               # lazily opened on first write

    def _ensure_staging(self):
        if self._f is None:
            self._staging = os.path.join(
                self.path,
                f".{self.topic}-{self.partition}"
                f".staging-{self._published:016x}")
            self._f = open(self._staging, "wb")
        return self._f

    def write_batch(self, records) -> None:
        names = [f.name for f in self.schema] if self.schema else None
        if self._skip:
            take = records[self._skip:]
            self._skip -= len(records) - len(take)
            records = take
        if not records:
            return
        f = self._ensure_staging()
        for op, row in records:
            obj = {"__op": "I" if op.is_insert else "D"}
            for i, v in enumerate(row):
                obj[names[i] if names else f"f{i}"] = _jsonable(v)
            f.write(json.dumps(obj).encode() + b"\n")
            self._rows_in_epoch += 1

    def commit(self, epoch: int) -> None:
        assert epoch == self._epoch
        if self._f is None:
            return                   # empty epoch: nothing staged
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        # the segment is NAMED by its start position: every published
        # segment begins exactly where the previous ended (the skip
        # reconciliation guarantees it), so a collision here can only
        # mean a duplicate publisher — fail loudly, never overwrite
        target = self._segment_path(self.path, self.topic,
                                    self.partition, self._published)
        if os.path.exists(target):
            os.unlink(self._staging)
            raise RuntimeError(
                f"segment {target} already exists — two sinks are "
                "publishing to one topic partition")
        os.replace(self._staging, target)       # atomic publish
        self._published += self._rows_in_epoch
        self._staging = None
