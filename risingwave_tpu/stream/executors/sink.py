"""SinkExecutor: deliver changelog streams to external systems.

Reference parity: src/stream/src/executor/sink.rs:39 + the Sink/
SinkWriter trait pair (src/connector/src/sink/mod.rs:156,171) and the
in-memory log-store decoupling (common/log_store/mod.rs) — collapsed:
the executor buffers deltas and hands them to the writer at CHECKPOINT
barriers only (`begin_epoch → write_batch* → commit(epoch)`), mirroring
sink.rs's `flush_current_epoch(.., is_checkpoint)`: non-checkpoint
epochs are not durable upstream, so committing them would write data a
crash can silently re-emit under fresh epochs. Committing only what is
checkpointed keeps the external system in lockstep with the recovery
point (at-least-once overall; the dedup window is one checkpoint).

Writers here: BlackholeSink (perf/testing), FileSink (newline-JSON
changelog with epoch markers), CollectSink (tests).
"""

from __future__ import annotations

import json
import os
from typing import AsyncIterator, List, Optional, Protocol, Tuple

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, StopMutation, is_barrier, is_chunk,
)


class SinkWriter(Protocol):
    """What the executor drives (sink/mod.rs:171 SinkWriter analog)."""

    def begin_epoch(self, epoch: int) -> None: ...

    def write_batch(self, records: List[Tuple[Op, tuple]]) -> None: ...

    def commit(self, epoch: int) -> None: ...


class BlackholeSink:
    """Swallow everything (sink/blackhole.rs analog); counts rows."""

    def __init__(self) -> None:
        self.rows = 0
        self.epochs = 0

    def begin_epoch(self, epoch: int) -> None:
        pass

    def write_batch(self, records) -> None:
        self.rows += len(records)

    def commit(self, epoch: int) -> None:
        self.epochs += 1


class CollectSink:
    """Test helper: keeps every committed record in memory."""

    def __init__(self) -> None:
        self.committed: List[Tuple[int, List[Tuple[Op, tuple]]]] = []
        self._pending: List[Tuple[Op, tuple]] = []
        self._epoch: Optional[int] = None

    def begin_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._pending = []

    def write_batch(self, records) -> None:
        self._pending.extend(records)

    def commit(self, epoch: int) -> None:
        self.committed.append((epoch, self._pending))
        self._pending = []


class FileSink:
    """Newline-JSON changelog with epoch frames.

    At-least-once: each commit appends a {"epoch": e} marker AFTER the
    epoch's records, and a replayed epoch ≤ the last marker is skipped —
    but epochs are wall-clock derived and NOT deterministic across
    restarts, so data re-emitted after a crash arrives under fresh
    (larger) epochs and is appended again. The duplicate window is
    bounded to one checkpoint because SinkExecutor only commits at
    checkpoint barriers; consumers needing exactly-once must dedup on a
    primary key."""

    def __init__(self, path: str):
        self.path = path
        self._buf: List[str] = []
        self._epoch: Optional[int] = None
        self._last_committed = 0
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if "epoch" in rec:
                        self._last_committed = max(
                            self._last_committed, rec["epoch"])

    def begin_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._buf = []

    def write_batch(self, records) -> None:
        if self._epoch is not None and \
                self._epoch <= self._last_committed:
            return                     # replayed epoch: drop
        for op, row in records:
            self._buf.append(json.dumps(
                {"op": op.name.lower(), "row": list(row)},
                default=str))

    def commit(self, epoch: int) -> None:
        if epoch <= self._last_committed:
            return
        with open(self.path, "a", encoding="utf-8") as f:
            for line in self._buf:
                f.write(line + "\n")
            f.write(json.dumps({"epoch": epoch}) + "\n")
        self._buf = []
        self._last_committed = epoch


class SinkExecutor(Executor):
    """Buffer deltas; flush through the writer at CHECKPOINT barriers.

    Non-checkpoint barriers only accumulate (sink.rs commits via
    flush_current_epoch(.., is_checkpoint)) — the external commit always
    corresponds to a durable recovery point."""

    def __init__(self, input_: Executor, writer: SinkWriter,
                 identity: str = "SinkExecutor"):
        super().__init__(ExecutorInfo(
            input_.schema, list(input_.pk_indices), identity))
        self.input = input_
        self.writer = writer
        self._pending: List[Tuple[Op, tuple]] = []

    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first)
        yield first
        async for msg in it:
            if is_chunk(msg):
                self._pending.extend(msg.to_records())
                yield msg
            elif is_barrier(msg):
                # a stop barrier ends this pipeline: flush even if the
                # scheduler made it a plain barrier, else the records
                # since the last checkpoint are dropped forever (no
                # recovery run will replay a graceful shutdown)
                stopping = isinstance(msg.mutation, StopMutation)
                if msg.kind.is_checkpoint or stopping:
                    # commit the epoch that just ENDED: its state is
                    # durable once this checkpoint completes upstream
                    epoch = msg.epoch.prev.value
                    self.writer.begin_epoch(epoch)
                    if self._pending:
                        self.writer.write_batch(self._pending)
                    self.writer.commit(epoch)
                    self._pending = []
                yield msg
            else:
                yield msg
