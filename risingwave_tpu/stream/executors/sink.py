"""SinkExecutor: deliver changelog streams to external systems.

Reference parity: src/stream/src/executor/sink.rs:39 + the Sink/
SinkWriter trait pair (src/connector/src/sink/mod.rs:156,171) and the
in-memory log-store decoupling (common/log_store/mod.rs) — collapsed:
the executor buffers the epoch's deltas and hands them to the writer at
every barrier (`begin_epoch → write_batch* → commit(epoch)`), so a sink
that talks to a slow external system naturally batches per epoch and a
crash replays from the last committed epoch (at-least-once; writers
that record the epoch get exactly-once dedup).

Writers here: BlackholeSink (perf/testing), FileSink (newline-JSON
changelog with epoch markers; idempotent replay via the epoch header),
CollectSink (tests).
"""

from __future__ import annotations

import json
import os
from typing import AsyncIterator, List, Optional, Protocol, Tuple

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, is_barrier, is_chunk,
)


class SinkWriter(Protocol):
    """What the executor drives (sink/mod.rs:171 SinkWriter analog)."""

    def begin_epoch(self, epoch: int) -> None: ...

    def write_batch(self, records: List[Tuple[Op, tuple]]) -> None: ...

    def commit(self, epoch: int) -> None: ...


class BlackholeSink:
    """Swallow everything (sink/blackhole.rs analog); counts rows."""

    def __init__(self) -> None:
        self.rows = 0
        self.epochs = 0

    def begin_epoch(self, epoch: int) -> None:
        pass

    def write_batch(self, records) -> None:
        self.rows += len(records)

    def commit(self, epoch: int) -> None:
        self.epochs += 1


class CollectSink:
    """Test helper: keeps every committed record in memory."""

    def __init__(self) -> None:
        self.committed: List[Tuple[int, List[Tuple[Op, tuple]]]] = []
        self._pending: List[Tuple[Op, tuple]] = []
        self._epoch: Optional[int] = None

    def begin_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._pending = []

    def write_batch(self, records) -> None:
        self._pending.extend(records)

    def commit(self, epoch: int) -> None:
        self.committed.append((epoch, self._pending))
        self._pending = []


class FileSink:
    """Newline-JSON changelog with epoch frames.

    Replay-safe: each commit appends a {"epoch": e} marker AFTER the
    epoch's records; a restarted pipeline re-emitting an epoch ≤ the
    last marker is skipped (exactly-once against the file)."""

    def __init__(self, path: str):
        self.path = path
        self._buf: List[str] = []
        self._epoch: Optional[int] = None
        self._last_committed = 0
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if "epoch" in rec:
                        self._last_committed = max(
                            self._last_committed, rec["epoch"])

    def begin_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._buf = []

    def write_batch(self, records) -> None:
        if self._epoch is not None and \
                self._epoch <= self._last_committed:
            return                     # replayed epoch: drop
        for op, row in records:
            self._buf.append(json.dumps(
                {"op": op.name.lower(), "row": list(row)},
                default=str))

    def commit(self, epoch: int) -> None:
        if epoch <= self._last_committed:
            return
        with open(self.path, "a", encoding="utf-8") as f:
            for line in self._buf:
                f.write(line + "\n")
            f.write(json.dumps({"epoch": epoch}) + "\n")
        self._buf = []
        self._last_committed = epoch


class SinkExecutor(Executor):
    """Buffer deltas per epoch; flush through the writer at barriers."""

    def __init__(self, input_: Executor, writer: SinkWriter,
                 identity: str = "SinkExecutor"):
        super().__init__(ExecutorInfo(
            input_.schema, list(input_.pk_indices), identity))
        self.input = input_
        self.writer = writer

    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first)
        self.writer.begin_epoch(first.epoch.curr.value)
        yield first
        async for msg in it:
            if is_chunk(msg):
                self.writer.write_batch(msg.to_records())
                yield msg
            elif is_barrier(msg):
                # commit the epoch that just ENDED (its data is durable
                # once this barrier's state commits upstream)
                self.writer.commit(msg.epoch.prev.value)
                self.writer.begin_epoch(msg.epoch.curr.value)
                yield msg
            else:
                yield msg
