"""SourceExecutor: turn a split reader into a barrier-respecting stream.

Reference parity: src/stream/src/executor/source/source_executor.rs:42 —
the barrier-select loop (:358-428): between two barriers the executor pulls
data from its reader; an arriving barrier always wins the select, so barrier
latency is bounded by one chunk's generation time. Split offsets persist in
a split-state table (source/state_table_handler.rs) at every checkpoint so
recovery resumes exactly where the committed epoch left off.

TPU notes: readers produce whole vectorized chunks (see connectors/), so the
per-message Python overhead is O(chunks), not O(rows). Pause/Resume
mutations gate generation without blocking barrier flow.
"""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator, Optional, Protocol

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.exchange import ChannelClosed, Receiver
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Barrier, Message, SourceChangeSplitMutation, is_barrier,
)
from risingwave_tpu.utils.metrics import STREAMING as _METRICS


class SplitReader(Protocol):
    """What a source executor needs from any connector reader."""

    split_id: str
    offset: int
    schema: object

    def seek(self, offset: int) -> None: ...

    def next_chunk(self) -> Optional[StreamChunk]: ...


SPLIT_STATE_PK = [0]  # split_id


class SourceExecutor(Executor):
    """Drives one split reader; barriers arrive via an injected channel."""

    def __init__(self, reader: SplitReader, barrier_rx: Receiver,
                 split_state: Optional[StateTable] = None,
                 actor_id: int = 0,
                 rate_limit_chunks_per_barrier: Optional[int] = None,
                 min_chunks_per_barrier: Optional[int] = None,
                 identity: str = "SourceExecutor",
                 freshness_key: Optional[str] = None):
        info = ExecutorInfo(reader.schema, [], identity)
        super().__init__(info)
        self.reader = reader
        self.barrier_rx = barrier_rx
        self.split_state = split_state
        self.actor_id = actor_id
        # freshness accounting key (stream/freshness.py): the SOURCE
        # name MVs register against (planner passes the catalog name;
        # hand-built pipelines default to the reader's split id), plus
        # the event-time column the ingest high-watermark reads
        from risingwave_tpu.stream.freshness import event_time_index
        self.freshness_key = freshness_key or getattr(
            reader, "split_id", identity)
        self._event_ts_idx = event_time_index(reader.schema)
        # optional throttle: max chunks generated per barrier interval
        # (FlowControlExecutor analog, keeps tests/bench deterministic)
        self.rate_limit = rate_limit_chunks_per_barrier
        # optional floor: generate this many chunks per epoch BEFORE
        # letting a waiting barrier win the select. The reference's
        # "barrier always wins" rule assumes barriers arrive on a wall
        # interval; under back-to-back injection (bench/test driving) it
        # starves epochs down to one chunk. The floor restores real
        # epoch sizes deterministically. None = reference behavior.
        self.min_chunks = min_chunks_per_barrier
        self.paused = False
        # cumulative wall time parked on the barrier channel with
        # nothing to generate. The monitor subtracts this from the
        # source's exclusive busy time: a source waiting out a slow
        # downstream epoch is IDLE, and counting the park as busy
        # would crown every source the straggler (trace diagnosis)
        self.idle_wait_s = 0.0

    # -- split-state persistence (state_table_handler.rs analog) --------
    def _recover_offset(self) -> None:
        if self.split_state is None:
            return
        splits = getattr(self.reader, "splits", None)
        if splits is not None:
            # multi-split reader (split rebalancing, ISSUE 15): one
            # durable row PER split — after a rescale moved this
            # split's row into our namespace, the byte offset resumes
            # exactly where the previous owner checkpointed
            for split_id, _off in splits():
                row = self.split_state.get_row((split_id,))
                if row is not None:
                    self.reader.seek_split(split_id, row[1])
            return
        row = self.split_state.get_row((self.reader.split_id,))
        if row is not None:
            self.reader.seek(row[1])

    def _persist_one(self, split_id: str, offset: int) -> None:
        row = (split_id, offset)
        old = self.split_state.get_row((split_id,))
        if old is None:
            self.split_state.insert(row)
        elif tuple(old) != row:
            self.split_state.update(old, row)

    def _persist_offset(self) -> None:
        if self.split_state is None:
            return
        splits = getattr(self.reader, "splits", None)
        if splits is not None:
            for split_id, off in splits():
                self._persist_one(split_id, off)
            return
        self._persist_one(self.reader.split_id, self.reader.offset)

    def _handle_barrier(self, barrier: Barrier) -> None:
        if barrier.is_pause():
            self.paused = True
        elif barrier.is_resume():
            self.paused = False
        m = barrier.mutation
        if isinstance(m, SourceChangeSplitMutation) and \
                self.actor_id in m.assignments:
            # v0: single split per actor; reassignment seeks it
            pass
        self._persist_offset()
        if self.split_state is not None:
            self.split_state.commit(barrier.epoch)
        # epoch frontier: everything ingested so far precedes this
        # barrier — the hwm recorded here IS the MV-visible event
        # frontier once materialize passes the same barrier
        from risingwave_tpu.stream.freshness import FRESHNESS
        FRESHNESS.note_source_barrier(self.freshness_key,
                                      barrier.epoch.curr.value)

    async def execute(self) -> AsyncIterator[Message]:
        # (barrier_rx teardown lives in Actor.run's close_receivers —
        # the owning actor's exit point, which runs deterministically
        # instead of waiting on async-generator finalization)
        # protocol: first message is the init barrier (source_executor.rs
        # waits for the first barrier before opening the reader)
        t0 = time.monotonic()
        first = await self.barrier_rx.recv()
        self.idle_wait_s += time.monotonic() - t0
        assert is_barrier(first), f"source got {first!r} before init barrier"
        if self.split_state is not None:
            self.split_state.init_epoch(first.epoch)
        self._recover_offset()
        from risingwave_tpu.stream.freshness import FRESHNESS
        FRESHNESS.note_source_barrier(self.freshness_key,
                                      first.epoch.curr.value)
        self.paused = first.is_pause()
        yield first
        if first.is_stop(self.actor_id):
            return

        exhausted = False
        idle = False
        chunks_this_epoch = 0
        while True:
            # barrier wins the select — except for the FIRST chunk of an
            # epoch, which is generated before looking at the channel.
            # Without that progress guarantee, back-to-back barrier
            # injection (collect → inject with no interval, the test/bench
            # driving pattern) can starve the stream forever: every
            # try_recv finds the next barrier already waiting.
            barrier: Optional[Barrier] = None
            can_generate = not (self.paused or exhausted or idle or (
                self.rate_limit is not None
                and chunks_this_epoch >= self.rate_limit))
            if not can_generate:
                t0 = time.monotonic()
                try:
                    barrier = await self.barrier_rx.recv()  # blocking
                except ChannelClosed:
                    return
                finally:
                    self.idle_wait_s += time.monotonic() - t0
            elif chunks_this_epoch > 0 and (
                    self.min_chunks is None
                    or chunks_this_epoch >= self.min_chunks):
                try:
                    barrier = self.barrier_rx.try_recv()
                except ChannelClosed:
                    return
            if barrier is not None:
                assert is_barrier(barrier)
                self._handle_barrier(barrier)
                chunks_this_epoch = 0
                idle = False            # log sources re-poll per epoch
                yield barrier
                if barrier.is_stop(self.actor_id):
                    return
                continue
            chunk = self.reader.next_chunk()
            if chunk is None:
                if getattr(self.reader, "unbounded", False):
                    # log-style source with no complete records yet:
                    # park on the barrier channel (not a busy-poll)
                    idle = True
                else:
                    exhausted = True
                continue
            chunks_this_epoch += 1
            _METRICS.source_rows.inc(chunk.cardinality(),
                                     source=self.reader.split_id)
            from risingwave_tpu.stream import freshness as _fresh
            if _fresh.enabled():
                # ingest high-watermark: one vectorized max over the
                # chunk's event-time column (arrival-clock fallback
                # when the schema has none)
                _fresh.FRESHNESS.note_ingest(
                    self.freshness_key,
                    _fresh.chunk_event_hwm(chunk, self._event_ts_idx))
            yield chunk
            # yield to the event loop so the barrier injector can run
            await asyncio.sleep(0)
