"""Test scaffolding: MockSource.

Reference parity: src/stream/src/executor/test_utils.rs:46 — `MockSource`
feeds hand-built chunks/barriers into an executor chain; every reference
executor test is written against it, and ours are too (SURVEY §4 lesson:
executor-level tests = MockSource + MemoryStateStore fake).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Iterable, List, Optional

from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.exchange import ChannelClosed, Receiver, channel_for_test
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, is_barrier


class MockSource(Executor):
    """Replays a scripted message list, or drains a channel if fed live."""

    def __init__(self, schema: Schema, messages: Iterable[Message] = (),
                 pk_indices: Optional[List[int]] = None,
                 stop_after_script: bool = True):
        super().__init__(ExecutorInfo(schema, pk_indices or [], "MockSource"))
        self.messages = list(messages)
        self.stop_after_script = stop_after_script
        self._tx, self._rx = channel_for_test()

    @staticmethod
    def channel(schema: Schema, pk_indices: Optional[List[int]] = None):
        """(sender, MockSource) pair for driving a chain interactively."""
        src = MockSource(schema, [], pk_indices, stop_after_script=False)
        return src._tx, src

    async def execute(self) -> AsyncIterator[Message]:
        for msg in self.messages:
            yield msg
        if self.stop_after_script:
            return
        while True:
            try:
                msg = await self._rx.recv()
            except ChannelClosed:
                return
            yield msg


async def collect_until_n_barriers(executor: Executor, n: int
                                   ) -> List[Message]:
    """Drive an executor until `n` barriers have been observed."""
    out: List[Message] = []
    seen = 0
    async for msg in executor.execute():
        out.append(msg)
        if is_barrier(msg):
            seen += 1
            if seen >= n:
                break
    return out
