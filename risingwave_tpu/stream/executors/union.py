"""UnionExecutor: merge N same-schema inputs into one aligned stream.

Reference parity: src/stream/src/executor/union.rs:29 (UnionExecutor —
`merge` over child executors with barrier alignment) with watermark
handling per super::watermark::BufferedWatermarks (min across inputs,
monotonic). Unlike MergeExecutor (which merges exchange *channels*),
Union composes child *executors* in the same actor.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Sequence

from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.merge import _WatermarkAligner, barrier_align_n
from risingwave_tpu.stream.message import Message, Watermark


class UnionExecutor(Executor):
    """Merge N upstream executors (union.rs:29 analog)."""

    def __init__(self, inputs: Sequence[Executor],
                 pk_indices: Sequence[int] = ()):
        assert inputs, "UnionExecutor needs at least one input"
        schema = inputs[0].schema
        for e in inputs[1:]:
            assert [f.data_type for f in e.schema] == \
                [f.data_type for f in schema], \
                f"union schema mismatch: {e.schema!r} vs {schema!r}"
        super().__init__(ExecutorInfo(schema, list(pk_indices),
                                      "UnionExecutor"))
        self.inputs = list(inputs)

    async def execute(self) -> AsyncIterator[Message]:
        n = len(self.inputs)
        wm = _WatermarkAligner(n)
        async for tag, msg in barrier_align_n(
                [e.execute() for e in self.inputs]):
            if tag == "barrier":
                yield msg
            elif isinstance(msg, Watermark):
                w = wm.update(tag, msg)
                if w is not None:
                    yield w
            else:
                yield msg
