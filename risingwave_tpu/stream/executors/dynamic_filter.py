"""DynamicFilterExecutor: filter a stream against a changing scalar.

Reference parity: src/stream/src/executor/dynamic_filter.rs:48 — left
input is the data stream, right input carries the single-row dynamic
bound (e.g. `WHERE v > (SELECT max(...) ...)`). Left rows are emitted
when they satisfy `left_col ⊙ bound` under the CURRENT bound; every
left row is kept in managed state, and when the bound moves at a
barrier the executor emits the transition delta — Inserts for stored
rows that newly satisfy, Deletes for rows that no longer do (the range
between old and new bound, one sorted-structure slice).

NULL semantics: left rows with NULL filter column never match; a NULL /
absent bound matches nothing (and retracts everything previously out).
"""

from __future__ import annotations

import bisect
from typing import AsyncIterator, Callable, List, Optional

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk
from risingwave_tpu.state.state_table import StateTable, to_logical_row
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.merge import barrier_align_2
from risingwave_tpu.stream.message import Message, is_barrier

_OPS: dict = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


class DynamicFilterExecutor(Executor):
    """`left_col ⊙ (single dynamic rhs value)` (dynamic_filter.rs:48)."""

    def __init__(self, left: Executor, right: Executor,
                 left_col: int, comparator: str,
                 left_state: StateTable):
        assert comparator in _OPS, comparator
        super().__init__(ExecutorInfo(
            left.schema, list(left.pk_indices),
            "DynamicFilterExecutor"))
        self.left_in, self.right_in = left, right
        self.left_col = left_col
        self.cmp_name = comparator
        self.cmp: Callable = _OPS[comparator]
        self.state = left_state
        self.bound = None          # applied bound (last barrier)
        self._pending_bound = None  # latest rhs value seen this epoch
        self._rows: List[tuple] = []   # sorted (value, row)

    # -- left state ------------------------------------------------------
    def _recover(self) -> None:
        for _pk, raw in self.state.iter_rows():
            row = to_logical_row(raw, self.schema)
            v = row[self.left_col]
            if v is not None:
                bisect.insort(self._rows, (v, row))

    def _passes(self, v) -> bool:
        return (v is not None and self.bound is not None
                and bool(self.cmp(v, self.bound)))

    # -- emission --------------------------------------------------------
    def _emit_chunk(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        """Rows of this chunk that satisfy the current bound."""
        if self.bound is None:
            return None
        c = chunk.columns[self.left_col]
        vals = np.asarray(c.values)
        vis = np.asarray(chunk.visibility)
        ok = vis if c.validity is None else vis & np.asarray(c.validity)
        sat = np.zeros(chunk.capacity, dtype=bool)
        idx = np.flatnonzero(ok)
        if len(idx):
            sat[idx] = [bool(self.cmp(v, self.bound))
                        for v in vals[idx].tolist()]
        new_vis = vis & sat
        if not new_vis.any():
            return None
        return StreamChunk(chunk.schema, chunk.columns, new_vis,
                           chunk.ops)

    def _pass_bounds(self, bound) -> tuple:
        """(start, end) slice of self._rows passing under `bound`."""
        n = len(self._rows)
        gt = self.cmp_name in (">", ">=")
        strict = self.cmp_name in (">", "<")
        if bound is None:
            return (n, n) if gt else (0, 0)
        vals_key = lambda e: e[0]           # noqa: E731
        if gt:    # v > bound (strict) / v >= bound
            s = (bisect.bisect_right(self._rows, bound, key=vals_key)
                 if strict else
                 bisect.bisect_left(self._rows, bound, key=vals_key))
            return (s, n)
        # v < bound (strict) / v <= bound
        e = (bisect.bisect_left(self._rows, bound, key=vals_key)
             if strict else
             bisect.bisect_right(self._rows, bound, key=vals_key))
        return (0, e)

    def _bound_transition(self) -> Optional[StreamChunk]:
        """Emit the delta when the bound moves: both pass-slices share an
        endpoint (gt shares end=n, lt shares start=0), so the symmetric
        difference is ONE contiguous slice — O(rows that change)."""
        old, new = self.bound, self._pending_bound
        if old == new:
            return None
        so, eo = self._pass_bounds(old)
        self.bound = new
        sn, en = self._pass_bounds(new)
        if (so, eo) == (sn, en):
            return None
        if self.cmp_name in (">", ">="):
            if sn > so:       # bound rose: rows[so:sn] stopped passing
                deletes = [r for _v, r in self._rows[so:sn]]
                inserts = []
            else:             # bound fell: rows[sn:so] started passing
                deletes = []
                inserts = [r for _v, r in self._rows[sn:so]]
        else:
            if en > eo:       # bound rose: rows[eo:en] started passing
                deletes = []
                inserts = [r for _v, r in self._rows[eo:en]]
            else:             # bound fell: rows[en:eo] stopped passing
                deletes = [r for _v, r in self._rows[en:eo]]
                inserts = []
        if not deletes and not inserts:
            return None
        return self._rows_chunk(deletes, inserts)

    def _rows_chunk(self, deletes, inserts) -> StreamChunk:
        rows = list(deletes) + list(inserts)
        ops = np.asarray([int(Op.DELETE)] * len(deletes)
                         + [int(Op.INSERT)] * len(inserts), dtype=np.int8)
        cols: List[Column] = []
        for j, f in enumerate(self.schema):
            vals_l = [r[j] for r in rows]
            okm = np.asarray([v is not None for v in vals_l])
            if f.data_type.is_device:
                vals = np.asarray([0 if v is None else v for v in vals_l],
                                  dtype=f.data_type.np_dtype)
            else:
                vals = np.asarray(vals_l, dtype=object)
            cols.append(Column(f.data_type, vals,
                               None if okm.all() else okm))
        return StreamChunk(self.schema, cols,
                           np.ones(len(rows), dtype=bool), ops)

    # -- main loop -------------------------------------------------------
    async def execute(self) -> AsyncIterator[Message]:
        lit = self.left_in.execute()
        rit = self.right_in.execute()
        first_l = await lit.__anext__()
        first_r = await rit.__anext__()
        assert is_barrier(first_l) and is_barrier(first_r)
        self.state.init_epoch(first_l.epoch)
        self._recover()
        yield first_l
        async for tag, msg in barrier_align_2(lit, rit):
            if tag == "barrier":
                out = self._bound_transition()
                if out is not None:
                    yield out
                self.state.commit(msg.epoch)
                yield msg
            elif tag == "left":
                if not isinstance(msg, StreamChunk):
                    continue
                out = self._emit_chunk(msg)
                if out is not None:
                    yield out
                for op, row in msg.to_records():
                    v = row[self.left_col]
                    if op.is_insert:
                        self.state.insert(row)
                        if v is not None:
                            bisect.insort(self._rows, (v, row))
                    else:
                        self.state.delete(row)
                        if v is not None:
                            i = bisect.bisect_left(self._rows, (v, row))
                            if i < len(self._rows) \
                                    and self._rows[i][1] == row:
                                del self._rows[i]
            elif tag == "right":
                if not isinstance(msg, StreamChunk):
                    continue
                for op, row in msg.to_records():
                    if op.is_insert:
                        self._pending_bound = row[0]
                    else:
                        if self._pending_bound == row[0]:
                            self._pending_bound = None
