"""Shared key-lane building: chunk columns → int32 device key lanes.

The device key contract (ops/hash_table.py): every key column becomes
three int32 lanes — (hi, lo) bijective split of a 64-bit image of the
value plus a null-indicator lane (NULL is a distinct key, matching the
reference's group/join key semantics). Used by HashAgg group keys and
HashJoin join keys; host twin of the dispatch hashing.

Varchar (and other host-typed) keys: the reference serializes them into
its HashKey bytes (src/common/src/hash/key.rs:312,647 KeySerialized) so
equality is exact. The TPU build cannot ship strings to HBM, so a
``KeyCodec`` INTERNS each distinct value to a dense int64 id — the id
lanes route/group on device exactly like native ints, and two distinct
strings can never merge (no hash-collision class at all). The interner
is per-operator host state, rebuilt on recovery from the state rows it
decodes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import DataType
from risingwave_tpu.ops import lanes

LANES_PER_KEY = 3


class Interner:
    """Exact value↔int64-id bijection for one host-typed key column.

    BOUNDED BY LIVE STATE, not by stream history (VERDICT r3 weak #6):
    ``gc(live_values)`` retires entries no live row references — ids
    stay STABLE for survivors (device rows store id lanes), retired
    ids go on a free list and are reused only after GC proves them
    unreferenced. Executors call gc at compaction/state-cleaning
    points, where the live value set is already in hand."""

    def __init__(self) -> None:
        self.to_id: Dict[object, int] = {}
        self.values: List[object] = []       # id → value (None = hole)
        self.free_ids: List[int] = []

    def __len__(self) -> int:
        return len(self.to_id)

    def nbytes(self) -> int:
        """Rough host-memory estimate (EstimateSize analog)."""
        data = sum(len(v) if isinstance(v, (str, bytes)) else 8
                   for v in self.to_id)
        return data + 120 * len(self.to_id) + 8 * len(self.values)

    def _alloc(self, v) -> int:
        if self.free_ids:
            i = self.free_ids.pop()
            self.values[i] = v
        else:
            i = len(self.values)
            self.values.append(v)
        self.to_id[v] = i
        return i

    def intern_col(self, vals: np.ndarray) -> np.ndarray:
        """object array → int64 ids (vectorized over DISTINCT values)."""
        uniq, inverse = np.unique(vals, return_inverse=True)
        ids = np.empty(len(uniq), dtype=np.int64)
        to_id = self.to_id
        for i, v in enumerate(uniq.tolist()):
            got = to_id.get(v)
            if got is None:
                got = self._alloc(v)
            ids[i] = got
        return ids[inverse]

    def intern_one(self, v) -> int:
        got = self.to_id.get(v)
        if got is None:
            got = self._alloc(v)
        return got

    def gc(self, live_values) -> int:
        """Drop entries not in `live_values`; returns entries freed."""
        live = set(live_values)
        dead = [v for v in self.to_id if v not in live]
        for v in dead:
            i = self.to_id.pop(v)
            self.values[i] = None
            self.free_ids.append(i)
        return len(dead)

    def lookup(self, ids: np.ndarray) -> np.ndarray:
        """id array → values. Unknown ids (NULL keys decode to id 0,
        which may not exist yet — e.g. a recovered interner whose rows
        were all NULL-keyed, ADVICE r3) map to None instead of raising."""
        out = np.empty(len(ids), dtype=object)
        vals = self.values
        n = len(vals)
        for i, x in enumerate(ids.tolist()):
            out[i] = vals[x] if 0 <= x < n else None
        return out


class KeyCodec:
    """Key-lane builder/decoder for a fixed key-column type list.

    Device-typed columns use the bijective i64 image; host-typed
    columns (varchar/bytea) go through a per-position Interner. A
    HashJoin shares ONE codec across both sides so equal strings get
    equal ids.
    """

    def __init__(self, types: Sequence[DataType]):
        self.types = list(types)
        self.interners: Dict[int, Interner] = {
            j: Interner() for j, dt in enumerate(self.types)
            if not dt.is_device}

    def interner_entries(self) -> int:
        return sum(len(it) for it in self.interners.values())

    def interner_nbytes(self) -> int:
        return sum(it.nbytes() for it in self.interners.values())

    def _col_i64(self, j: int, vals: np.ndarray) -> np.ndarray:
        it = self.interners.get(j)
        if it is None:
            return to_i64(vals)
        return it.intern_col(vals)

    def build(self, chunk: StreamChunk,
              indices: Sequence[int]) -> np.ndarray:
        cols = []
        for i in indices:
            c = chunk.columns[i]
            cols.append((np.asarray(c.values),
                         None if c.validity is None
                         else np.asarray(c.validity)))
        return self.build_arrays(cols)

    def build_with_mask(self, chunk: StreamChunk, indices: Sequence[int]
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """(lanes, all-keys-nonnull mask) in ONE pass — the mask falls
        out of the valid lanes the build already computes, so callers
        (the join hot path) don't re-scan host columns per row."""
        lanes_ = self.build(chunk, indices)
        nonnull = np.ones(lanes_.shape[0], dtype=bool)
        for j in range(len(self.types)):
            nonnull &= lanes_[:, LANES_PER_KEY * j + 2] != 0
        return lanes_, nonnull

    def build_arrays(self, cols: Sequence[Tuple[np.ndarray, np.ndarray]]
                     ) -> np.ndarray:
        n = len(cols[0][0])
        out = np.empty((n, LANES_PER_KEY * len(cols)), dtype=np.int32)
        for j, (vals, ok) in enumerate(cols):
            if j in self.interners:
                # Host-typed columns carry NULL as the None OBJECT, not
                # (only) a validity mask — and pad slots of a capacity-
                # padded chunk are arbitrary. Both must stay out of the
                # interner and read as null in the valid lane. The fill
                # must match the column's value type: np.unique sorts,
                # and str/bytes do not compare.
                bad = np.fromiter(
                    (not isinstance(v, (str, bytes))
                     for v in vals.tolist()), dtype=bool, count=n)
                ok = (~bad if ok is None else ok & ~bad)
                if bad.any():
                    vals = vals.copy()
                    vals[bad] = b"" if self.types[j] == DataType.BYTEA \
                        else ""
            v64 = self._col_i64(j, vals)
            if ok is not None:
                v64 = np.where(ok, v64, 0)
            hi, lo = lanes.split_i64(v64)
            out[:, LANES_PER_KEY * j] = hi
            out[:, LANES_PER_KEY * j + 1] = lo
            out[:, LANES_PER_KEY * j + 2] = \
                1 if ok is None else ok.astype(np.int32)
        return out

    def lanes_of_values(self, values: Sequence) -> np.ndarray:
        lane = np.zeros(LANES_PER_KEY * len(self.types), dtype=np.int32)
        for j, (v, dt) in enumerate(zip(values, self.types)):
            if v is None:
                continue
            it = self.interners.get(j)
            if it is not None:
                v64 = np.asarray([it.intern_one(v)], dtype=np.int64)
            else:
                v64 = to_i64(np.asarray([v], dtype=dt.np_dtype))
            hi, lo = lanes.split_i64(v64)
            lane[LANES_PER_KEY * j] = hi[0]
            lane[LANES_PER_KEY * j + 1] = lo[0]
            lane[LANES_PER_KEY * j + 2] = 1
        return lane

    def decode(self, keys: np.ndarray
               ) -> List[Tuple[np.ndarray, np.ndarray]]:
        cols = []
        for j, dt in enumerate(self.types):
            hi = keys[:, LANES_PER_KEY * j]
            lo = keys[:, LANES_PER_KEY * j + 1]
            ok = keys[:, LANES_PER_KEY * j + 2] != 0
            v64 = lanes.merge_i64(hi, lo)
            it = self.interners.get(j)
            if it is not None:
                vals = it.lookup(np.where(ok, v64, 0))
            elif np.issubdtype(np.dtype(dt.np_dtype), np.floating):
                vals = v64.view(np.float64).astype(dt.np_dtype)
            else:
                vals = v64.astype(dt.np_dtype)
            cols.append((vals, ok))
        return cols


def to_i64(vals: np.ndarray) -> np.ndarray:
    """Column values → int64, bijective per distinct key.

    Floats are bit-cast (1.2 and 1.7 are distinct keys) with -0.0
    normalized so it groups with 0.0. xp-generic (get_xp): the fused
    key-lane prelude traces this exact implementation under jit."""
    from risingwave_tpu.common.chunk import get_xp
    xp = get_xp(vals)
    if np.issubdtype(np.dtype(vals.dtype), np.floating):
        vals = xp.where(vals == 0, xp.zeros((), dtype=vals.dtype), vals)
        return vals.astype(xp.float64).view(xp.int64)
    return vals.astype(xp.int64)


