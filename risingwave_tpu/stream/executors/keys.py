"""Shared key-lane building: chunk columns → int32 device key lanes.

The device key contract (ops/hash_table.py): every key column becomes
three int32 lanes — (hi, lo) bijective split of a 64-bit image of the
value plus a null-indicator lane (NULL is a distinct key, matching the
reference's group/join key semantics). Used by HashAgg group keys and
HashJoin join keys; host twin of the dispatch hashing.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import DataType
from risingwave_tpu.ops import lanes

LANES_PER_KEY = 3


def to_i64(vals: np.ndarray) -> np.ndarray:
    """Column values → int64, bijective per distinct key.

    Floats are bit-cast (1.2 and 1.7 are distinct keys) with -0.0
    normalized so it groups with 0.0."""
    if np.issubdtype(vals.dtype, np.floating):
        vals = np.where(vals == 0, np.zeros((), dtype=vals.dtype), vals)
        return vals.astype(np.float64).view(np.int64)
    return vals.astype(np.int64)


def build_key_lanes(chunk: StreamChunk,
                    indices: Sequence[int]) -> np.ndarray:
    """int32[capacity, 3*len(indices)] key lanes for the device kernels."""
    cols = []
    for i in indices:
        c = chunk.columns[i]
        cols.append((np.asarray(c.values),
                     None if c.validity is None
                     else np.asarray(c.validity)))
    return build_key_lanes_arrays(cols)


def build_key_lanes_arrays(
        cols: Sequence[Tuple[np.ndarray, np.ndarray]]) -> np.ndarray:
    """(values, valid|None) pairs → int32[n, 3*len(cols)] key lanes."""
    n = len(cols[0][0])
    out = np.empty((n, LANES_PER_KEY * len(cols)), dtype=np.int32)
    for j, (vals, ok) in enumerate(cols):
        v64 = to_i64(vals)
        if ok is not None:
            v64 = np.where(ok, v64, 0)
        hi, lo = lanes.split_i64(v64)
        out[:, LANES_PER_KEY * j] = hi
        out[:, LANES_PER_KEY * j + 1] = lo
        out[:, LANES_PER_KEY * j + 2] = \
            1 if ok is None else ok.astype(np.int32)
    return out


def key_lanes_of_values(values: Sequence, types: Sequence[DataType]
                        ) -> np.ndarray:
    """One logical key tuple → int32[3*k] lanes (recovery path)."""
    lane = np.zeros(LANES_PER_KEY * len(types), dtype=np.int32)
    for j, (v, dt) in enumerate(zip(values, types)):
        if v is None:
            continue
        v64 = to_i64(np.asarray([v], dtype=dt.np_dtype))
        hi, lo = lanes.split_i64(v64)
        lane[LANES_PER_KEY * j] = hi[0]
        lane[LANES_PER_KEY * j + 1] = lo[0]
        lane[LANES_PER_KEY * j + 2] = 1
    return lane


def decode_key_lanes(keys: np.ndarray, types: Sequence[DataType]
                     ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Key-lane matrix → per key col (values in col dtype, valid mask)."""
    cols = []
    for j, dt in enumerate(types):
        hi = keys[:, LANES_PER_KEY * j]
        lo = keys[:, LANES_PER_KEY * j + 1]
        ok = keys[:, LANES_PER_KEY * j + 2] != 0
        v64 = lanes.merge_i64(hi, lo)
        if np.issubdtype(np.dtype(dt.np_dtype), np.floating):
            vals = v64.view(np.float64).astype(dt.np_dtype)
        else:
            vals = v64.astype(dt.np_dtype)
        cols.append((vals, ok))
    return cols
