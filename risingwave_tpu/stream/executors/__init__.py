"""Stream operator executors.

Each module mirrors one reference executor family
(src/stream/src/executor/*); see per-module docstrings for file:line parity.
"""

from risingwave_tpu.stream.executors.simple import (
    FilterExecutor, ProjectExecutor, ReceiverExecutor,
)
from risingwave_tpu.stream.executors.materialize import MaterializeExecutor
from risingwave_tpu.stream.executors.test_utils import MockSource

__all__ = [
    "FilterExecutor", "ProjectExecutor", "ReceiverExecutor",
    "MaterializeExecutor", "MockSource",
]
