"""SimpleAggExecutor and StatelessSimpleAggExecutor: single-group aggs.

Reference parity: src/stream/src/executor/simple_agg.rs:39 (global
single-row agg: always-one-group state, first flush emits Insert, later
flushes emit an update pair when dirty) and stateless_simple_agg.rs:21
(per-chunk partial aggregation, no state — the local half of two-phase
aggregation; its partials are merged by a downstream SimpleAgg with SUM
calls).

TPU notes: one group means no hash table — each chunk reduces with one
vectorized pass (sign-weighted sums / masked min-max) and the scalar
state lives on the host; exact integer sums use Python ints (no limb
arrays needed at cardinality 1). MIN/MAX require append-only input (same
materialized-input caveat as the hash kernel, hash_agg.py:36-39).
"""

from __future__ import annotations

from typing import AsyncIterator, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.executors.hash_agg import AggCall
from risingwave_tpu.stream.message import (
    Message, is_barrier, is_chunk, is_watermark,
)

_SUM_OUT = {
    DataType.INT16: DataType.INT64, DataType.INT32: DataType.INT64,
    DataType.INT64: DataType.INT64,
    DataType.FLOAT32: DataType.FLOAT64, DataType.FLOAT64: DataType.FLOAT64,
}


def simple_agg_out_field(call: AggCall, input_schema: Schema,
                         name: str) -> Field:
    if call.kind == AggKind.COUNT:
        return Field(name, DataType.INT64)
    in_dt = input_schema[call.input_idx].data_type
    if call.kind == AggKind.SUM:
        return Field(name, _SUM_OUT[in_dt])
    return Field(name, in_dt)    # MIN/MAX


class _ScalarAcc:
    """One agg call's host accumulator (exact, sign-aware)."""

    def __init__(self, call: AggCall, input_schema: Schema):
        self.call = call
        self.kind = call.kind
        self.count = 0          # non-null contributions (sign-weighted)
        self.value = None       # sum value / min-max value

    def apply(self, chunk: StreamChunk) -> None:
        vis = np.asarray(chunk.visibility)
        if not vis.any():
            return
        ops = np.asarray(chunk.ops)
        sign = np.where(
            (ops == int(Op.INSERT)) | (ops == int(Op.UPDATE_INSERT)),
            1, -1)
        if self.kind == AggKind.COUNT and self.call.input_idx is None:
            self.count += int(sign[vis].sum())
            return
        c = chunk.columns[self.call.input_idx]
        ok = vis if c.validity is None else vis & np.asarray(c.validity)
        if not ok.any():
            return
        vals = np.asarray(c.values)[ok]
        s = sign[ok]
        if self.kind == AggKind.COUNT:
            self.count += int(s.sum())
        elif self.kind == AggKind.SUM:
            self.count += int(s.sum())
            if np.issubdtype(vals.dtype, np.floating):
                d = float((vals * s).sum())
                self.value = d if self.value is None else self.value + d
            else:
                # exact: Python ints never wrap
                d = sum(int(v) * int(g) for v, g in zip(vals, s))
                self.value = d if self.value is None else self.value + d
        else:                     # MIN / MAX (append-only enforced above)
            if (s < 0).any():
                raise ValueError(
                    f"{self.kind.value} with retractions requires the "
                    "materialized-input path — append-only input only")
            self.count += int(len(vals))
            m = vals.max() if self.kind == AggKind.MAX else vals.min()
            m = m.item()
            if self.value is None:
                self.value = m
            elif self.kind == AggKind.MAX:
                self.value = max(self.value, m)
            else:
                self.value = min(self.value, m)

    def output(self):
        if self.kind == AggKind.COUNT:
            return self.count
        return self.value if self.count > 0 else None

    def partial_output(self):
        """Raw signed delta (stateless/two-phase local half): a sum of
        -5 over a retraction-only chunk must reach the merger as -5,
        not NULL — the count>0 NULL gate only applies to final output."""
        if self.kind == AggKind.COUNT:
            return self.count
        return self.value

    # -- persistence: (value_as_float_or_int, count) per call ------------
    def to_state(self) -> Tuple:
        return (self.output(), self.count)

    def restore(self, value, count: int) -> None:
        self.count = int(count)
        if self.kind == AggKind.COUNT:
            return
        self.value = value


def _acc_state_fields(calls: Sequence[AggCall], input_schema: Schema
                      ) -> List[Field]:
    out = []
    for i, call in enumerate(calls):
        out.append(simple_agg_out_field(call, input_schema, f"acc{i}"))
        out.append(Field(f"cnt{i}", DataType.INT64))
    return out


def simple_agg_state_schema(input_schema: Schema,
                            calls: Sequence[AggCall]
                            ) -> Tuple[Schema, List[int]]:
    """State-table schema for SimpleAgg: [pk] + (value, count) per call."""
    fields = [Field("pk", DataType.INT16)]
    fields.extend(_acc_state_fields(calls, input_schema))
    return Schema(fields), [0]


class SimpleAggExecutor(Executor):
    """Global single-row aggregation (simple_agg.rs:39 analog)."""

    def __init__(self, input_: Executor, calls: Sequence[AggCall],
                 state: StateTable,
                 output_names: Optional[Sequence[str]] = None,
                 append_only: bool = False):
        self.input = input_
        self.calls = list(calls)
        self.state = state
        self.append_only = append_only
        if not append_only and any(
                c.kind in (AggKind.MIN, AggKind.MAX) for c in self.calls):
            raise NotImplementedError(
                "MIN/MAX over retractable input needs the "
                "materialized-input path — pass append_only=True "
                "or use sum/count")
        names = list(output_names) if output_names else [
            f"agg{i}" for i in range(len(self.calls))]
        fields = [simple_agg_out_field(c, input_.schema, nm)
                  for c, nm in zip(self.calls, names)]
        super().__init__(ExecutorInfo(Schema(fields), [],
                                      "SimpleAggExecutor"))
        self.accs = [_ScalarAcc(c, input_.schema) for c in self.calls]
        self._last_row: Optional[Tuple] = None

    def _current_row(self) -> Tuple:
        return tuple(a.output() for a in self.accs)

    def _persist(self) -> None:
        row = (0,)
        for a in self.accs:
            v, cnt = a.to_state()
            row += (v, cnt)
        old = self.state.get_row((0,))
        if old is None:
            self.state.insert(row)
        elif tuple(old) != row:
            self.state.update(tuple(old), row)

    def _emit(self) -> Optional[StreamChunk]:
        row = self._current_row()
        if self._last_row is None:
            chunk = self._rows_chunk([(Op.INSERT, row)])
        elif row != self._last_row:
            chunk = self._rows_chunk([(Op.UPDATE_DELETE, self._last_row),
                                      (Op.UPDATE_INSERT, row)])
        else:
            return None
        self._last_row = row
        return chunk

    def _rows_chunk(self, rows) -> StreamChunk:
        n = len(rows)
        cols: List[Column] = []
        for j, f in enumerate(self.schema):
            vals_l = [r[1][j] for r in rows]
            ok = np.asarray([v is not None for v in vals_l])
            if f.data_type.is_device:
                vals = np.asarray(
                    [0 if v is None else v for v in vals_l],
                    dtype=f.data_type.np_dtype)
            else:
                vals = np.asarray(vals_l, dtype=object)
            cols.append(Column(f.data_type, vals,
                               None if ok.all() else ok))
        ops = np.asarray([int(r[0]) for r in rows], dtype=np.int8)
        return StreamChunk(self.schema, cols,
                           np.ones(n, dtype=bool), ops)

    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first)
        self.state.init_epoch(first.epoch)
        row = self.state.get_row((0,))
        if row is not None:
            for i, a in enumerate(self.accs):
                a.restore(row[1 + 2 * i], row[2 + 2 * i])
            self._last_row = self._current_row()
        yield first
        async for msg in it:
            if is_chunk(msg):
                for a in self.accs:
                    a.apply(msg)
            elif is_barrier(msg):
                out = self._emit()
                if out is not None:
                    yield out
                self._persist()
                self.state.commit(msg.epoch)
                yield msg
            elif is_watermark(msg):
                pass    # single group: input watermarks don't propagate


class StatelessSimpleAggExecutor(Executor):
    """Per-chunk partial aggregation (stateless_simple_agg.rs:21 analog).

    Emits one Insert row per non-empty chunk with that chunk's partial
    aggregates; a downstream SimpleAgg with SUM calls merges them
    (two-phase aggregation's local half)."""

    def __init__(self, input_: Executor, calls: Sequence[AggCall],
                 output_names: Optional[Sequence[str]] = None):
        self.input = input_
        self.calls = list(calls)
        names = list(output_names) if output_names else [
            f"agg{i}" for i in range(len(self.calls))]
        fields = [simple_agg_out_field(c, input_.schema, nm)
                  for c, nm in zip(self.calls, names)]
        super().__init__(ExecutorInfo(Schema(fields), [],
                                      "StatelessSimpleAggExecutor"))

    async def execute(self) -> AsyncIterator[Message]:
        async for msg in self.input.execute():
            if is_chunk(msg):
                if not np.asarray(msg.visibility).any():
                    continue
                accs = [_ScalarAcc(c, self.input.schema)
                        for c in self.calls]
                for a in accs:
                    a.apply(msg)
                row = tuple(a.partial_output() for a in accs)
                yield self._row_chunk(row)
            elif is_watermark(msg):
                pass
            else:
                yield msg

    def _row_chunk(self, row: Tuple) -> StreamChunk:
        cols: List[Column] = []
        for f, v in zip(self.schema, row):
            ok = None if v is not None else np.zeros(1, dtype=bool)
            if f.data_type.is_device:
                vals = np.asarray([0 if v is None else v],
                                  dtype=f.data_type.np_dtype)
            else:
                vals = np.asarray([v], dtype=object)
            cols.append(Column(f.data_type, vals, ok))
        return StreamChunk(self.schema, cols, np.ones(1, dtype=bool),
                           np.asarray([int(Op.INSERT)], dtype=np.int8))
