"""FusedFragmentExecutor: a filter/project run as ONE traced step.

Reference departure (TiLT, arxiv 2301.12030): the reference interprets
its operator graph — each executor a separate async stage; this
executor collapses a maximal fusable run (frontend/opt/fusion.py marks
them) into a single ``jax.jit`` program per chunk. Two deployment
shapes share the machinery (ops/fused.py):

- **standalone** (this executor): the run feeds a join input side,
  materialize, or any non-agg consumer. The chunk's referenced device
  columns enter one jitted chain step (filters + projection + noop-pair
  drop), host-typed passthrough columns ride around the trace, and the
  output materializes back to host numpy for the consumer. N vectorized
  host passes become one compiled program; semantics are bit-identical
  to the sequential executors (see FusedStages docstring).
- **agg-prelude** (stream/executors/hash_agg.py): the same composed run
  inlines INTO the agg kernel's jitted apply with donated state — no
  host materialization at all; this executor never appears, the
  HashAggExecutor absorbs the stages.

Watermarks and barriers are per-message host work and flow through the
composed derivation chain (FusedStages.derive_watermarks) exactly as
the sequential ProjectExecutors would have derived them.
"""

from __future__ import annotations

from typing import AsyncIterator, List

import numpy as np

from risingwave_tpu.common.chunk import Column, StreamChunk
from risingwave_tpu.ops.fused import FusedStages, build_chain_step
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, Watermark, is_barrier, is_chunk,
)


class FusedFragmentExecutor(Executor):
    """One jitted dataflow step for a fused filter/project run."""

    def __init__(self, input_: Executor, stages: FusedStages):
        self.input = input_
        self.fused_stages = stages
        assert len(stages.in_schema) == len(input_.schema), \
            "fused stage chain planned against a different input"
        info = ExecutorInfo(
            stages.out_schema, [],
            f"FusedFragmentExecutor[{stages.describe()}]")
        super().__init__(info)
        self._step = None            # lazy: plan-only processes must
        self._ref = list(stages.ref_cols)   # not init a JAX backend

    # MonitoredExecutor drains this at each barrier: per-LOGICAL-stage
    # row/chunk attribution inside the fused block
    def drain_stage_metrics(self):
        return self.fused_stages.drain_stage_metrics()

    def _run_step(self, chunk: StreamChunk):
        if self._step is None:
            self._step = build_chain_step(self.fused_stages)
        vals, oks = [], []
        for i in self._ref:
            c = chunk.columns[i]
            vals.append(np.asarray(c.values))
            oks.append(np.ones(chunk.capacity, dtype=bool)
                       if c.validity is None
                       else np.asarray(c.validity))
        # host passthrough columns bypass the trace, but the noop-pair
        # drop must still see their adjacent equality
        host_same = self.fused_stages.host_noop_eq(chunk)
        if host_same is None:
            host_same = np.ones(chunk.capacity, dtype=bool)
        # one jitted chain step per chunk IS a device dispatch — count
        # it (ISSUE 9 bench honesty: absorbing a run into a keyed
        # executor's epoch dispatches must show up as a drop here)
        from risingwave_tpu.utils.metrics import STREAMING
        card = float(chunk.cardinality())
        STREAMING.device_dispatch.inc(1, executor=self.identity)
        STREAMING.rows_per_dispatch.observe(card,
                                            executor=self.identity)
        from risingwave_tpu.stream.trace_ctx import dispatch_span
        with dispatch_span(self.identity, card):
            return self._step(tuple(vals), tuple(oks),
                              np.asarray(chunk.visibility),
                              np.asarray(chunk.ops), host_same)

    async def execute(self) -> AsyncIterator[Message]:
        fs = self.fused_stages
        out_schema = fs.out_schema
        wm_cols = set(fs.wm_time_cols())
        first_seen = False
        async for msg in self.input.execute():
            if is_chunk(msg):
                # synthetic runtime columns (absorbed row_id_gen ids,
                # watermark thresholds) append host-side and enter the
                # trace as ordinary device inputs
                aug = fs.augment(msg)
                flat_vals, flat_ok, vis, ops, stage_rows = \
                    self._run_step(aug)
                vis = np.asarray(vis)
                fs.note_stage_rows(np.asarray(stage_rows), 1)
                if not vis.any():
                    # empty-suppression contract, end to end: the
                    # sequential filter/project would have emitted
                    # nothing either (and an all-late chunk emits no
                    # watermark — WatermarkFilterExecutor parity)
                    continue
                cols: List[Column] = []
                k = 0
                units = 1 if fs.hop is None else fs.hop.units
                for j, f in enumerate(out_schema):
                    host_src = fs.host_out.get(j)
                    if host_src is not None:
                        src = msg.columns[host_src]
                        if units > 1:
                            # absorbed hop: the trace expanded rows
                            # units× — host passthrough columns tile
                            # copy-major to stay positionally aligned
                            cols.append(Column(
                                f.data_type,
                                np.tile(np.asarray(src.values), units),
                                None if src.validity is None else
                                np.tile(np.asarray(src.validity),
                                        units)))
                        else:
                            cols.append(Column(f.data_type, src.values,
                                               src.validity))
                        continue
                    okc = np.asarray(flat_ok[k])
                    cols.append(Column(
                        f.data_type, np.asarray(flat_vals[k]),
                        None if okc.all() else okc))
                    k += 1
                yield StreamChunk(out_schema, cols, vis,
                                  np.asarray(ops))
                # the absorbed watermark_filter announces its advanced
                # watermark after every forwarded chunk, derived
                # through the later projection stages
                for wm in fs.post_chunk_watermarks():
                    for d in fs.derive_watermarks(wm):
                        yield d
            elif isinstance(msg, Watermark):
                if msg.col_idx in wm_cols:
                    # an absorbed watermark_filter owns this column —
                    # upstream watermarks on it are superseded
                    continue
                for wm in fs.derive_watermarks(msg):
                    yield wm
            elif is_barrier(msg):
                wms = fs.on_barrier(msg, first=not first_seen)
                first_seen = True
                yield msg
                for wm in wms:
                    for d in fs.derive_watermarks(wm):
                        yield d
            else:
                yield msg
