"""RowIdGenExecutor: append a hidden serial row-id column.

Reference parity: src/stream/src/executor/row_id_gen.rs + the snowflake
layout of src/common/src/util/row_id.rs — tables/MVs with no user pk get a
generated `_row_id` so every row has a unique, stable key. The reference
packs (timestamp, vnode, sequence); ids are unique across parallel actors
AND across restarts, because the timestamp component comes from the epoch
and recovery always resumes at a strictly newer epoch.

Layout: | shard (10, most significant) | rel_ms (epoch physical ms, ~41
bits) | seq (12) |. Shard occupies the TOP bits so a sequence that
overflows its 12 bits carries into rel_ms *within the same shard* — ids
stay unique across shards at any per-epoch row count, and monotone per
shard. The sequence is rebased to the current barrier's epoch floor at
every barrier: after a crash the new INITIAL barrier carries an epoch
above the committed one, so re-generated ids can never collide with
committed MV pks.

TPU notes: id assignment is a vectorized arange add — one whole-column op
per chunk, no per-row Python.
"""

from __future__ import annotations

from typing import AsyncIterator

import numpy as np

from risingwave_tpu.common.chunk import Column, StreamChunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, is_barrier, is_chunk

ROW_ID_FIELD = Field("_row_id", DataType.SERIAL)

_SHARD_BITS = 10
_SEQ_BITS = 12


class RowIdCounter:
    """The id counter alone — the runtime of a `row_id_gen` stage
    absorbed into a fused run (ops/fused.py). RowIdGenExecutor IS one
    (plus the executor loop), so host fusion hands the executor itself
    to the stage while worker-side IR rebuilds construct a bare
    counter; both share this one id layout and rebase rule."""

    def __init__(self, vnode_base: int = 0):
        assert 0 <= vnode_base < (1 << _SHARD_BITS)
        self._shard = vnode_base << (63 - _SHARD_BITS)
        self._next = self._shard

    @property
    def vnode_base(self) -> int:
        return self._shard >> (63 - _SHARD_BITS)

    def _rebase(self, epoch_value: int) -> None:
        floor = self._shard | ((epoch_value >> 16) << _SEQ_BITS)
        if self._next < floor:
            self._next = floor


class RowIdGenExecutor(RowIdCounter, Executor):
    """Appends `_row_id` (SERIAL) as the last column (row_id_gen.rs)."""

    def __init__(self, input_: Executor, vnode_base: int = 0):
        schema = Schema(list(input_.schema.fields) + [ROW_ID_FIELD])
        info = ExecutorInfo(schema, [len(input_.schema)], "RowIdGenExecutor")
        Executor.__init__(self, info)
        RowIdCounter.__init__(self, vnode_base)
        self.input = input_

    async def execute(self) -> AsyncIterator[Message]:
        async for msg in self.input.execute():
            if is_chunk(msg):
                cap = msg.capacity
                # every slot (visible or padding) gets an id: vectorized,
                # ids of padding slots are simply never observed
                ids = self._next + np.arange(cap, dtype=np.int64)
                self._next += cap
                col = Column(DataType.SERIAL, ids)
                yield StreamChunk(self.schema,
                                  list(msg.columns) + [col],
                                  msg.visibility, msg.ops)
            else:
                if is_barrier(msg):
                    self._rebase(msg.epoch.curr.value)
                yield msg
