"""RowIdGenExecutor: append a hidden serial row-id column.

Reference parity: src/stream/src/executor/row_id_gen.rs — tables/MVs with no
user pk get a generated `_row_id` so every row has a unique, stable key.
The reference packs (vnode, local monotonic seq) so ids are unique across
parallel actors; we do the same: id = (vnode_base << 48) | seq.

TPU notes: id assignment is a vectorized arange add — one device op per
chunk, no per-row Python.
"""

from __future__ import annotations

from typing import AsyncIterator

import numpy as np

from risingwave_tpu.common.chunk import Column, StreamChunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import Message, is_chunk

ROW_ID_FIELD = Field("_row_id", DataType.SERIAL)


class RowIdGenExecutor(Executor):
    """Appends `_row_id` (SERIAL) as the last column (row_id_gen.rs)."""

    def __init__(self, input_: Executor, vnode_base: int = 0):
        schema = Schema(list(input_.schema.fields) + [ROW_ID_FIELD])
        info = ExecutorInfo(schema, [len(input_.schema)], "RowIdGenExecutor")
        super().__init__(info)
        self.input = input_
        # high 16 bits identify the generating shard: ids never collide
        # across parallel source actors (row_id_gen.rs vnode split analog)
        self._base = vnode_base << 48
        self._seq = 0

    async def execute(self) -> AsyncIterator[Message]:
        async for msg in self.input.execute():
            if is_chunk(msg):
                cap = msg.capacity
                # every slot (visible or padding) gets an id: vectorized,
                # ids of padding slots are simply never observed
                ids = self._base + self._seq + np.arange(
                    cap, dtype=np.int64)
                self._seq += cap
                col = Column(DataType.SERIAL, ids)
                yield StreamChunk(self.schema,
                                  list(msg.columns) + [col],
                                  msg.visibility, msg.ops)
            else:
                yield msg
