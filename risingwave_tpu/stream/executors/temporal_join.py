"""TemporalJoinExecutor: stream ⋈ versioned table AS OF process time.

Reference parity: src/stream/src/executor/temporal_join.rs:52 — the
left stream probes the RIGHT side's current version at arrival time;
matches emit immediately and are never revised when the right side
later changes (append-only output, the defining temporal-join
property). The right side is an ARRANGEMENT (arrange/lookup family,
src/stream/src/executor/lookup.rs:42): a key → row map maintained
from the right input's changelog — here a host dict upserted by the
right MV's chain output (snapshot backfill + live deltas), since
right-side rows must be readable by arbitrary key at probe time and
varchar payloads cannot live in HBM anyway.

Semantics:
- right pk == join key (enforced by the planner): one row per key.
- INNER: unmatched left rows drop. LEFT_OUTER: they emit NULL-padded.
- left rows probe the arrangement AS OF their arrival epoch — the
  process-time contract makes startup ordering best-effort by design
  (FOR SYSTEM_TIME AS OF PROCTIME()).
- no join state for the left side, no degrees: nothing to persist;
  recovery replays the right chain (backfill) to rebuild the
  arrangement.
"""

from __future__ import annotations

from typing import AsyncIterator, Dict, List, Optional, Sequence

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.merge import barrier_align_2
from risingwave_tpu.stream.message import (
    Message, Watermark, is_barrier,
)


class TemporalJoinExecutor(Executor):
    """stream LEFT/INNER temporal join against an arranged table."""

    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 outer: bool = False, actor_id: int = 0,
                 output_names: Optional[Sequence[str]] = None):
        assert len(left_keys) == len(right_keys)
        self.left_in, self.right_in = left, right
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.outer = outer
        names = list(output_names) if output_names else None
        fields = []
        k = 0
        for sch in (left.schema, right.schema):
            for f in sch:
                fields.append(Field(names[k] if names else f.name,
                                    f.data_type))
                k += 1
        # output is APPEND-ONLY: identity is the left row (row-id'd by
        # the planner); right columns are frozen as-of probe time
        super().__init__(ExecutorInfo(
            Schema(fields), list(left.pk_indices),
            f"TemporalJoinExecutor(actor={actor_id})"))
        self.n_left = len(left.schema)
        # the arrangement: right join-key tuple → right row tuple
        self._arranged: Dict[tuple, tuple] = {}

    # -- arrangement maintenance ------------------------------------------
    def _apply_right(self, chunk: StreamChunk) -> None:
        for op, row in chunk.to_records():
            key = tuple(row[i] for i in self.right_keys)
            if any(v is None for v in key):
                continue
            if op.is_insert:
                self._arranged[key] = tuple(row)
            else:
                self._arranged.pop(key, None)

    # -- probe ------------------------------------------------------------
    def _probe_left(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        recs = chunk.to_records()
        out_rows: List[tuple] = []
        null_right = (None,) * len(self.right_in.schema)
        for op, row in recs:
            assert op.is_insert, \
                "temporal join left input must be append-only"
            key = tuple(row[i] for i in self.left_keys)
            match = None if any(v is None for v in key) else \
                self._arranged.get(key)
            if match is not None:
                out_rows.append(tuple(row) + match)
            elif self.outer:
                out_rows.append(tuple(row) + null_right)
        if not out_rows:
            return None
        t = len(out_rows)
        cap = next_pow2(t)
        cols = []
        for i, f in enumerate(self.schema):
            dt = f.data_type
            vals = [r[i] for r in out_rows]
            ok = np.ones(cap, dtype=bool)
            ok[:t] = [v is not None for v in vals]
            if dt.is_device:
                arr = np.zeros(cap, dtype=dt.np_dtype)
                arr[:t] = [0 if v is None else v for v in vals]
            else:
                arr = np.empty(cap, dtype=object)
                arr[:t] = vals
            cols.append(Column(dt, arr, None if ok.all() else ok))
        vis = np.zeros(cap, dtype=bool)
        vis[:t] = True
        ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
        return StreamChunk(self.schema, cols, vis, ops)

    # -- main loop --------------------------------------------------------
    async def execute(self) -> AsyncIterator[Message]:
        lit = self.left_in.execute()
        rit = self.right_in.execute()
        first_l = await lit.__anext__()
        first_r = await rit.__anext__()
        assert is_barrier(first_l) and is_barrier(first_r)
        yield first_l
        # left messages BUFFER within the epoch and probe at the
        # barrier, after every right row of the epoch has applied:
        # probe-vs-arrangement interleave is then deterministic (all
        # rights ≤ epoch N are visible to lefts of epoch N) — the same
        # answer in process and across a cluster exchange, instead of
        # racy as-of-arrival processing time. One barrier of added
        # probe latency, matching the epoch-batched kernel stance.
        left_buf: List[Message] = []
        async for tag, msg in barrier_align_2(lit, rit):
            if tag == "barrier":
                for m in left_buf:
                    if isinstance(m, StreamChunk):
                        out = self._probe_left(m)
                        if out is not None:
                            yield out
                    else:
                        yield m          # left watermark, in order
                left_buf.clear()
                yield msg
            elif tag == "right":
                if isinstance(msg, StreamChunk):
                    self._apply_right(msg)
                # right-side watermarks do not bound the output
            else:                                    # left
                if isinstance(msg, StreamChunk):
                    left_buf.append(msg)
                elif isinstance(msg, Watermark):
                    if msg.col_idx < self.n_left:
                        left_buf.append(msg)
