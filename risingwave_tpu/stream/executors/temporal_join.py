"""TemporalJoinExecutor: stream ⋈ versioned table AS OF process time.

Reference parity: src/stream/src/executor/temporal_join.rs:52 — the
left stream probes the RIGHT side's current version at arrival time;
matches emit immediately and are never revised when the right side
later changes (append-only output, the defining temporal-join
property). The right side is an ARRANGEMENT (arrange/lookup family,
src/stream/src/executor/lookup.rs:42): a key → row map maintained
from the right input's changelog — here a host dict upserted by the
right MV's chain output (snapshot backfill + live deltas), since
right-side rows must be readable by arbitrary key at probe time and
varchar payloads cannot live in HBM anyway.

Semantics:
- right pk == join key (enforced by the planner): one row per key.
- INNER: unmatched left rows drop. LEFT_OUTER: they emit NULL-padded.
- left rows probe the arrangement AS OF their arrival epoch — the
  process-time contract makes startup ordering best-effort by design
  (FOR SYSTEM_TIME AS OF PROCTIME()).
- no join state for the left side, no degrees: nothing to persist;
  recovery replays the right chain (backfill) to rebuild the
  arrangement.
"""

from __future__ import annotations

from typing import AsyncIterator, Dict, List, Optional, Sequence

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk, next_pow2
from risingwave_tpu.common.types import Field, Schema
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.merge import barrier_align_2
from risingwave_tpu.stream.message import (
    Message, Watermark, is_barrier,
)


class TemporalJoinExecutor(Executor):
    """stream LEFT/INNER temporal join against an arranged table."""

    def __init__(self, left: Executor, right: Executor,
                 left_keys: Sequence[int], right_keys: Sequence[int],
                 outer: bool = False, actor_id: int = 0,
                 output_names: Optional[Sequence[str]] = None):
        assert len(left_keys) == len(right_keys)
        self.left_in, self.right_in = left, right
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.outer = outer
        names = list(output_names) if output_names else None
        fields = []
        k = 0
        for sch in (left.schema, right.schema):
            for f in sch:
                fields.append(Field(names[k] if names else f.name,
                                    f.data_type))
                k += 1
        # output is APPEND-ONLY: identity is the left row (row-id'd by
        # the planner); right columns are frozen as-of probe time
        super().__init__(ExecutorInfo(
            Schema(fields), list(left.pk_indices),
            f"TemporalJoinExecutor(actor={actor_id})"))
        self.n_left = len(left.schema)
        # the arrangement, COLUMNAR (the r10 ad-ctr profile: per-row
        # to_records materialization of whole left chunks was ~7s of
        # the post-epoch-batching p99 tail): right join-key tuple →
        # row ref into a host column arena; probes touch python only
        # for the key lookup and gather everything else vectorized
        from risingwave_tpu.stream.executors.hash_join import _Arena
        self._arranged: Dict[tuple, int] = {}
        self._arena = _Arena(right.schema)
        self._next_ref = 0

    # -- arrangement maintenance ------------------------------------------
    def _row_keys(self, chunk: StreamChunk, idx: np.ndarray,
                  key_cols: Sequence[int]) -> List[tuple]:
        """Join-key tuples for the given rows (key columns only — the
        payload columns never materialize to python)."""
        cols = []
        for i in key_cols:
            c = chunk.columns[i]
            vals = np.asarray(c.values)[idx].tolist()
            if c.validity is not None:
                okv = np.asarray(c.validity)[idx].tolist()
                vals = [None if not o else v
                        for v, o in zip(vals, okv)]
            cols.append(vals)
        return list(zip(*cols)) if cols else [()] * len(idx)

    def _apply_right(self, chunk: StreamChunk) -> None:
        vis_idx = np.flatnonzero(np.asarray(chunk.visibility))
        if not len(vis_idx):
            return
        ops = np.asarray(chunk.ops)[vis_idx]
        keys = self._row_keys(chunk, vis_idx, self.right_keys)
        is_ins = (ops == int(Op.INSERT)) | \
            (ops == int(Op.UPDATE_INSERT))
        ins_rows = [j for j in range(len(vis_idx))
                    if is_ins[j] and not any(v is None
                                             for v in keys[j])]
        ref_of = {}
        if ins_rows:
            refs = np.arange(self._next_ref,
                             self._next_ref + len(ins_rows),
                             dtype=np.int32)
            self._next_ref += len(ins_rows)
            self._arena.store(refs, chunk, vis_idx[ins_rows])
            ref_of = dict(zip(ins_rows, refs.tolist()))
        # dict ops apply in ROW ORDER: an update pair lands as
        # [U-, U+] on one key and must end with the new version
        for j in range(len(vis_idx)):
            if any(v is None for v in keys[j]):
                continue
            if is_ins[j]:
                self._arranged[keys[j]] = ref_of[j]
            else:
                self._arranged.pop(keys[j], None)
        self._maybe_compact()

    def _maybe_compact(self) -> None:
        """Reclaim dead arena refs wholesale once they dominate (the
        dim side is an MV changelog: update pairs strand old rows)."""
        if self._next_ref < 4096 or \
                len(self._arranged) * 2 > self._next_ref:
            return
        live = list(self._arranged.items())
        old_refs = np.asarray([r for _k, r in live], dtype=np.int64)
        new_arena = type(self._arena)(self.right_in.schema)
        new_arena.ensure(max(len(live) - 1, 0))
        for i in range(len(self.right_in.schema)):
            new_arena.cols[i][:len(live)] = \
                self._arena.cols[i][old_refs]
            new_arena.valid[i][:len(live)] = \
                self._arena.valid[i][old_refs]
        self._arena = new_arena
        self._arranged = {k: j for j, (k, _r) in enumerate(live)}
        self._next_ref = len(live)

    # -- probe ------------------------------------------------------------
    def _probe_left(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        vis_idx = np.flatnonzero(np.asarray(chunk.visibility))
        if not len(vis_idx):
            return None
        ops = np.asarray(chunk.ops)[vis_idx]
        assert ((ops == int(Op.INSERT))
                | (ops == int(Op.UPDATE_INSERT))).all(), \
            "temporal join left input must be append-only"
        keys = self._row_keys(chunk, vis_idx, self.left_keys)
        arranged = self._arranged
        get = arranged.get
        refs = np.fromiter((get(k, -1) for k in keys),
                           dtype=np.int64, count=len(keys))
        # NULL-key rows never match: one vectorized validity pass
        # instead of a per-key any() (the r10/r11 probe profile)
        for i in self.left_keys:
            c = chunk.columns[i]
            if c.validity is not None:
                refs[~np.asarray(c.validity)[vis_idx]] = -1
        matched = refs >= 0
        sel = matched if not self.outer \
            else np.ones(len(keys), dtype=bool)
        t = int(sel.sum())
        if t == 0:
            return None
        cap = next_pow2(t)
        out_idx = vis_idx[sel]
        cols: List[Column] = []
        # left columns: vectorized gather from the incoming chunk
        for i, f in enumerate(self.left_in.schema):
            c = chunk.columns[i]
            src = np.asarray(c.values)[out_idx]
            vals = np.zeros(cap, dtype=src.dtype) \
                if src.dtype != object else np.empty(cap, dtype=object)
            vals[:t] = src
            ok = np.ones(cap, dtype=bool)
            if c.validity is not None:
                ok[:t] = np.asarray(c.validity)[out_idx]
            cols.append(Column(f.data_type, vals,
                               None if ok.all() else ok))
        # right columns: vectorized gather from the arena by ref;
        # unmatched (outer) rows NULL-pad via the validity mask
        sel_refs = np.maximum(refs[sel], 0)
        sel_ok = matched[sel]
        for i, f in enumerate(self.right_in.schema):
            col = self._arena.gather_col(i, sel_refs, cap)
            ok = np.ones(cap, dtype=bool)
            ok[:t] = sel_ok if col.validity is None \
                else (np.asarray(col.validity)[:t] & sel_ok)
            if col.values.dtype == object:
                vals = col.values
                if not sel_ok.all():
                    vals = vals.copy()
                    vals[:t][~sel_ok] = None
            else:
                vals = np.where(np.concatenate(
                    [sel_ok, np.ones(cap - t, dtype=bool)]),
                    col.values, 0) if not sel_ok.all() else col.values
            cols.append(Column(f.data_type, vals,
                               None if ok.all() else ok))
        vis = np.zeros(cap, dtype=bool)
        vis[:t] = True
        ops_out = np.full(cap, int(Op.INSERT), dtype=np.int8)
        return StreamChunk(self.schema, cols, vis, ops_out)

    # -- main loop --------------------------------------------------------
    async def execute(self) -> AsyncIterator[Message]:
        lit = self.left_in.execute()
        rit = self.right_in.execute()
        first_l = await lit.__anext__()
        first_r = await rit.__anext__()
        assert is_barrier(first_l) and is_barrier(first_r)
        yield first_l
        # left messages BUFFER within the epoch and probe at the
        # barrier, after every right row of the epoch has applied:
        # probe-vs-arrangement interleave is then deterministic (all
        # rights ≤ epoch N are visible to lefts of epoch N) — the same
        # answer in process and across a cluster exchange, instead of
        # racy as-of-arrival processing time. One barrier of added
        # probe latency, matching the epoch-batched kernel stance.
        left_buf: List[Message] = []
        async for tag, msg in barrier_align_2(lit, rit):
            if tag == "barrier":
                for m in left_buf:
                    if isinstance(m, StreamChunk):
                        out = self._probe_left(m)
                        if out is not None:
                            yield out
                    else:
                        yield m          # left watermark, in order
                left_buf.clear()
                yield msg
            elif tag == "right":
                if isinstance(msg, StreamChunk):
                    self._apply_right(msg)
                # right-side watermarks do not bound the output
            else:                                    # left
                if isinstance(msg, StreamChunk):
                    left_buf.append(msg)
                elif isinstance(msg, Watermark):
                    if msg.col_idx < self.n_left:
                        left_buf.append(msg)
