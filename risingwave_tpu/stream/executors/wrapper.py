"""WrapperExecutor: runtime sanity checks around any executor.

Reference parity: src/stream/src/executor/wrapper.rs (+ wrapper/
schema_check.rs, update_check.rs, epoch_check.rs) — in debug builds every
executor is wrapped with assertions that catch protocol violations at
the point of origin instead of three operators downstream:

- schema check: chunk column count + dtypes match the executor schema
- update check: UPDATE_DELETE must be immediately followed (in visible
  row order) by UPDATE_INSERT
- epoch check: barrier epochs strictly increase
- watermark check: per-column watermark values never regress
"""

from __future__ import annotations

from typing import AsyncIterator, Dict, Optional

import numpy as np

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, Watermark, is_barrier, is_chunk,
)


class SanityError(AssertionError):
    """A stream-protocol violation caught by WrapperExecutor."""


class WrapperExecutor(Executor):
    """Debug assertions around an inner executor (wrapper.rs analog)."""

    def __init__(self, inner: Executor):
        super().__init__(ExecutorInfo(
            inner.schema, list(inner.pk_indices),
            f"Wrapper({inner.identity})"))
        self.inner = inner
        self._last_epoch: Optional[int] = None
        self._watermarks: Dict[int, object] = {}

    def _check_chunk(self, chunk: StreamChunk) -> None:
        ident = self.inner.identity
        if len(chunk.columns) != len(self.schema):
            raise SanityError(
                f"{ident}: chunk has {len(chunk.columns)} columns, "
                f"schema has {len(self.schema)}")
        for i, (c, f) in enumerate(zip(chunk.columns, self.schema)):
            if c.data_type != f.data_type:
                raise SanityError(
                    f"{ident}: column {i} is {c.data_type}, "
                    f"schema says {f.data_type}")
        ops = np.asarray(chunk.ops)
        vis = np.asarray(chunk.visibility)
        visible_ops = ops[vis]
        is_ud = visible_ops == int(Op.UPDATE_DELETE)
        is_ui = visible_ops == int(Op.UPDATE_INSERT)
        # every visible U- must be followed by a visible U+
        follows = np.roll(is_ui, -1)
        if len(visible_ops) and bool(is_ud[-1]):
            raise SanityError(f"{ident}: chunk ends with UPDATE_DELETE")
        if bool((is_ud & ~follows).any()):
            raise SanityError(
                f"{ident}: UPDATE_DELETE not followed by UPDATE_INSERT")
        if bool((is_ui & ~np.roll(is_ud, 1)).any()):
            raise SanityError(
                f"{ident}: UPDATE_INSERT not preceded by UPDATE_DELETE")

    async def execute(self) -> AsyncIterator[Message]:
        async for msg in self.inner.execute():
            if is_chunk(msg):
                self._check_chunk(msg)
            elif is_barrier(msg):
                e = msg.epoch.curr.value
                if self._last_epoch is not None and e <= self._last_epoch:
                    raise SanityError(
                        f"{self.inner.identity}: barrier epoch {e:#x} not "
                        f"after {self._last_epoch:#x}")
                self._last_epoch = e
            elif isinstance(msg, Watermark):
                prev = self._watermarks.get(msg.col_idx)
                if prev is not None and msg.value < prev:
                    raise SanityError(
                        f"{self.inner.identity}: watermark regressed on "
                        f"col {msg.col_idx}: {msg.value} < {prev}")
                self._watermarks[msg.col_idx] = msg.value
            yield msg
