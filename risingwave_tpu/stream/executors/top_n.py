"""TopN executors: streaming ORDER BY ... OFFSET ... LIMIT maintenance.

Reference parity: src/stream/src/executor/top_n/ — top_n_plain.rs
(TopNExecutor), group_top_n.rs (GroupTopNExecutor), top_n_appendonly.rs
(AppendOnlyTopNExecutor); state layout managed state = all candidate
rows keyed by [group key +] order key + pk (top_n_state.rs).

Re-design notes: the reference replays each row against a btree cache
and emits per-row deltas. Here each *chunk* applies as a batch and the
executor emits the NET delta of the visible window [offset, offset+limit)
per group — equivalent eventual output with one sorted-structure pass
per chunk. Ordering is host-side (control-heavy small-N work, same as
the reference's CPU btree — nothing here wants the MXU).

NULLS ordering follows PostgreSQL: NULLS LAST for ASC, NULLS FIRST for
DESC.
"""

from __future__ import annotations

import bisect
from collections import Counter
from typing import AsyncIterator, Dict, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import Column, Op, StreamChunk
from risingwave_tpu.common.types import Schema
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Message, is_barrier, is_chunk, is_watermark,
)


class _Key:
    """None-aware, per-column asc/desc comparable sort key."""

    __slots__ = ("vals", "descs")

    def __init__(self, vals: Tuple, descs: Tuple[bool, ...]):
        self.vals = vals
        self.descs = descs

    def __lt__(self, other: "_Key") -> bool:
        for a, b, d in zip(self.vals, other.vals, self.descs):
            if a is None and b is None:
                continue
            if a is None:               # NULLS LAST asc / FIRST desc
                return d
            if b is None:
                return not d
            if a == b:
                continue
            return (a > b) if d else (a < b)
        return False

    def __eq__(self, other) -> bool:
        return self.vals == other.vals

    def __repr__(self) -> str:
        return f"_Key({self.vals})"


class _SortedRows:
    """One group's candidates: rows sorted by order key + pk tiebreak."""

    __slots__ = ("entries",)

    def __init__(self):
        self.entries: List[Tuple[_Key, tuple]] = []

    def insert(self, key: _Key, row: tuple) -> None:
        bisect.insort(self.entries, (key, row))

    def delete(self, key: _Key, row: tuple) -> None:
        i = bisect.bisect_left(self.entries, (key, row))
        if i < len(self.entries) and self.entries[i][1] == row:
            del self.entries[i]

    def window(self, offset: int, limit: Optional[int]) -> List[tuple]:
        hi = None if limit is None else offset + limit
        return [r for _k, r in self.entries[offset:hi]]

    def truncate_beyond(self, n: int) -> List[tuple]:
        """Drop rows ranked >= n (append-only pruning); returns dropped."""
        dropped = [r for _k, r in self.entries[n:]]
        del self.entries[n:]
        return dropped


class GroupTopNExecutor(Executor):
    """Streaming [group] top-n (top_n_plain.rs / group_top_n.rs analog).

    `group_indices=[]` gives plain TopN; `append_only=True` prunes
    managed state beyond the window (top_n_appendonly.rs analog).
    """

    def __init__(self, input_: Executor, order_by: Sequence[Tuple[int, bool]],
                 offset: int, limit: Optional[int], state: StateTable,
                 group_indices: Sequence[int] = (),
                 append_only: bool = False,
                 pk_indices: Optional[Sequence[int]] = None,
                 tier_cap: Optional[int] = None):
        # planner chains sometimes know the pk better than the input
        # executor advertises (e.g. a projection over an agg)
        pk = list(pk_indices if pk_indices is not None
                  else input_.pk_indices)
        super().__init__(ExecutorInfo(
            input_.schema, pk,
            "GroupTopNExecutor" if group_indices else "TopNExecutor"))
        self.input = input_
        self.order_by = list(order_by)
        self.offset = int(offset)
        self.limit = limit
        self.state = state
        self.group_indices = list(group_indices)
        self.append_only = append_only
        # sort = order cols, then pk for a total (deterministic) order
        self._sort_cols = [i for i, _ in self.order_by] + [
            i for i in pk
            if i not in {j for j, _ in self.order_by}]
        self._descs = tuple([d for _, d in self.order_by] +
                            [False] * (len(self._sort_cols)
                                       - len(self.order_by)))
        self.groups: Dict[tuple, _SortedRows] = {}
        # fast-key eligibility: native tuples compare in C (an order of
        # magnitude over _Key.__lt__'s per-column Python loop — the q5
        # bench's single hottest path); DESC needs numeric negation, so
        # any DESC column with a non-numeric physical type falls back
        from risingwave_tpu.common.types import DataType
        numeric = {DataType.INT16, DataType.INT32, DataType.INT64,
                   DataType.SERIAL, DataType.DECIMAL, DataType.DATE,
                   DataType.TIME, DataType.TIMESTAMP,
                   DataType.TIMESTAMPTZ, DataType.FLOAT32,
                   DataType.FLOAT64, DataType.BOOLEAN}
        self._fast_keys = all(
            (not d) or input_.schema[i].data_type in numeric
            for i, d in zip(self._sort_cols, self._descs))
        # host-state accounting (EstimateSize analog): sorted group
        # caches are exactly the kind of unbounded host cache the
        # memory manager wants on its books
        import weakref

        from risingwave_tpu.utils import memory as _mem
        mem_name = f"{self.identity}#{id(self)}"
        wref = weakref.ref(self)
        row_est = 96 + 16 * len(input_.schema)

        def _nbytes() -> int:
            s = wref()
            if s is None:
                _mem.GLOBAL.unregister(mem_name)
                return 0
            entries = sum(len(sr.entries) for sr in s.groups.values())
            return row_est * entries + 120 * len(s._cold_groups)

        _mem.GLOBAL.register(mem_name, _nbytes)
        # cold tier (state/tier.py): whole GROUP caches evict — the
        # sorted candidate rows drop from memory but stay durable in
        # the state table (pk leads with the group key, so reload is
        # one prefix scan); a chunk touching an evicted group reloads
        # it BEFORE the old-window capture, so emitted deltas stay
        # exact. Grouped TopN only: plain TopN is one window — nothing
        # to tier.
        self._tier = None
        self._tier_part = None
        self._cold_groups: set = set()
        self._tier_seq = 0
        if tier_cap is not None:
            g = len(self.group_indices)
            if not g:
                raise ValueError("tier_cap needs a grouped TopN")
            if state.pk_indices[:g] != self.group_indices:
                raise ValueError(
                    "tier_cap needs the state-table pk prefixed by "
                    "the group key (reload prefix-scans by group): "
                    f"pk={state.pk_indices} group={self.group_indices}")
            for i in state.dist_key_indices:
                if state.pk_indices.index(i) >= g:
                    raise ValueError(
                        "tier_cap needs dist keys inside the group "
                        "prefix")
            from risingwave_tpu.state import tier as _tier
            self._tier = _tier.GLOBAL
            # registration deferred to execute(): plan-only executors
            # must leave no ghost entries in the global registry
            self._tier_cap = int(tier_cap)
            self._tier_name = mem_name
            self._tier_nbytes = _nbytes

    # -- helpers ---------------------------------------------------------
    def _key_of(self, row: tuple):
        if self._fast_keys:
            # per-column (null_rank, value) pairs; physical rows make
            # every DESC value negatable. NULLS LAST asc / FIRST desc.
            return tuple(
                ((1, 0) if not d else (-1, 0)) if row[i] is None
                else (0, -row[i] if d else row[i])
                for i, d in zip(self._sort_cols, self._descs))
        return _Key(tuple(row[i] for i in self._sort_cols), self._descs)

    def _group_of(self, row: tuple) -> tuple:
        return tuple(row[i] for i in self.group_indices)

    def _window(self, g: tuple) -> List[tuple]:
        rows = self.groups.get(g)
        return rows.window(self.offset, self.limit) if rows else []

    def _recover(self) -> None:
        # rows are PHYSICAL end to end (DECIMAL = scaled int64): order
        # is preserved under the physical encoding, state-table writes
        # expect it, and chunk rebuild must not lossily convert
        for _pk, row in self.state.iter_rows():
            g = self._group_of(row)
            self.groups.setdefault(g, _SortedRows()).insert(
                self._key_of(row), row)
        if self._tier is not None and self.groups:
            # everything recovers resident (cold markers do not survive
            # a crash); seed the tier clock so the first checkpoint
            # sweep re-applies the cap
            self._tier.touch(self._tier_part, list(self.groups),
                             self._tier_seq)

    # -- cold tier (state/tier.py) ---------------------------------------
    def _tier_register(self) -> None:
        """Register at execute() start — only executors that actually
        RUN appear in the global registry."""
        import weakref
        tref = weakref.ref(self)

        def _evict_cb(keys):
            s = tref()
            return 0 if s is None else s._tier_evict(keys)

        self._tier_part = self._tier.register(
            self._tier_name, _evict_cb, cap=self._tier_cap,
            nbytes=self._tier_nbytes)

    def _tier_evict(self, groups: List[tuple]) -> int:
        """Tier sweep callback (checkpoint barriers, post-commit): drop
        the given groups' sorted caches; their candidate rows stay
        durable in the state table."""
        n = 0
        for g in groups:
            if self.groups.pop(g, None) is not None:
                self._cold_groups.add(g)
                n += 1
        return n

    def _reload_group(self, g: tuple) -> None:
        """Reload an evicted group's candidates with one prefix scan —
        runs BEFORE the old-window capture, so the emitted delta is
        computed against the true pre-chunk window."""
        self._cold_groups.discard(g)
        rows = _SortedRows()
        for _pk, row in self.state.iter_prefix(list(g)):
            row = tuple(row)
            rows.insert(self._key_of(row), row)
        if rows.entries:
            self.groups[g] = rows
        self._tier.note_reload(self._tier_part, 1)

    # -- chunk path ------------------------------------------------------
    def _apply(self, chunk: StreamChunk) -> Optional[StreamChunk]:
        touched: Dict[tuple, List[tuple]] = {}
        _idx, prows, pops = chunk.to_physical_records()
        # cold groups this chunk touches reload BEFORE write_chunk:
        # the reload prefix-scan must see PRE-chunk state only, or the
        # old-window capture would already contain this chunk's rows
        # (suppressing deltas) and the loop would double-insert them
        if self._cold_groups:
            for row in prows:
                g = self._group_of(row)
                if g in self._cold_groups:
                    self._reload_group(g)
        # state writes batch as ONE vectorized chunk apply (the same
        # insert/delete multiset the loop below maintains in memory) —
        # a per-row insert() pays a full pk encode each (the other q5
        # hot path); only append-only truncation drops need row calls
        self.state.write_chunk(chunk)
        for op_i, row in zip(pops.tolist(), prows):
            is_ins = Op(op_i).is_insert
            g = self._group_of(row)
            if g not in touched:
                touched[g] = self._window(g)
            rows = self.groups.setdefault(g, _SortedRows())
            key = self._key_of(row)
            if is_ins:
                rows.insert(key, row)
                if self.append_only and self.limit is not None:
                    for dropped in rows.truncate_beyond(
                            self.offset + self.limit):
                        self.state.delete(dropped)
            else:
                if self.append_only:
                    raise ValueError(
                        "delete on append-only TopN input")
                rows.delete(key, row)
        if self._tier is not None and touched:
            self._tier.touch(self._tier_part, list(touched),
                             self._tier_seq)
        # net window delta per touched group
        deletes: List[tuple] = []
        inserts: List[tuple] = []
        for g, old_window in touched.items():
            new_window = self._window(g)
            old_c, new_c = Counter(old_window), Counter(new_window)
            for r, cnt in (old_c - new_c).items():
                deletes.extend([r] * cnt)
            for r, cnt in (new_c - old_c).items():
                inserts.extend([r] * cnt)
        if not deletes and not inserts:
            return None
        return self._delta_chunk(deletes, inserts)

    def _delta_chunk(self, deletes: List[tuple],
                     inserts: List[tuple]) -> StreamChunk:
        rows = deletes + inserts
        n = len(rows)
        ops = np.asarray([int(Op.DELETE)] * len(deletes)
                         + [int(Op.INSERT)] * len(inserts), dtype=np.int8)
        cols: List[Column] = []
        for j, f in enumerate(self.schema):
            vals_l = [r[j] for r in rows]
            ok = np.asarray([v is not None for v in vals_l])
            if f.data_type.is_device:
                vals = np.asarray([0 if v is None else v for v in vals_l],
                                  dtype=f.data_type.np_dtype)
            else:
                vals = np.asarray(vals_l, dtype=object)
            cols.append(Column(f.data_type, vals,
                               None if ok.all() else ok))
        return StreamChunk(self.schema, cols, np.ones(n, dtype=bool), ops)

    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first)
        if self._tier is not None:
            self._tier_register()
        self.state.init_epoch(first.epoch)
        self._recover()
        yield first
        try:
            async for msg in it:
                if is_chunk(msg):
                    out = self._apply(msg)
                    if out is not None:
                        yield out
                elif is_barrier(msg):
                    self.state.commit(msg.epoch)
                    if self._tier is not None:
                        # sweep at checkpoints, post-commit: evicted
                        # groups' rows are durable and no chunk is in
                        # flight (tier.py epoch-sequencing argument)
                        self._tier_seq += 1
                        if msg.kind.is_checkpoint:
                            self._tier.sweep(self._tier_part,
                                             self._tier_seq)
                    yield msg
                elif is_watermark(msg):
                    if msg.col_idx in self.group_indices:
                        yield msg   # group-key watermarks pass through
        finally:
            if self._tier_part is not None:
                self._tier.unregister(self._tier_part)


def TopNExecutor(input_: Executor, order_by, offset, limit,
                 state: StateTable, append_only: bool = False
                 ) -> GroupTopNExecutor:
    """Plain (ungrouped) TopN — top_n_plain.rs / top_n_appendonly.rs."""
    return GroupTopNExecutor(input_, order_by, offset, limit, state,
                             group_indices=(), append_only=append_only)
