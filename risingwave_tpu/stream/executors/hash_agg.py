"""HashAggExecutor: streaming GROUP BY on device-resident state.

Reference parity: src/stream/src/executor/hash_agg.rs:67 (executor),
:329 (apply_chunk), :445 (flush_data) and the value-state encoding of
aggregation/agg_group.rs. The TPU re-design moves the per-row group map
into HBM (ops/hash_agg.py); this executor is the thin host driver:

  chunk    → build int32 key/input lanes (ops/lanes.py codecs), one
             jitted device step
  barrier  → one device gather of dirty groups → emit change chunk,
             persist physical rows through the StateTable, commit epoch

Emission semantics match flush_data: first touch of a group emits Insert,
subsequent changes emit an UpdateDelete/UpdateInsert pair, a group whose
row count drops to zero emits Delete. Outputs are compared against the
device-resident emitted snapshot, so repeated no-op touches emit nothing.

Value-state row layout (physical): group keys | group_rows | per call
(value [+ non-null count]). Recovery reloads the table and re-encodes it
into the kernel (``GroupedAggKernel.rebuild``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import (
    AsyncIterator, Dict, List, Optional, Sequence, Tuple,
)

import numpy as np

from risingwave_tpu.common.chunk import (
    Column, Op, StreamChunk, next_pow2,
)
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.ops.hash_agg import (
    HOST_AGG_KINDS, AggKind, AggSpec, GroupedAggKernel, acc_dtypes,
)
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.executors.keys import (
    LANES_PER_KEY as _LANES_PER_KEY, KeyCodec,
)
from risingwave_tpu.stream.message import (
    Barrier, Message, Watermark, is_barrier, is_chunk, is_watermark,
)
from risingwave_tpu.stream import hotkeys as _hotkeys
from risingwave_tpu.utils.metrics import STREAMING as _METRICS

_SUM_OUT = {
    DataType.INT16: DataType.INT64, DataType.INT32: DataType.INT64,
    DataType.INT64: DataType.INT64, DataType.DECIMAL: DataType.DECIMAL,
    DataType.FLOAT32: DataType.FLOAT64, DataType.FLOAT64: DataType.FLOAT64,
}


def agg_result_type(kind: AggKind,
                    input_type: Optional[DataType]) -> DataType:
    """Result type of one aggregate call — the ONE copy of these
    rules (AggCall.out_type and the binder's post-agg typing both
    call it; agg/mod.rs return-type derivation analog)."""
    if kind in (AggKind.COUNT, AggKind.APPROX_COUNT_DISTINCT):
        return DataType.INT64
    if kind == AggKind.STRING_AGG:
        return DataType.VARCHAR
    if kind == AggKind.ARRAY_AGG:
        return DataType.LIST
    if kind == AggKind.SUM:
        try:
            return _SUM_OUT[input_type]
        except KeyError:
            raise TypeError(f"sum over {input_type} unsupported")
    return input_type


@dataclass(frozen=True)
class AggCall:
    """Logical aggregate call (agg/mod.rs AggCall analog)."""

    kind: AggKind
    input_idx: Optional[int] = None      # None ⇒ count(*)
    # DISTINCT dedup (aggregation/distinct.rs analog): the executor
    # keeps a per-(group, value) multiset and gates the device kernel
    # so each distinct value contributes once. MIN/MAX ignore it
    # (semantically identity).
    distinct: bool = False
    # string_agg separator (ignored by other kinds)
    delimiter: str = ","

    def out_type(self, input_schema: Schema) -> DataType:
        in_t = None if self.input_idx is None \
            else input_schema[self.input_idx].data_type
        return agg_result_type(self.kind, in_t)

    def spec(self, input_schema: Schema) -> AggSpec:
        if self.kind == AggKind.COUNT and self.input_idx is None:
            return AggSpec(AggKind.COUNT, None)
        in_t = input_schema[self.input_idx].data_type
        if self.kind in HOST_AGG_KINDS:
            return AggSpec(self.kind, np.dtype(object))
        if not in_t.is_device:
            raise TypeError(f"agg over host type {in_t} needs the host path")
        return AggSpec(self.kind, np.dtype(in_t.np_dtype))


def agg_output_schema(input_schema: Schema, group_indices: Sequence[int],
                      agg_calls: Sequence[AggCall],
                      names: Optional[Sequence[str]] = None) -> Schema:
    """Output schema: group keys then one column per agg call."""
    fields = [input_schema[i] for i in group_indices]
    for j, call in enumerate(agg_calls):
        name = names[j] if names else f"agg{j}"
        fields.append(Field(name, call.out_type(input_schema)))
    return Schema(fields)


def agg_state_schema(input_schema: Schema, group_indices: Sequence[int],
                     agg_calls: Sequence[AggCall]
                     ) -> Tuple[Schema, List[int]]:
    """Value-state table schema + pk indices (pk = group keys)."""
    fields = [input_schema[i] for i in group_indices]
    fields.append(Field("_group_rows", DataType.INT64))
    specs = [c.spec(input_schema) for c in agg_calls]
    for j, dt in enumerate(acc_dtypes(specs)):
        lt = DataType.FLOAT64 if np.issubdtype(dt, np.floating) \
            else DataType.INT64
        fields.append(Field(f"_acc{j}", lt))
    return Schema(fields), list(range(len(group_indices)))


def minput_state_schema(input_schema: Schema,
                        group_indices: Sequence[int], call: AggCall
                        ) -> Tuple[Schema, List[int], List[int]]:
    """Materialized-input table for ONE retractable MIN/MAX call
    (aggregation/minput.rs analog, value-multiset form): rows are
    (group keys..., value, _cnt) with pk = (group keys, value) so a
    prefix scan over the group yields the surviving values.

    Returns (schema, pk_indices, dist_key_indices)."""
    fields = [input_schema[i] for i in group_indices]
    fields.append(Field("_value", input_schema[call.input_idx].data_type))
    fields.append(Field("_cnt", DataType.INT64))
    g = len(group_indices)
    return Schema(fields), list(range(g + 1)), list(range(g))


def hll_state_schema(input_schema: Schema,
                     group_indices: Sequence[int]
                     ) -> Tuple[Schema, List[int], List[int]]:
    """Dense-HLL sketch table for ONE approx_count_distinct call:
    (group keys..., _sketch BYTEA) — one packed register file per
    group, upserted per barrier for dirty groups
    (approx_count_distinct/mod.rs:35-42 parity, 2^16 registers)."""
    fields = [input_schema[i] for i in group_indices]
    fields.append(Field("_sketch", DataType.BYTEA))
    g = len(group_indices)
    return Schema(fields), list(range(g)), list(range(g))


def agg_aux_tables(input_schema: Schema,
                   group_indices: Sequence[int],
                   agg_calls: Sequence["AggCall"], append_only: bool,
                   store, dedup_table_id, minput_table_id
                   ) -> Tuple[Dict[int, StateTable],
                              Dict[int, StateTable]]:
    """Build the aux state tables HashAggExecutor needs:
    per-DISTINCT-column dedup tables and per-call materialized-input
    tables (retractable MIN/MAX + host aggs). The ONE selection rule
    shared by the planner and the shipped-plan factory — both callers
    must agree or the same query gets different state tables.

    ``dedup_table_id(input_idx)`` / ``minput_table_id(call_idx)``
    supply ids. Iteration order is dedup tables first (call order,
    first DISTINCT occurrence per column), then minput tables in call
    order — the planner's sequential-id replay contract (ALTER
    PARALLELISM re-plans from a recorded id base) depends on it.

    Returns (distinct_tables, minput_tables)."""
    distinct_tables: Dict[int, StateTable] = {}
    for c in agg_calls:
        if c.distinct and c.input_idx not in distinct_tables:
            dsch, dpk, ddk = minput_state_schema(
                input_schema, group_indices, c)
            distinct_tables[c.input_idx] = StateTable(
                dedup_table_id(c.input_idx), dsch, dpk, store,
                dist_key_indices=ddk)
    minput_tables: Dict[int, StateTable] = {}
    for j, c in enumerate(agg_calls):
        if c.kind == AggKind.APPROX_COUNT_DISTINCT:
            hsch, hpk, hdk = hll_state_schema(input_schema,
                                              group_indices)
            # sanity off: sketch rows are blind upserts (same pk,
            # newer epoch shadows)
            minput_tables[j] = StateTable(
                minput_table_id(j), hsch, hpk, store,
                dist_key_indices=hdk, sanity_check=False)
    for j, c in enumerate(agg_calls):
        # retractable MIN/MAX need the value multiset; host aggs
        # (string_agg/array_agg) ARE their value multiset
        if ((c.kind in (AggKind.MIN, AggKind.MAX)
             and not append_only) or c.kind in HOST_AGG_KINDS):
            msch, mpk, mdk = minput_state_schema(
                input_schema, group_indices, c)
            minput_tables[j] = StateTable(
                minput_table_id(j), msch, mpk, store,
                dist_key_indices=mdk)
    return distinct_tables, minput_tables


class HashAggExecutor(Executor):
    """Streaming hash aggregation over a device kernel (hash_agg.rs:67)."""

    def __init__(self, input_: Executor, group_indices: Sequence[int],
                 agg_calls: Sequence[AggCall], table: StateTable,
                 append_only: bool = False,
                 output_names: Optional[Sequence[str]] = None,
                 minput_tables: Optional[Dict[int, StateTable]] = None,
                 actor_id: int = 0,
                 kernel: Optional[object] = None,
                 distinct_tables: Optional[Dict[int, StateTable]] = None,
                 kernel_capacity: Optional[int] = None,
                 flush_capacity: Optional[int] = None,
                 tier_cap: Optional[int] = None,
                 fused_stages=None):
        self.input = input_
        self.group_indices = list(group_indices)
        self.agg_calls = list(agg_calls)
        self.table = table
        self.append_only = append_only
        # fragment fusion (ops/fused.py): when set, `input_` is the RAW
        # upstream and the filter/project run in `fused_stages` inlines
        # into the kernel's jitted apply (one dispatch per batch, state
        # donated). Every index below (group, call inputs, schemas)
        # lives in the POST-stage column space.
        self.fused_stages = fused_stages
        in_schema = input_.schema if fused_stages is None \
            else fused_stages.out_schema
        self.group_types = [in_schema[i].data_type
                            for i in self.group_indices]
        # varchar/host-typed group keys go through the exact interning
        # codec (keys.py KeyCodec; key.rs:647 KeySerialized parity)
        self.key_codec = KeyCodec(self.group_types)
        self.specs = [c.spec(in_schema) for c in self.agg_calls]
        # retractable MIN/MAX: device extremes go stale on deletes; the
        # materialized-input tables (minput.rs analog) let the flush
        # recompute and patch them (see _recompute_extremes)
        self.minput: Dict[int, StateTable] = dict(minput_tables or {})
        self._deleted_lanes: set = set()
        # per-epoch buffered value-multiset deltas: call → key → delta
        # (written through to the StateTables once per barrier, keeping
        # store round-trips off the chunk hot path)
        self._minput_pending: Dict[int, Dict[tuple, int]] = {}
        # DISTINCT dedup (distinct.rs): ONE durable (group, value, cnt)
        # table + in-memory multiplicity mirror per distinct INPUT
        # COLUMN — count(DISTINCT x) and sum(DISTINCT x) share it,
        # like the reference's per-column dedup tables
        self.distinct_tables: Dict[int, StateTable] = dict(
            distinct_tables or {})
        self._distinct_cols: Dict[int, List[int]] = {}
        for j, c in enumerate(self.agg_calls):
            if c.distinct and c.kind in (AggKind.COUNT, AggKind.SUM):
                self._distinct_cols.setdefault(c.input_idx, []).append(j)
        missing_d = [col for col in self._distinct_cols
                     if col not in self.distinct_tables]
        if missing_d:
            raise ValueError(
                f"DISTINCT column(s) {missing_d} need dedup state "
                "tables — pass distinct_tables keyed by input column "
                "(minput_state_schema shape)")
        self._distinct_mult: Dict[int, Dict[tuple, int]] = {}
        self._distinct_pending: Dict[int, Dict[tuple, int]] = {}
        # incremental live-group count (gates interner GC cheaply)
        self._live_groups = 0
        # host-state accounting (memory_manager.rs analog)
        import weakref

        from risingwave_tpu.utils import memory as _mem
        mem_name = f"HashAggExecutor#{id(self)}"   # identity not set yet
        wref = weakref.ref(self)

        def _nbytes() -> int:
            s = wref()
            if s is None:
                _mem.GLOBAL.unregister(mem_name)
                return 0
            distinct = sum(120 * len(m)
                           for m in s._distinct_mult.values())
            pend = sum(120 * len(m)
                       for m in s._minput_pending.values())
            from risingwave_tpu.ops.hash_agg import HLL_M as _M
            sketches = sum((_M + 120) * len(d)
                           for d in s._hll_regs.values())
            cold = 120 * len(getattr(s, "_cold_groups", ()))
            return (s.key_codec.interner_nbytes() + distinct + pend
                    + sketches + cold)

        _mem.GLOBAL.register(mem_name, _nbytes)
        # dense-HLL calls: sketch registry host-side, one BYTEA aux
        # table per call (transported in the minput dict by
        # agg_aux_tables; split here — the multiset write paths must
        # never touch a sketch table)
        self._hll_calls = [j for j, s in enumerate(self.specs)
                           if s.kind == AggKind.APPROX_COUNT_DISTINCT]
        self.hll_tables: Dict[int, StateTable] = {
            j: self.minput.pop(j) for j in self._hll_calls
            if j in self.minput}
        missing_s = [j for j in self._hll_calls
                     if j not in self.hll_tables]
        if missing_s:
            raise ValueError(
                "approx_count_distinct needs a sketch state table per "
                f"call ({missing_s}) — pass minput_tables from "
                "agg_aux_tables (hll_state_schema)")
        # per-call: group tuple → uint8[HLL_M] registers; prev emitted
        # estimate; groups dirty since the last barrier
        self._hll_regs: Dict[int, Dict[tuple, np.ndarray]] = {
            j: {} for j in self._hll_calls}
        self._hll_prev: Dict[int, Dict[tuple, int]] = {
            j: {} for j in self._hll_calls}
        self._hll_dirty: Dict[int, set] = {
            j: set() for j in self._hll_calls}
        # host aggs (string_agg/array_agg) always need the value
        # multiset — their output IS the multiset
        self._host_calls = [j for j, s in enumerate(self.specs)
                            if s.kind in HOST_AGG_KINDS]
        missing_h = [j for j in self._host_calls if j not in self.minput]
        if missing_h:
            raise ValueError(
                f"{[self.specs[j].kind.value for j in missing_h]} need "
                "materialized-input state tables — pass minput_tables "
                "(see minput_state_schema)")
        if not append_only:
            need = [j for j, s in enumerate(self.specs)
                    if s.kind in (AggKind.MIN, AggKind.MAX)]
            missing = [j for j in need if j not in self.minput]
            if missing:
                raise ValueError(
                    "retractable min/max needs materialized-input state "
                    f"tables for call(s) {missing} — pass minput_tables "
                    "(see minput_state_schema) or append_only=True")
            if any(s.kind == AggKind.APPROX_COUNT_DISTINCT
                   for s in self.specs):
                raise ValueError(
                    "approx_count_distinct needs an append-only "
                    "upstream — an HLL sketch cannot retract")
        # kernel injection: the planner passes a vnode-sharded kernel
        # (parallel/agg.ShardedAggKernel) when parallelism > 1 — same
        # host surface, SPMD launch shape (dispatch.rs:582's hash
        # exchange becomes the in-kernel all_to_all)
        # capacity/flush presize: growth doublings and flush-buffer
        # bumps each cost a fresh XLA compile — builders that know
        # their cardinality pass hints and skip the ladder entirely.
        # Construction is LAZY (first data touch): building device
        # state here would initialize the JAX backend — and claim the
        # TPU — in processes that only PLAN (the distributed frontend
        # serializes this executor to IR and throws it away)
        self._kern_kw = {}
        if kernel_capacity is not None:
            self._kern_kw["capacity"] = kernel_capacity
        if flush_capacity is not None:
            self._kern_kw["flush_capacity"] = flush_capacity
        self._kernel = kernel
        # watermark-driven state cleaning (state_table.rs:894 analog):
        # latest watermark seen on the FIRST group column (the state
        # tables' pk prefix — the only position a range delete covers,
        # mirroring the reference's prefix rule), and the last value
        # already applied to the kernel/table
        self._clean_wm: Optional[int] = None
        self._cleaned_wm: Optional[int] = None
        out_schema = agg_output_schema(in_schema, group_indices, agg_calls,
                                       output_names)
        super().__init__(ExecutorInfo(
            out_schema, list(range(len(group_indices))),
            f"HashAggExecutor(actor={actor_id})"))
        # cold-tier participation (state/tier.py): groups past the cap
        # evict — device slots + host mirrors (distinct multisets, HLL
        # registers) drop, the value-state/aux tables stay durable —
        # and a later touch of an evicted group reloads it before the
        # chunk applies. Agg state is FULLY durable, so reload-on-touch
        # is retraction-safe (a delete touching a cold group reloads
        # first, then retracts normally). Single-chip lazy kernel only:
        # the sharded kernel's vnode routing has no targeted-evict path.
        self._tier = None
        self._tier_part = None
        self._cold_groups: Dict[tuple, tuple] = {}
        self._tier_seq = 0            # barrier counter = LRU clock
        self.tier_cap = tier_cap      # fragmenter ships this in the IR
        if tier_cap is not None:
            if kernel is not None:
                raise ValueError(
                    "tier_cap needs the single-chip lazy kernel "
                    "(sharded kernels have no targeted-evict path)")
            from risingwave_tpu.state import tier as _tier
            self._tier = _tier.GLOBAL
            # registration is DEFERRED to execute(): plan-only
            # executors (EXPLAIN, distributed CREATEs that serialize
            # to IR and discard) must leave no ghost entries in the
            # process-global registry
            self._tier_nbytes = _nbytes
        if fused_stages is not None:
            # fusion eligibility — the rewrite rule refuses these
            # before ever mutating the plan; failing loud here guards
            # the IR-rebuild path too. THE one predicate lives in
            # opt/fusion.py (rule, checker and both executor guards
            # all call it — no drifting copies).
            from risingwave_tpu.frontend.opt.fusion import (
                agg_ineligible_reason,
            )
            r = agg_ineligible_reason(self)
            if r is not None:
                raise ValueError(f"agg is not fusion-eligible: {r}")

    @property
    def kernel(self):
        """Device kernel, built on first touch (see __init__ note —
        plan-only processes must not initialize a JAX backend)."""
        if self._kernel is None:
            kw = dict(self._kern_kw)
            if self.fused_stages is not None:
                from risingwave_tpu.ops.fused import (
                    build_agg_prelude, raw_width,
                )
                kw["prelude"] = build_agg_prelude(
                    self.fused_stages, self.group_indices,
                    self.agg_calls, self.specs)
                kw["raw_width"] = raw_width(
                    len(self.fused_stages.ref_cols))
                kw["metrics_label"] = self.identity
                if self.fused_stages.hop is not None:
                    # in-trace hop expansion: keep per-dispatch
                    # POST-expansion rows near the normal batch size
                    kw["expand_units"] = self.fused_stages.hop.units
            self._kernel = GroupedAggKernel(
                key_width=_LANES_PER_KEY * len(self.group_indices),
                specs=self.specs, **kw)
            # dispatch spans carry the executor identity even when the
            # metrics_label is unset (unfused mode counts dispatches at
            # the executor, but trace spans always stamp the kernel at
            # its real jit sites)
            self._kernel._span_label = self.identity
        elif self.fused_stages is not None and \
                getattr(self._kernel, "supports_prelude", False) and \
                self._kernel._prelude is None:
            # injected SHARDED kernel + fused plan (ISSUE 10): install
            # the prelude on first touch — the absorbed run then
            # traces ahead of the vnode routing inside the SPMD step
            from risingwave_tpu.ops.fused import (
                build_agg_prelude, raw_width,
            )
            self._kernel.set_prelude(
                build_agg_prelude(self.fused_stages,
                                  self.group_indices, self.agg_calls,
                                  self.specs),
                raw_width(len(self.fused_stages.ref_cols)),
                metrics_label=self.identity,
                prelude_key=(
                    f"{self.fused_stages.trace_key()}"
                    f"|g={self.group_indices}"
                    f"|c={[(c.kind.value, c.input_idx) for c in self.agg_calls]}"))
        return self._kernel

    @kernel.setter
    def kernel(self, k) -> None:
        self._kernel = k

    # -- fragment fusion (frontend/opt/fusion.py mutates in place) -------
    def adopt_fused_stages(self, fs, raw_input) -> None:
        """Absorb a filter/project run: `raw_input` becomes the direct
        input and `fs` (whose out_schema must equal the input schema
        this executor was planned against) runs inside the kernel's
        jitted apply. Only valid before the kernel is built."""
        from risingwave_tpu.frontend.opt.fusion import (
            agg_fusable_reason,
        )
        r = agg_fusable_reason(self)
        if r is not None:
            raise ValueError(f"agg is not fusion-eligible: {r}")
        got = [f.data_type for f in fs.out_schema]
        # fused_stages is None here (agg_fusable_reason refused
        # re-fusing above), so the planned-against schema IS the input
        want = [f.data_type for f in self.input.schema]
        if got != want:
            raise ValueError(
                f"fused stage chain emits {got}, agg planned on {want}")
        self.fused_stages = fs
        self.input = raw_input

    def drain_stage_metrics(self):
        """Per-logical-stage (identity, rows, chunks) attribution for
        the monitor; empty when unfused."""
        if self.fused_stages is None:
            return []
        return self.fused_stages.drain_stage_metrics()

    @property
    def _fused_raw_key_cols(self):
        """Raw input columns carrying the group-key VALUES through the
        absorbed run (None when any key is a computed expression) —
        cached; drives the sharded kernel's host-side owner counts."""
        if not hasattr(self, "_fused_raw_keys_cache"):
            self._fused_raw_keys_cache = None if \
                self.fused_stages is None else \
                self.fused_stages.input_positions(self.group_indices)
        return self._fused_raw_keys_cache

    # -- chunk path ------------------------------------------------------
    def _inputs(self, chunk: StreamChunk) -> Tuple:
        """Per call: (host input lane arrays, valid mask) — the kernel
        packs everything into one int32 matrix (one transfer)."""
        out = []
        for call, spec in zip(self.agg_calls, self.specs):
            if call.input_idx is None:          # count(*)
                out.append(((), None))
                continue
            c = chunk.columns[call.input_idx]
            in_lanes = spec.encode_input(np.asarray(c.values))
            ok = np.ones(chunk.capacity, dtype=bool) \
                if c.validity is None else np.asarray(c.validity)
            out.append((in_lanes, ok))
        return tuple(out)

    def _apply_chunk(self, chunk: StreamChunk) -> None:
        if self.fused_stages is not None:
            # fused fragment path: the RAW chunk ships as one int64
            # matrix; filter/project/key-encode/lane-encode all run
            # inside the kernel's jitted apply. Dispatch metrics are
            # counted by the kernel at REAL dispatch sites (one per
            # backlog flush), not per chunk — that granularity IS the
            # fusion win the bench compares.
            from risingwave_tpu.ops.fused import encode_raw_chunk
            raw = encode_raw_chunk(chunk, self.fused_stages.ref_cols)
            # when the group keys map to raw input columns, host-side
            # lanes serve two consumers: the heavy-hitter sketch (a
            # pre-filter superset of the grouped rows — safe when the
            # traced filter drops rows) and, for sharded kernels, the
            # skew-exact per-row owner routing bucket
            sharded = getattr(self.kernel, "counts_own_dispatches",
                              False)
            raw_keys = self._fused_raw_key_cols
            lanes = None
            if raw_keys is not None and (sharded or _hotkeys.ENABLED):
                lanes = self.key_codec.build(chunk, raw_keys)
                if _hotkeys.ENABLED:
                    _hotkeys.HOTKEYS.observe(
                        self.identity, lanes,
                        np.asarray(chunk.visibility), self.key_codec)
            if sharded:
                owners = None if lanes is None \
                    else self.kernel.owners_of(lanes)
                self.kernel.apply_raw(raw, chunk.cardinality(),
                                      owners=owners)
            else:
                self.kernel.apply_raw(raw, chunk.cardinality())
            return
        key_lanes = self.key_codec.build(chunk, self.group_indices)
        signs = np.asarray(chunk.signs())
        vis = np.asarray(chunk.visibility)
        if _hotkeys.ENABLED:
            # heavy-hitter sketch over the agg's group keys: the lanes
            # are already built for the kernel — the sketch adds one
            # hash+unique pass over the visible rows
            _hotkeys.HOTKEYS.observe(self.identity, key_lanes, vis,
                                     self.key_codec)
        if self._tier is not None:
            self._tier_touch(key_lanes, vis)
        # one kernel.apply below = one fused device dispatch (~2ms host
        # cost through the tunnel): the metric pair the coalescing
        # layer optimizes — fewer dispatches, denser rows per dispatch.
        # Sharded kernels count at their own jit sites instead
        # (kernel="sharded_agg", real epoch-batched launches).
        if not getattr(self.kernel, "counts_own_dispatches", False):
            _METRICS.device_dispatch.inc(1, executor=self.identity)
            _METRICS.rows_per_dispatch.observe(float(vis.sum()),
                                               executor=self.identity)
        inputs = list(self._inputs(chunk))
        if self.minput:
            self._apply_minput(chunk, key_lanes, signs, vis)
        for col, js in self._distinct_cols.items():
            _in_lanes0, ok0 = inputs[js[0]]
            mask = self._apply_distinct(col, chunk, key_lanes, signs,
                                        vis & ok0)
            for j in js:
                inputs[j] = (inputs[j][0], mask)
        self.kernel.apply(key_lanes, signs, vis, tuple(inputs))
        for j in self._hll_calls:
            self._apply_hll(j, chunk, key_lanes, signs, vis)

    def _apply_hll(self, j: int, chunk: StreamChunk,
                   key_lanes: np.ndarray, signs: np.ndarray,
                   vis: np.ndarray) -> None:
        """Scatter-max this chunk's rows into the per-group dense
        register files (vectorized; python work is O(groups in
        chunk))."""
        from risingwave_tpu.ops.hash_agg import hll_lanes
        from risingwave_tpu.stream.executors.keys import to_i64

        call = self.agg_calls[j]
        c = chunk.columns[call.input_idx]
        ok = vis if c.validity is None \
            else (vis & np.asarray(c.validity))
        rows = np.flatnonzero(ok)
        if not len(rows):
            return
        if (signs[rows] < 0).any():
            raise ValueError(
                "approx_count_distinct saw a retraction — the sketch "
                "is append-only (guarded at construction)")
        reg, rho = hll_lanes(to_i64(np.asarray(c.values)[rows]))
        rho8 = rho.astype(np.uint8)
        _uniq, inverse = np.unique(key_lanes[rows], axis=0,
                                   return_inverse=True)
        order = np.argsort(inverse, kind="stable")
        starts = np.searchsorted(inverse[order],
                                 np.arange(len(_uniq), dtype=np.int64))
        ends = np.append(starts[1:], len(order))
        g_cols = [(np.asarray(chunk.columns[i].values),
                   None if chunk.columns[i].validity is None
                   else np.asarray(chunk.columns[i].validity))
                  for i in self.group_indices]
        regs_d, dirty = self._hll_regs[j], self._hll_dirty[j]
        from risingwave_tpu.ops.hash_agg import HLL_M
        for u in range(len(_uniq)):
            r0 = int(rows[order[starts[u]]])
            gkey = tuple(
                None if (okc is not None and not okc[r0])
                else (gv[r0].item() if hasattr(gv[r0], "item")
                      else gv[r0])
                for gv, okc in g_cols)
            arr = regs_d.get(gkey)
            if arr is None:
                arr = regs_d[gkey] = np.zeros(HLL_M, dtype=np.uint8)
            sel = order[starts[u]:ends[u]]
            np.maximum.at(arr, reg[sel], rho8[sel])
            dirty.add(gkey)

    # -- per-(group, value) multisets (minput + distinct) ----------------
    def _multiset_groups(self, chunk: StreamChunk, key_lanes: np.ndarray,
                         signs: np.ndarray, ok: np.ndarray,
                         input_idx: int, vals_override=None):
        """Vectorized grouping of visible rows by (group key, value).

        Returns (rows, inverse, n_uniq, deltas, key_tuple_fn, order,
        starts) — python work is O(distinct keys), not O(rows)
        (hash_agg.rs minput/distinct parity without the per-row loop).
        """
        from risingwave_tpu.stream.executors.keys import to_i64

        rows = np.flatnonzero(ok)
        if not len(rows):
            return None
        c = chunk.columns[input_idx]
        vals = vals_override if vals_override is not None \
            else np.asarray(c.values)
        comp = np.empty((len(rows), key_lanes.shape[1] + 1),
                        dtype=np.int64)
        comp[:, :key_lanes.shape[1]] = key_lanes[rows]
        if vals.dtype == object:
            # host-typed values (string_agg/array_agg): EXACT local
            # interning for the grouping image only — ids live for this
            # call alone, so nothing accumulates across the stream (a
            # hash image could merge distinct values; np.unique cannot
            # sort mixed None/str)
            local: Dict[object, int] = {}
            comp[:, -1] = np.fromiter(
                (local.setdefault(v, len(local))
                 for v in vals[rows].tolist()),
                dtype=np.int64, count=len(rows))
        else:
            comp[:, -1] = to_i64(vals[rows])
        _uniq, inverse = np.unique(comp, axis=0, return_inverse=True)
        n_uniq = int(inverse.max()) + 1
        deltas = np.zeros(n_uniq, dtype=np.int64)
        np.add.at(deltas, inverse, signs[rows])
        # first chunk-row index per unique key (stable order)
        order = np.argsort(inverse, kind="stable")
        starts = np.searchsorted(inverse[order],
                                 np.arange(n_uniq, dtype=np.int64))
        first_rows = rows[order[starts]]
        g_cols = [(np.asarray(chunk.columns[i].values),
                   None if chunk.columns[i].validity is None
                   else np.asarray(chunk.columns[i].validity))
                  for i in self.group_indices]

        def _pyval(x):
            return x.item() if hasattr(x, "item") else x

        def key_tuple(u: int) -> tuple:
            r = int(first_rows[u])
            group = tuple(
                None if (okc is not None and not okc[r])
                else _pyval(gv[r])
                for gv, okc in g_cols)
            return group + (_pyval(vals[r]),)

        return rows, inverse, n_uniq, deltas, key_tuple, order, starts

    def _apply_minput(self, chunk: StreamChunk, key_lanes: np.ndarray,
                      signs: np.ndarray, vis: np.ndarray) -> None:
        """Maintain the per-call value multisets; remember which groups
        saw deletes (only those can have stale device extremes)."""
        del_rows = np.flatnonzero(vis & (signs < 0))
        for r in del_rows.tolist():
            self._deleted_lanes.add(tuple(key_lanes[r].tolist()))
        for j in self.minput:
            call = self.agg_calls[j]
            c = chunk.columns[call.input_idx]
            vals_override = None
            if call.kind == AggKind.ARRAY_AGG:
                # pg array_agg PRESERVES NULL elements: feed them into
                # the multiset (string_agg and MIN/MAX skip NULLs); a
                # device-typed column needs an object view so NULL
                # slots carry None instead of buffer fill
                ok = vis
                if c.validity is not None and c.data_type.is_device:
                    vo = np.asarray(c.values).astype(object)
                    vo[~np.asarray(c.validity)] = None
                    vals_override = vo
            else:
                ok = vis if c.validity is None \
                    else vis & np.asarray(c.validity)
            ms = self._multiset_groups(chunk, key_lanes, signs, ok,
                                       call.input_idx,
                                       vals_override=vals_override)
            if ms is None:
                continue
            _rows, _inv, n_uniq, deltas, key_tuple, _o, _s = ms
            pend = self._minput_pending.setdefault(j, {})
            for u in np.flatnonzero(deltas != 0).tolist():
                key = key_tuple(u)
                pend[key] = pend.get(key, 0) + int(deltas[u])

    def _apply_distinct(self, col: int, chunk: StreamChunk,
                        key_lanes: np.ndarray, signs: np.ndarray,
                        ok: np.ndarray) -> np.ndarray:
        """DISTINCT gating (aggregation/distinct.rs): per (group, value)
        multiset — the device kernel sees ONE representative row only
        when the value's multiplicity crosses zero, with the chunk sign
        matching the crossing direction. Returns the call's new valid
        mask."""
        new_ok = np.zeros(chunk.capacity, dtype=bool)
        ms = self._multiset_groups(chunk, key_lanes, signs, ok, col)
        if ms is None:
            return new_ok
        rows, inverse, n_uniq, deltas, key_tuple, order, starts = ms
        mult = self._distinct_mult.setdefault(col, {})
        pend = self._distinct_pending.setdefault(col, {})
        srt = inverse[order]
        for u in range(n_uniq):
            d = int(deltas[u])
            if d == 0:
                continue
            key = key_tuple(u)
            old = mult.get(key, 0)
            new = old + d
            if new < 0:
                raise ValueError(
                    f"distinct retract below zero for {key}")
            if new == 0:
                del mult[key]
            else:
                mult[key] = new
            pend[key] = pend.get(key, 0) + d
            eff = (1 if new > 0 else 0) - (1 if old > 0 else 0)
            if eff == 0:
                continue
            # representative row with the matching sign (exists: the
            # net delta moved in that direction)
            lo = int(starts[u])
            hi = int(starts[u + 1]) if u + 1 < n_uniq else len(srt)
            cand = rows[order[lo:hi]]
            match = cand[signs[cand] == eff]
            new_ok[int(match[0])] = True
        return new_ok

    @staticmethod
    def _write_multiset_pending(pending: Dict[int, Dict[tuple, int]],
                                tables: Dict[int, StateTable]) -> None:
        """Write buffered multiset deltas through to the StateTables
        (once per barrier; reads during recompute then see them)."""
        for j, deltas in pending.items():
            table = tables[j]
            for key, d in deltas.items():
                if d == 0:
                    continue
                cur = table.get_row(key)
                cnt = (0 if cur is None else cur[-1]) + d
                row = key + (cnt,)
                if cur is None:
                    assert cnt > 0, f"retract of unseen value {key}"
                    table.insert(row)
                elif cnt == 0:
                    table.delete(cur)
                else:
                    table.update(cur, row)
        pending.clear()

    def _write_minput_pending(self) -> None:
        self._write_multiset_pending(self._minput_pending, self.minput)

    # -- cold tier (state/tier.py) ---------------------------------------
    def _tier_register(self) -> None:
        """Register with the global tier at execute() start — only
        executors that actually RUN appear in the registry."""
        import weakref
        tref = weakref.ref(self)

        def _evict_cb(keys):
            s = tref()
            return 0 if s is None else s._tier_evict(keys)

        self._tier_part = self._tier.register(
            f"{self.identity}#{id(self)}", _evict_cb,
            cap=int(self.tier_cap), nbytes=self._tier_nbytes)

    @staticmethod
    def _pyval(x):
        return x.item() if hasattr(x, "item") else x

    def _tier_touch(self, key_lanes: np.ndarray,
                    vis: np.ndarray) -> None:
        """LRU recency + reload-on-touch: the chunk's distinct group
        keys refresh the tier clock, and any that are COLD reload from
        their committed state rows BEFORE this chunk's device apply."""
        rows = np.flatnonzero(vis)
        if not len(rows):
            return
        uniq = np.unique(key_lanes[rows], axis=0)
        tuples = list(map(tuple, uniq.tolist()))
        self._tier.touch(self._tier_part, tuples, self._tier_seq)
        if self._cold_groups:
            need = [t for t in tuples if t in self._cold_groups]
            if need:
                self._reload_groups(need)

    def _reload_groups(self, lanes_ts: List[tuple]) -> None:
        """Reload evicted groups (the _reload_cold analog): device
        accumulators from the value-state row, distinct-multiset and
        HLL-register mirrors from their aux tables. Fully durable state
        makes this retraction-safe — a delete touching a cold group
        reloads first, then retracts against exact state."""
        from risingwave_tpu.ops.hash_agg import hll_estimate_dense
        ng = len(self.group_indices)
        rows: List[tuple] = []
        lanes_keep: List[tuple] = []
        groups: List[tuple] = []
        for lt in lanes_ts:
            vt = self._cold_groups.pop(lt)
            row = self.table.get_row(vt)
            if row is None:
                continue       # retired under a watermark while cold
            rows.append(row)
            lanes_keep.append(lt)
            groups.append(vt)
        if not rows:
            return
        keys = np.asarray(lanes_keep, dtype=np.int32)
        grows = np.asarray([int(r[ng]) for r in rows], dtype=np.int64)
        acc_cols = [
            np.asarray([0 if r[ng + 1 + j] is None else r[ng + 1 + j]
                        for r in rows], dtype=dt)
            for j, dt in enumerate(acc_dtypes(self.specs))]
        self.kernel.load_groups(keys, grows, acc_cols)
        for col, t in self.distinct_tables.items():
            mult = self._distinct_mult.setdefault(col, {})
            for vt in groups:
                for _pk, row in t.iter_prefix(list(vt)):
                    mult[tuple(row[:-1])] = int(row[-1])
        for j, t in self.hll_tables.items():
            for vt in groups:
                row = t.get_row(vt)
                if row is not None:
                    arr = np.frombuffer(row[-1], dtype=np.uint8).copy()
                    self._hll_regs[j][vt] = arr
                    self._hll_prev[j][vt] = int(
                        hll_estimate_dense(arr)[0])
        self._tier.note_reload(self._tier_part, len(rows))

    def _tier_evict(self, lanes_ts: List[tuple]) -> int:
        """Tier sweep callback (checkpoint barriers only, post-flush):
        move the given groups to the cold tier — device slots rebuild
        away, host mirrors drop, durable rows stay. Groups with NO
        durable row (retracted to zero, watermark-cleaned) are
        phantoms: skipped, not marked cold, not counted — the tier's
        counters are in keys ACTUALLY evicted."""
        mat = np.asarray(lanes_ts, dtype=np.int32)
        gk = self._group_key_host(mat)
        kept_lanes: List[tuple] = []
        kept_groups: List[tuple] = []
        for r, lt in enumerate(lanes_ts):
            vt = tuple(None if not ok[r] else self._pyval(vals[r])
                       for vals, ok in gk)
            if self.table.get_row(vt) is None:
                continue
            kept_lanes.append(lt)
            kept_groups.append(vt)
        if not kept_lanes:
            return 0
        self.kernel.evict_keys(np.asarray(kept_lanes, dtype=np.int32))
        for lt, vt in zip(kept_lanes, kept_groups):
            self._cold_groups[lt] = vt
        gset = set(kept_groups)
        ng = len(self.group_indices)
        for col, mult in self._distinct_mult.items():
            if mult:
                self._distinct_mult[col] = {
                    k: v for k, v in mult.items()
                    if k[:ng] not in gset}
        for j in self._hll_calls:
            self._hll_regs[j] = {k: v for k, v in
                                 self._hll_regs[j].items()
                                 if k not in gset}
            self._hll_prev[j] = {k: v for k, v in
                                 self._hll_prev[j].items()
                                 if k not in gset}
        self._deleted_lanes -= set(kept_lanes)
        return len(kept_lanes)

    def _tier_forget_expired(self, phys: int) -> None:
        """Watermark cleaning retired groups below `phys`: drop their
        cold markers (rows already range-deleted) and their resident
        tier entries (retired on device by retire_below)."""
        if self._cold_groups:
            self._cold_groups = {
                lt: vt for lt, vt in self._cold_groups.items()
                if vt[0] is None or vt[0] >= phys}
        part = self._tier_part
        if part is None or not part.keys:
            return
        from risingwave_tpu.ops import lanes as _lanes
        keys_list = list(part.keys)
        mat = np.asarray(keys_list, dtype=np.int64)
        ok = mat[:, 2] != 0
        v = _lanes.merge_i64(mat[:, 0].astype(np.int32),
                             mat[:, 1].astype(np.int32))
        dead = ok & (v < phys)
        if dead.any():
            self._tier.forget(part, [
                k for k, d in zip(keys_list, dead.tolist()) if d])

    # -- watermark state cleaning ----------------------------------------
    def _cleanable_type(self) -> bool:
        """Integer-family first group col only: the device compare runs
        on the bijective (hi, lo) i64 split, which is order-preserving
        for ints/timestamps but not for bit-cast floats."""
        dt = np.dtype(self.group_types[0].np_dtype)
        return np.issubdtype(dt, np.integer) or dt == np.dtype(bool)

    def _clean_state(self) -> None:
        """Retire groups below the watermark: device rebuild + ordered
        range delete from every state table. Runs after flush/advance
        (a dirty group must emit its last change before retirement);
        late rows for a retired group restart it from scratch — the
        same contract as the reference's cleaned state tables."""
        wm = self._clean_wm
        if wm is None or (self._cleaned_wm is not None
                          and wm <= self._cleaned_wm):
            return
        phys = int(wm)
        self.kernel.retire_below(0, phys)
        n = self.table.delete_below_prefix(phys)
        self._live_groups = max(0, self._live_groups - n)
        for t in self.minput.values():
            t.delete_below_prefix(phys)
        for col, t in self.distinct_tables.items():
            t.delete_below_prefix(phys)
            mult = self._distinct_mult.get(col)
            if mult:
                self._distinct_mult[col] = {
                    k: v for k, v in mult.items()
                    if k[0] is None or k[0] >= phys}
        for j, t in self.hll_tables.items():
            t.delete_below_prefix(phys)
            self._hll_regs[j] = {
                k: v for k, v in self._hll_regs[j].items()
                if k[0] is None or k[0] >= phys}
            self._hll_prev[j] = {
                k: v for k, v in self._hll_prev[j].items()
                if k[0] is None or k[0] >= phys}
        if self._tier is not None:
            self._tier_forget_expired(phys)
        self._cleaned_wm = wm
        _METRICS.agg_rows_cleaned.inc(n, executor=self.identity)

    INTERNER_GC_MIN = 4096

    def _maybe_gc_interner(self) -> None:
        """Retire group-key interner entries no live group references
        (bounded-by-live-state, VERDICT r3 weak #6). Runs every
        barrier; the gate uses the INCREMENTALLY-tracked live-group
        count (see _flush) so the O(live) table scan only happens when
        at least half the entries are provably dead."""
        codec = self.key_codec
        if not codec.interners:
            return
        total = codec.interner_entries()
        if total < self.INTERNER_GC_MIN or \
                total <= 2 * max(self._live_groups, 1) * \
                len(codec.interners):
            return
        live_cols: Dict[int, list] = {j: [] for j in codec.interners}
        n_live = 0
        for _pk, row in self.table.iter_rows():
            n_live += 1
            for j in live_cols:
                v = row[j]
                if v is not None:
                    live_cols[j].append(v)
        self._live_groups = n_live     # re-sync the incremental count
        for j, it in codec.interners.items():
            it.gc(live_cols[j])

    # -- barrier path ----------------------------------------------------
    def _group_key_host(self, keys: np.ndarray
                        ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Key lanes → per group col (values in col dtype, valid mask)."""
        return self.key_codec.decode(keys)

    def _flush(self) -> Optional[StreamChunk]:
        own = getattr(self.kernel, "counts_own_dispatches", False)
        if not own:
            _METRICS.device_dispatch.inc(1, executor=self.identity)
        fr = self.kernel.flush()
        if self.fused_stages is not None:
            # flush synchronized the queue — the per-stage row vectors
            # are landed DMAs; attribute them to the logical executors
            # inside the fused block (monitor drains at the barrier)
            sr = self.kernel.drain_stage_rows()
            if sr is not None:
                self.fused_stages.note_stage_rows(sr, 0)
        # the flush dispatch gathers the dirty groups — observe them so
        # the histogram count tracks the dispatch counter exactly
        if not own:
            _METRICS.rows_per_dispatch.observe(float(fr.n),
                                               executor=self.identity)
        _METRICS.agg_dirty_groups.set(fr.n, executor=self.identity)
        _METRICS.agg_table_capacity.set(self.kernel.capacity,
                                        executor=self.identity)
        gk = None
        host_prev = None
        if self._host_calls and fr.n:
            # host-agg PREV outputs come from the multiset tables as
            # of the LAST barrier — read before this epoch's writes
            gk = self._group_key_host(fr.keys)
            host_prev = self._host_agg_outputs(fr, gk)
        if self.minput:
            self._write_minput_pending()
        if self._distinct_pending:
            self._write_multiset_pending(self._distinct_pending,
                                         self.distinct_tables)
        if fr.n == 0:
            self._deleted_lanes.clear()
            self.kernel.advance()
            return None
        if gk is None:
            gk = self._group_key_host(fr.keys)   # decode lanes once
        if self.minput and self._deleted_lanes:
            self._recompute_extremes(fr, gk)
        if self._host_calls:
            host_new = self._host_agg_outputs(fr, gk)
            for j in self._host_calls:
                fr.prev_outs[j], fr.prev_nulls[j] = host_prev[j]
                fr.outs[j], fr.nulls[j] = host_new[j]
        if self._hll_calls:
            self._overwrite_hll_outputs(fr, gk)
            self._persist_hll_dirty()
        self._deleted_lanes.clear()
        outs, nulls = fr.outs, fr.nulls
        pouts, pnulls = fr.prev_outs, fr.prev_nulls
        cur_live = fr.group_rows > 0
        was = fr.was_emitted
        changed = np.zeros(fr.n, dtype=bool)
        for o, po, nu, pnu in zip(outs, pouts, nulls, pnulls):
            changed |= (nu != pnu) | (~nu & (o != po))
        ins_i = np.flatnonzero(cur_live & ~was)
        upd_i = np.flatnonzero(cur_live & was & changed)
        del_i = np.flatnonzero(~cur_live & was)
        # incremental live-group count (cheap gate for interner GC)
        self._live_groups += len(ins_i) - len(del_i)
        # persistence must also cover groups whose outputs are unchanged
        # but whose internal state (row/non-null counts) moved — otherwise
        # recovery reloads a stale row count
        state_moved = fr.group_rows != fr.prev_rows
        for nn, pnn in zip(fr.nns, fr.prev_nns):
            if nn is not None:
                state_moved |= nn != pnn
        persist_upd_i = np.flatnonzero(
            cur_live & was & (changed | state_moved))
        self._persist(fr, gk, ins_i, persist_upd_i, del_i)
        self.kernel.advance()
        t = len(ins_i) + 2 * len(upd_i) + len(del_i)
        if t == 0:
            return None
        cap = next_pow2(t)

        def emit_col(cur: np.ndarray, prev: np.ndarray, dtype) -> np.ndarray:
            out = np.zeros(cap, dtype=dtype)
            k = len(ins_i)
            out[:k] = cur[ins_i]
            out[k:k + 2 * len(upd_i):2] = prev[upd_i]
            out[k + 1:k + 2 * len(upd_i):2] = cur[upd_i]
            out[k + 2 * len(upd_i):t] = prev[del_i]
            return out

        columns: List[Column] = []
        for (vals, ok), dt in zip(gk, self.group_types):
            v = emit_col(vals, vals, dt.np_dtype)
            okc = emit_col(ok, ok, bool)
            columns.append(Column(dt, v, None if okc.all() else okc))
        for j, (o, po, nu, pnu) in enumerate(zip(outs, pouts, nulls,
                                                 pnulls)):
            dt = self.schema[len(self.group_indices) + j].data_type
            v = emit_col(o.astype(dt.np_dtype), po.astype(dt.np_dtype),
                         dt.np_dtype)
            nuc = emit_col(nu, pnu, bool)
            columns.append(Column(dt, v, None if not nuc.any() else ~nuc))
        ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
        k = len(ins_i)
        ops[k:k + 2 * len(upd_i):2] = int(Op.UPDATE_DELETE)
        ops[k + 1:k + 2 * len(upd_i):2] = int(Op.UPDATE_INSERT)
        ops[k + 2 * len(upd_i):t] = int(Op.DELETE)
        vis = np.zeros(cap, dtype=bool)
        vis[:t] = True
        return StreamChunk(self.schema, columns, vis, ops)

    def _overwrite_hll_outputs(self, fr, gk) -> None:
        """Replace the placeholder approx outputs with estimates from
        the dense sketches (and exact prev estimates for update
        pairs)."""
        from risingwave_tpu.ops.hash_agg import HLL_M, hll_estimate_dense

        gkeys = [tuple(
            None if not ok[r]
            else (vals[r].item() if hasattr(vals[r], "item")
                  else vals[r])          # interned VARCHAR keys decode
            for vals, ok in gk)          # to plain python strings
                 for r in range(fr.n)]
        for j in self._hll_calls:
            regs_d, prev_d = self._hll_regs[j], self._hll_prev[j]
            dirty = self._hll_dirty[j]
            # estimate ONLY dirty sketches (64KB register files: a
            # full re-stack per flushed row would move gigabytes per
            # barrier at scale); clean groups reuse the cached value
            fresh = [g for g in dict.fromkeys(gkeys) if g in dirty]
            ests = {}
            if fresh:
                mat = np.stack([regs_d[g] for g in fresh])
                for g, e in zip(fresh,
                                hll_estimate_dense(mat).tolist()):
                    ests[g] = int(e)
            for r, g in enumerate(gkeys):
                prev = prev_d.get(g)
                new = ests.get(g)
                if new is None:
                    new = prev if prev is not None else 0
                fr.outs[j][r] = new
                fr.nulls[j][r] = False
                fr.prev_outs[j][r] = 0 if prev is None else prev
                fr.prev_nulls[j][r] = prev is None
                prev_d[g] = new

    def _persist_hll_dirty(self) -> None:
        """Upsert dirty register files (one BYTEA row per group; the
        sketch table is sanity-off so same-pk rewrites shadow)."""
        for j in self._hll_calls:
            table, regs_d = self.hll_tables[j], self._hll_regs[j]
            for gkey in self._hll_dirty[j]:
                table.insert(gkey + (regs_d[gkey].tobytes(),))
            self._hll_dirty[j].clear()

    def _recompute_extremes(self, fr, gk) -> None:
        """Correct stale device MIN/MAX for groups that saw deletes by
        scanning their surviving value multiset, then patch the device
        accumulators (hash_agg.rs + minput.rs flush semantics)."""
        need = [r for r in range(fr.n)
                if tuple(fr.keys[r].tolist()) in self._deleted_lanes]
        if not need:
            return
        for r in need:
            group = tuple(
                None if not ok[r]
                else (vals[r].item() if hasattr(vals[r], "item")
                      else vals[r])
                for vals, ok in gk)
            for j, table in self.minput.items():
                if self.specs[j].kind in HOST_AGG_KINDS:
                    continue       # host outputs recompute separately
                is_max = self.specs[j].kind == AggKind.MAX
                best = None
                for _pk, row in table.iter_prefix(group):
                    v = row[-2]
                    if best is None or (v > best if is_max else v < best):
                        best = v
                nn = fr.nns[j][r]
                if nn == 0 or best is None:
                    fr.nulls[j][r] = True
                    fr.nns[j][r] = 0
                else:
                    fr.outs[j][r] = best
                    fr.nulls[j][r] = False
        decoded = [
            (fr.outs[j], fr.nns[j])
            if j in self.minput
            and self.specs[j].kind not in HOST_AGG_KINDS else None
            for j in range(len(self.specs))]
        self.kernel.patch_accs(decoded, raw_accs=fr.raw_accs)

    def _host_agg_outputs(self, fr, gk):
        """string_agg/array_agg outputs for the flushed groups, read
        from the value multisets. Values compose in VALUE order (the
        multiset has no arrival order and pg leaves the order
        unspecified without an in-agg ORDER BY; value order is the
        deterministic, recovery-stable choice)."""
        out: Dict[int, tuple] = {}
        for j in self._host_calls:
            call = self.agg_calls[j]
            table = self.minput[j]
            vals_col = np.empty(fr.n, dtype=object)
            nulls_col = np.zeros(fr.n, dtype=bool)
            for r in range(fr.n):
                group = tuple(
                    None if not ok[r] else
                    (vals[r].item() if hasattr(vals[r], "item")
                     else vals[r])
                    for vals, ok in gk)
                items: List = []
                for _pk, row in table.iter_prefix(group):
                    v, cnt = row[-2], int(row[-1])
                    items.extend([v] * cnt)
                if not items:
                    nulls_col[r] = True
                elif call.kind == AggKind.STRING_AGG:
                    vals_col[r] = call.delimiter.join(
                        str(v) for v in items if v is not None)
                else:                # ARRAY_AGG keeps NULL elements
                    vals_col[r] = tuple(items)
            out[j] = (vals_col, nulls_col)
        return out

    def _state_rows(self, fr, gk, idx: np.ndarray,
                    prev: bool) -> List[tuple]:
        """Physical value-state rows for the given flush indices
        (per-call column layout: AggSpec.host_acc_cols)."""
        from risingwave_tpu.ops.hash_agg import _call_slices
        rows_col = fr.prev_rows if prev else fr.group_rows
        outs = fr.prev_outs if prev else fr.outs
        nulls = fr.prev_nulls if prev else fr.nulls
        nns = fr.prev_nns if prev else fr.nns
        raw = fr.prev_raw_accs if prev else fr.raw_accs
        cols: List[list] = []
        for vals, ok in gk:
            sel = vals[idx]
            okl = ok[idx]
            cols.append([v if o else None
                         for v, o in zip(sel.tolist(), okl.tolist())])
        cols.append(rows_col[idx].tolist())
        for j, (spec, sl) in enumerate(
                zip(self.specs, _call_slices(self.specs))):
            nn = nns[j]
            cols.extend(spec.host_acc_cols(
                outs[j][idx], nulls[j][idx],
                None if nn is None else nn[idx],
                None if raw is None else
                [raw[k][idx] for k in range(sl.start, sl.stop)]))
        return list(zip(*cols)) if cols else []

    def _persist(self, fr, gk, ins_i, upd_i, del_i) -> None:
        # bulk row APIs: one vectorized pk-encode pass per flush class
        # instead of per-row vnode hashing (the r3 q8 profile's top cost)
        self.table.insert_rows(self._state_rows(fr, gk, ins_i, prev=False))
        self.table.update_rows(self._state_rows(fr, gk, upd_i, prev=True),
                               self._state_rows(fr, gk, upd_i, prev=False))
        self.table.delete_rows(self._state_rows(fr, gk, del_i, prev=True))

    # -- recovery --------------------------------------------------------
    def _recover(self) -> None:
        keys_l: List[np.ndarray] = []
        rows_l: List[int] = []
        accs_l: List[tuple] = []
        ng = len(self.group_indices)
        for _pk, row in self.table.iter_rows():
            keys_l.append(self.key_codec.lanes_of_values(row[:ng]))
            rows_l.append(int(row[ng]))
            accs_l.append(row[ng + 1:])
        self._live_groups = len(rows_l)
        if not rows_l:
            return
        if self._tier is not None:
            # recovery rebuilds EVERYTHING resident (cold markers do
            # not survive a crash); seeding the tier clock with the
            # recovered keys lets the first checkpoint sweep re-apply
            # the cap instead of carrying the full set forever
            self._tier.touch(self._tier_part,
                             [tuple(k.tolist()) for k in keys_l],
                             self._tier_seq)
        keys = np.stack(keys_l)
        dts = acc_dtypes(self.specs)
        acc_cols = []
        for j, dt in enumerate(dts):
            col = np.asarray([0 if a[j] is None else a[j]
                              for a in accs_l], dtype=dt)
            acc_cols.append(col)
        self.kernel.rebuild(keys, np.asarray(rows_l, dtype=np.int64),
                            acc_cols)

    # -- main loop -------------------------------------------------------
    async def execute(self) -> AsyncIterator[Message]:
        it = self.input.execute()
        first = await it.__anext__()
        assert is_barrier(first), f"expected init barrier, got {first!r}"
        if self._tier is not None:
            self._tier_register()
        self.table.init_epoch(first.epoch)
        for t in self.minput.values():
            t.init_epoch(first.epoch)
        from risingwave_tpu.ops.hash_agg import hll_estimate_dense
        for j, t in self.hll_tables.items():
            t.init_epoch(first.epoch)
            for _pk, row in t.iter_rows():
                gkey = tuple(row[:-1])
                arr = np.frombuffer(row[-1], dtype=np.uint8).copy()
                self._hll_regs[j][gkey] = arr
                # emitted outputs were committed with this sketch —
                # prev estimates must match them exactly
                self._hll_prev[j][gkey] = int(
                    hll_estimate_dense(arr)[0])
        for col, t in self.distinct_tables.items():
            t.init_epoch(first.epoch)
            mult = {}
            for _pk, row in t.iter_rows():
                mult[tuple(row[:-1])] = int(row[-1])
            if mult:
                self._distinct_mult[col] = mult
        self._recover()
        yield first
        try:
            async for msg in it:
                if is_chunk(msg):
                    self._apply_chunk(msg)
                elif is_barrier(msg):
                    out = self._flush()
                    self._clean_state()
                    self._maybe_gc_interner()
                    self.table.commit(msg.epoch)
                    for t in self.minput.values():
                        t.commit(msg.epoch)
                    for t in self.hll_tables.values():
                        t.commit(msg.epoch)
                    for t in self.distinct_tables.values():
                        t.commit(msg.epoch)
                    if self._tier is not None:
                        # sweep at CHECKPOINT barriers only, after the
                        # flush+advance+commit above — the evicted
                        # groups are provably clean and durable, and no
                        # epoch is in flight (tier.py epoch-sequencing)
                        self._tier_seq += 1
                        if msg.kind.is_checkpoint:
                            self._tier.sweep(self._tier_part,
                                             self._tier_seq)
                    if out is not None:
                        yield out
                    yield msg
                elif is_watermark(msg):
                    # fused blocks first map the watermark through the
                    # absorbed projects' derivations (the sequential
                    # ProjectExecutors' exact per-message semantics)
                    wms = [msg] if self.fused_stages is None \
                        else self.fused_stages.derive_watermarks(msg)
                    # forward only group-key watermarks, re-indexed
                    for m in wms:
                        if m.col_idx in self.group_indices:
                            pos = self.group_indices.index(m.col_idx)
                            if pos == 0 and self._cleanable_type():
                                self._clean_wm = m.value
                            yield m.with_idx(pos)
        finally:
            # executor teardown: release this identity's gauge series
            _METRICS.agg_dirty_groups.remove(executor=self.identity)
            _METRICS.agg_table_capacity.remove(executor=self.identity)
            if self._tier_part is not None:
                self._tier.unregister(self._tier_part)
