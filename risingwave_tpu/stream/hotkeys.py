"""Heavy-hitter telemetry: space-saving sketches over join/agg keys.

"Parallelism won't help, the key is skewed" must be a named,
cross-checked verdict before the autoscaler spends a rescale on it
(ISSUE 16 / ROADMAP item 5). Every hash-join build/probe side and
hash-agg input feeds its chunk key lanes through a space-saving sketch
(Metwally et al.): k counters, an over-full insert evicts the minimum
counter and inherits its count as the new key's error bound. The
classic guarantees carry over: any key with true frequency above
``total/k`` is present, and every counter overestimates by at most its
recorded error — so with k=64 the share estimate for a genuinely hot
key (say the 90%-of-stream ad campaign) is exact to well under the
5pp acceptance bound, because evictions only ever recycle cold
counters.

The vectorization contract: the per-row work is NumPy (hash the
(n, 3·ncols) int32 key lanes to one int64 per row, ``np.unique`` the
visible ones); only the per-*unique* merge is a Python loop, capped at
``_PER_CHUNK`` entries per chunk. Keys stay as opaque hashes plus one
representative lane row on the hot path — decoding through the
executor's KeyCodec happens at read time (rw_hot_keys, ctl, walker).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

ENABLED = True


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)


# sketch capacity (k) and the per-chunk unique-key merge cap (top-m by
# chunk count; dropping the chunk's own cold tail below m cannot demote
# a sustained heavy hitter)
K = 64
_PER_CHUNK = 128

# rw_hot_keys reports at most this many ranks per input; the walker's
# skew verdict threshold lives in stream/bottleneck.py
TOP_N = 8


class _Sketch:
    """One space-saving sketch over a single executor input."""

    __slots__ = ("counts", "errs", "lanes", "total", "codec", "mult")

    def __init__(self) -> None:
        self.counts: Dict[int, int] = {}   # key hash -> est count
        self.errs: Dict[int, int] = {}     # key hash -> max overcount
        self.lanes: Dict[int, np.ndarray] = {}  # representative row
        self.total = 0                     # all observed rows
        self.codec = None                  # KeyCodec for display
        self.mult: Optional[np.ndarray] = None

    def observe(self, key_lanes: np.ndarray, vis: np.ndarray,
                codec) -> None:
        if self.codec is None:
            self.codec = codec
        lanes = key_lanes[vis] if vis is not None else key_lanes
        n = int(lanes.shape[0])
        if n == 0:
            return
        self.total += n
        if self.mult is None or self.mult.shape[0] != lanes.shape[1]:
            # fixed odd multipliers: a cheap universal-ish hash of the
            # (hi, lo, valid) lane columns down to one int64 per row
            with np.errstate(over="ignore"):
                self.mult = (2 * np.arange(1, lanes.shape[1] + 1,
                                           dtype=np.int64) - 1) \
                    * np.uint64(0x9E3779B97F4A7C15).astype(np.int64)
        with np.errstate(over="ignore"):
            hashes = lanes.astype(np.int64) @ self.mult
        uniq, first, cnt = np.unique(hashes, return_index=True,
                                     return_counts=True)
        if uniq.shape[0] > _PER_CHUNK:
            top = np.argpartition(cnt, -_PER_CHUNK)[-_PER_CHUNK:]
            uniq, first, cnt = uniq[top], first[top], cnt[top]
        counts = self.counts
        for h, idx, c in zip(uniq.tolist(), first.tolist(),
                             cnt.tolist()):
            cur = counts.get(h)
            if cur is not None:
                counts[h] = cur + c
                continue
            if len(counts) < K:
                counts[h] = c
                self.errs[h] = 0
                self.lanes[h] = np.array(lanes[idx])
                continue
            # evict the minimum counter; the newcomer inherits its
            # count as both floor and error bound (space-saving)
            victim = min(counts, key=counts.get)
            floor = counts.pop(victim)
            self.errs.pop(victim, None)
            self.lanes.pop(victim, None)
            counts[h] = floor + c
            self.errs[h] = floor
            self.lanes[h] = np.array(lanes[idx])

    def top(self, n: int) -> List[Tuple[int, int, int]]:
        """[(hash, est_count, max_err)] by estimated count."""
        order = sorted(self.counts, key=self.counts.get, reverse=True)
        return [(h, self.counts[h], self.errs.get(h, 0))
                for h in order[:n]]

    def display(self, h: int) -> str:
        lane = self.lanes.get(h)
        if lane is None or self.codec is None:
            return f"#{h & 0xFFFFFFFF:08x}"
        try:
            cols = self.codec.decode(lane.reshape(1, -1))
            parts = []
            for values, valid in cols:
                v = values[0] if len(values) else None
                parts.append("NULL" if (len(valid) and not valid[0])
                             else str(v))
            return "|".join(parts)
        except Exception:               # noqa: BLE001 — display only
            return f"#{h & 0xFFFFFFFF:08x}"


class HotKeys:
    """Process-global registry of per-executor-input sketches."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sketches: Dict[str, _Sketch] = {}     # identity -> sketch
        self._fragment: Dict[str, str] = {}          # identity -> mv
        self._remote: Dict[str, List[tuple]] = {}    # worker -> rows

    # -- hot path -------------------------------------------------------
    def observe(self, identity: str, key_lanes, vis, codec) -> None:
        if not ENABLED or key_lanes is None:
            return
        with self._lock:
            sk = self._sketches.get(identity)
            if sk is None:
                sk = self._sketches[identity] = _Sketch()
        sk.observe(np.asarray(key_lanes), vis, codec)

    def bind_fragment(self, identity: str, fragment: str) -> None:
        with self._lock:
            self._fragment[identity] = fragment

    # -- read side ------------------------------------------------------
    def hot_share(self, identity: str,
                  min_share: float = 0.0) -> Optional[Tuple[str, float]]:
        """(display_key, share) of the input's hottest key, if its
        *guaranteed* share (estimate minus error) clears min_share —
        the bottleneck walker's skew test. Conservative on purpose: a
        skew verdict vetoes a scale-up, so it must not fire on an
        overcounted cold key."""
        with self._lock:
            sks = [sk for i, sk in self._sketches.items()
                   if i == identity
                   or i.partition("/")[0] == identity]
        best = None
        for sk in sks:
            if sk.total == 0:
                continue
            top = sk.top(1)
            if not top:
                continue
            h, est, err = top[0]
            share = (est - err) / sk.total
            if share >= min_share and \
                    (best is None or share > best[1]):
                best = (sk.display(h), share)
        return best

    def rows(self) -> List[tuple]:
        """rw_hot_keys payload: (mv, executor, rank, key, est_count,
        share, max_share_err) — local sketches plus drained worker
        rows."""
        rows = self._local_rows()
        with self._lock:
            for remote in self._remote.values():
                rows.extend(remote)
        rows.sort(key=lambda r: (r[0], r[1], r[2]))
        return rows

    def _local_rows(self) -> List[tuple]:
        with self._lock:
            items = list(self._sketches.items())
            frag = dict(self._fragment)
        rows = []
        for identity, sk in items:
            if sk.total == 0:
                continue
            # join inputs suffix the executor identity ("/0", "/1") —
            # the fragment binding is on the base identity
            mv = frag.get(identity) \
                or frag.get(identity.partition("/")[0], "")
            for rank, (h, est, err) in enumerate(sk.top(TOP_N)):
                rows.append((mv, identity, rank, sk.display(h),
                             int(est), round(est / sk.total, 4),
                             round(err / sk.total, 4)))
        return rows

    # -- series lifecycle ----------------------------------------------
    def unregister_fragment(self, fragment: str) -> None:
        with self._lock:
            dead = {i for i, f in self._fragment.items()
                    if f == fragment}
            for i in dead:
                self._fragment.pop(i, None)
            for i in [s for s in self._sketches
                      if s in dead or s.partition("/")[0] in dead]:
                self._sketches.pop(i, None)
            self._remote = {
                w: [r for r in rows if r[0] != fragment]
                for w, rows in self._remote.items()}

    # -- cross-process merge (cluster `signals` drain) -------------------
    def drain_rows(self) -> List[tuple]:
        """Snapshot local rows, already decoded to primitives (an
        executor input lives in one process, so the coordinator can
        union worker snapshots without counter merging)."""
        return self._local_rows()

    def ingest(self, rows, worker: str = "") -> int:
        rows = [tuple(r) for r in rows]
        with self._lock:
            self._remote[worker] = rows
        return len(rows)

    def clear(self) -> None:
        with self._lock:
            self._sketches.clear()
            self._fragment.clear()
            self._remote.clear()


HOTKEYS = HotKeys()
