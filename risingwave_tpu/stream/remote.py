"""Remote exchange: the cross-node data plane over TCP.

Reference parity: ExchangeService.GetStream (proto/task_service.proto:
113, src/compute/src/rpc/service/exchange_service.rs) with credit-based
flow control (src/stream/src/executor/exchange/{permit.rs:35,
input.rs:103}; src/rpc_client/src/compute_client.rs:110) and the
serialized StreamChunk wire shape (proto/data.proto:136). TPU-native
notes: this path carries HOST chunks between processes/hosts (DCN);
intra-mesh exchange is the all_to_all collective (parallel/exchange.py)
— two transports, one dispatch abstraction.

Wire protocol (all big-endian):
    frame   = tag(1B) ++ len(4B) ++ payload
    tags    : 'H' hello {up_actor, down_actor, initial credits}
              'D' data chunk   'B' barrier   'W' watermark
              'C' credit grant (receiver → sender; chunk budget)
Chunks serialize schema-light: per column dtype tag + raw numpy bytes
(device types) or value-codec rows (host types); barriers carry kind +
epochs + the mutation kinds the data plane must forward.
"""

from __future__ import annotations

import asyncio
import struct
from typing import AsyncIterator, Dict, Optional, Tuple

import numpy as np

from risingwave_tpu.common.chunk import Column, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.storage.value_codec import decode_row, encode_row
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Barrier, BarrierKind, Message, PauseMutation, ResumeMutation,
    StopMutation, Watermark, is_barrier, is_chunk,
)
from risingwave_tpu.stream.trace_ctx import (
    barrier_trailer, record_remote_transfer,
)

# stable numeric wire ids per logical type (enum definition order;
# append-only as types are added)
_TYPE_IDS = {dt: i for i, dt in enumerate(DataType)}
_TYPE_FROM_ID = {i: dt for dt, i in _TYPE_IDS.items()}

_MUTATIONS = {0: None, 1: StopMutation, 2: PauseMutation,
              3: ResumeMutation}
_MUTATION_IDS = {type(None): 0, StopMutation: 1, PauseMutation: 2,
                 ResumeMutation: 3}


# -- serde ----------------------------------------------------------------


def encode_chunk(chunk: StreamChunk) -> bytes:
    # compact before encoding: invisible (masked/padding) rows are
    # pure wire waste — a 1/N-visible dispatch slice would otherwise
    # serialize N× its data. Zero-visible chunks (senders normally
    # pre-suppress them) shrink to the minimal empty bucket.
    from risingwave_tpu.stream.coalesce import compact
    dense = compact(chunk)
    chunk = dense if dense is not None else StreamChunk.from_pydict(
        chunk.schema, {f.name: [] for f in chunk.schema}, capacity=8)
    out = bytearray()
    cap = chunk.capacity
    out += struct.pack(">IH", cap, len(chunk.columns))
    out += np.asarray(chunk.visibility, dtype=np.uint8).tobytes()
    out += np.asarray(chunk.ops, dtype=np.int8).tobytes()
    for c in chunk.columns:
        out += struct.pack(">B", _TYPE_IDS[c.data_type])
        has_validity = c.validity is not None
        out += struct.pack(">B", 1 if has_validity else 0)
        if has_validity:
            out += np.asarray(c.validity, dtype=np.uint8).tobytes()
        if c.data_type.is_device:
            out += np.ascontiguousarray(c.values).tobytes()
        else:
            # host object columns carry NULL in-band as None (see
            # chunk._make_column) — the value codec preserves it
            row = encode_row(tuple(c.values.tolist()))
            out += struct.pack(">I", len(row)) + row
    return bytes(out)


def decode_chunk(data: bytes, schema: Schema) -> StreamChunk:
    cap, ncols = struct.unpack_from(">IH", data, 0)
    pos = 6
    vis = np.frombuffer(data[pos:pos + cap], dtype=np.uint8).astype(bool)
    pos += cap
    ops = np.frombuffer(data[pos:pos + cap], dtype=np.int8).copy()
    pos += cap
    cols = []
    assert ncols == len(schema), (ncols, len(schema))
    for f in schema:
        type_id, has_validity = struct.unpack_from(">BB", data, pos)
        assert type_id == _TYPE_IDS[f.data_type], (type_id, f.data_type)
        pos += 2
        validity = None
        if has_validity:
            validity = np.frombuffer(
                data[pos:pos + cap], dtype=np.uint8).astype(bool)
            pos += cap
        if f.data_type.is_device:
            dt = np.dtype(f.data_type.np_dtype)
            nbytes = cap * dt.itemsize
            vals = np.frombuffer(
                data[pos:pos + nbytes], dtype=dt).copy()
            pos += nbytes
        else:
            ln = struct.unpack_from(">I", data, pos)[0]
            pos += 4
            decoded = decode_row(data[pos:pos + ln])
            pos += ln
            vals = np.empty(cap, dtype=object)
            vals[:] = list(decoded)
        cols.append(Column(f.data_type, vals, validity))
    return StreamChunk(schema, cols, vis, ops)


def encode_barrier(b: Barrier) -> bytes:
    kind = {BarrierKind.INITIAL: 0, BarrierKind.BARRIER: 1,
            BarrierKind.CHECKPOINT: 2}[b.kind]
    mid = _MUTATION_IDS.get(type(b.mutation))
    if mid is None:
        raise ValueError(
            f"mutation {type(b.mutation).__name__} not remote-safe yet")
    out = struct.pack(">BQQB", kind, b.epoch.curr.value,
                      b.epoch.prev.value, mid)
    if isinstance(b.mutation, StopMutation):
        actors = sorted(b.mutation.actors)
        out += struct.pack(">I", len(actors))
        out += struct.pack(f">{len(actors)}I", *actors)
    return out


def decode_barrier(data: bytes) -> Barrier:
    kind_i, curr, prev, mid = struct.unpack_from(">BQQB", data, 0)
    kind = (BarrierKind.INITIAL, BarrierKind.BARRIER,
            BarrierKind.CHECKPOINT)[kind_i]
    mcls = _MUTATIONS[mid]
    mutation = None
    if mcls is StopMutation:
        n = struct.unpack_from(">I", data, 18)[0]
        actors = struct.unpack_from(f">{n}I", data, 22)
        mutation = StopMutation(frozenset(actors))
    elif mcls is not None:
        mutation = mcls()
    return Barrier(EpochPair(Epoch(curr), Epoch(prev)), kind, mutation)


def encode_watermark(w: Watermark) -> bytes:
    return struct.pack(">HBq", w.col_idx, _TYPE_IDS[w.data_type],
                       int(w.value))


def decode_watermark(data: bytes) -> Watermark:
    col, tid, value = struct.unpack_from(">HBq", data, 0)
    return Watermark(col, _TYPE_FROM_ID[tid], value)


def _frame(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload)) + payload


# per-connection write batching bound: frames already queued coalesce
# into one socket write up to this many bytes (latency unaffected — we
# never WAIT for more frames, only drain what is instantly available)
_WRITE_BATCH_BYTES = 256 * 1024


# -- server (upstream side) ----------------------------------------------


class ExchangeServer:
    """Hosts outgoing edges: downstream peers connect and pull one
    (up_actor, down_actor) stream each, granting credits as they
    consume (exchange_service.rs + permit.rs collapsed)."""

    def __init__(self) -> None:
        self._edges: Dict[Tuple[int, int], asyncio.Queue] = {}
        self._credits: Dict[Tuple[int, int], asyncio.Semaphore] = {}
        self._outputs: Dict[Tuple[int, int], "RemoteOutputQueue"] = {}
        self._server: Optional[asyncio.AbstractServer] = None

    async def serve(self, host: str = "127.0.0.1", port: int = 0):
        self._server = await asyncio.start_server(self._handle, host,
                                                  port)
        return self._server

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        # release handler tasks first: wait_closed() (3.12+) waits for
        # them, and each blocks on its edge queue until the sentinel
        for q in self._edges.values():
            q.put_nowait(None)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def reset_edges(self) -> None:
        """Release every registered edge without closing the server
        (the worker `reset` verb): connected peers get the clean-end
        sentinel, the registries clear, and redeployed actors register
        fresh edges on the SAME port — remote peers reconnect to the
        address they already know."""
        for q in self._edges.values():
            q.put_nowait(None)
        self._edges.clear()
        self._credits.clear()
        self._outputs.clear()

    def register_edge(self, up: int, down: int) -> "RemoteOutputQueue":
        key = (up, down)
        q: asyncio.Queue = asyncio.Queue()
        self._edges[key] = q
        sem = asyncio.Semaphore(0)
        self._credits[key] = sem
        o = RemoteOutputQueue(q, sem, label=f"remote:{up}->{down}")
        self._outputs[key] = o
        return o

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        out: Optional[RemoteOutputQueue] = None
        clean = False
        try:
            tag, payload = await _read_frame(reader)
            assert tag == b"H", tag
            up, down, credits = struct.unpack(">III", payload)
            key = (up, down)
            q = self._edges[key]
            out = self._outputs[key]
            sem = self._credits[key]
            for _ in range(credits):
                sem.release()

            async def credit_pump():
                try:
                    while True:
                        t, p = await _read_frame(reader)
                        if t != b"C":
                            continue
                        for _ in range(struct.unpack(">I", p)[0]):
                            sem.release()
                except (asyncio.IncompleteReadError,
                        ConnectionResetError):
                    # peer vanished: unblock the sender LOUDLY — a
                    # silently-starved credit budget would wedge the
                    # upstream actor and with it barrier collection
                    if out is not None:
                        out.mark_broken()

            pump = asyncio.ensure_future(credit_pump())
            try:
                while True:
                    frame = await q.get()
                    if frame is None:
                        clean = True
                        break
                    # batch whatever else is already queued into ONE
                    # write+drain: many small frames to the same edge
                    # (compacted dispatch slices) otherwise pay a
                    # syscall + flush each
                    size = len(frame)
                    batch = [frame]
                    while size < _WRITE_BATCH_BYTES:
                        try:
                            nxt = q.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                        if nxt is None:
                            clean = True
                            break
                        batch.append(nxt)
                        size += len(nxt)
                    writer.write(b"".join(batch) if len(batch) > 1
                                 else frame)
                    await writer.drain()
                    if clean:
                        break
            finally:
                pump.cancel()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                KeyError):
            pass
        finally:
            if not clean and out is not None:
                out.mark_broken()
            writer.close()


class RemoteOutputQueue:
    """Sender half of one edge: an Output-compatible object.

    Chunks consume one credit each (block when the receiver is behind);
    barriers bypass the data budget so checkpoints can't be starved by
    backpressure (permit.rs's separate barrier budget)."""

    def __init__(self, q: asyncio.Queue, credits: asyncio.Semaphore,
                 label: str = ""):
        self._q = q
        self._credits = credits
        self._broken = False
        # channel label in stream_backpressure_wait_seconds — remote
        # credit parks are the cross-node half of sender backpressure
        self.label = label

    def mark_broken(self) -> None:
        """Downstream disconnected: wake blocked senders into an error
        (a silent stall would hang barrier collection cluster-wide)."""
        self._broken = True
        self._credits.release()          # each woken waiter re-releases

    async def send(self, msg: Message) -> None:
        if self._broken:
            raise ConnectionError("remote exchange peer disconnected")
        if is_chunk(msg):
            from risingwave_tpu.stream.coalesce import is_empty
            if is_empty(msg):
                return     # nothing to ship: no frame, no credit burned
            if self._credits.locked():
                # credit-starved: the wire peer is behind — park time
                # is backpressure, not the sending executor's work
                import time as _time
                from risingwave_tpu.stream.exchange import (
                    note_backpressure,
                )
                t0 = _time.perf_counter()
                await self._credits.acquire()
                note_backpressure(_time.perf_counter() - t0, self.label)
            else:
                await self._credits.acquire()
            if self._broken:
                self._credits.release()  # cascade the wake-up
                raise ConnectionError(
                    "remote exchange peer disconnected")
            await self._q.put(_frame(b"D", encode_chunk(msg)))
        elif is_barrier(msg):
            # span-context trailer (stream/trace_ctx.py): empty bytes
            # when tracing is off — the frame stays byte-identical
            await self._q.put(_frame(
                b"B", encode_barrier(msg) + barrier_trailer(msg)))
        elif isinstance(msg, Watermark):
            await self._q.put(_frame(b"W", encode_watermark(msg)))
        else:
            raise TypeError(f"unsendable {msg!r}")

    def close(self) -> None:
        self._q.put_nowait(None)


# -- client (downstream side) --------------------------------------------


class RemoteInput(Executor):
    """Executor that pulls one remote edge (exchange/input.rs:103).

    Grants `credit_batch` chunk credits whenever consumed credits
    accumulate to that many (credit-based flow control over the wire).
    """

    def __init__(self, host: str, port: int, up_actor: int,
                 down_actor: int, schema: Schema,
                 initial_credits: int = 16, credit_batch: int = 8):
        super().__init__(ExecutorInfo(
            schema, [], f"RemoteInput({up_actor}->{down_actor})"))
        self.host, self.port = host, port
        self.up, self.down = up_actor, down_actor
        self.initial_credits = initial_credits
        self.credit_batch = credit_batch
        # wall time parked on the wire waiting for the next frame —
        # idle, not processing; the monitor subtracts it from this
        # node's exclusive busy time (same contract as SourceExecutor:
        # an input edge waiting out a slow remote epoch must not read
        # as the chain's straggler)
        self.idle_wait_s = 0.0

    async def execute(self) -> AsyncIterator[Message]:
        import time as _time
        reader, writer = await asyncio.open_connection(self.host,
                                                       self.port)
        writer.write(_frame(b"H", struct.pack(
            ">III", self.up, self.down, self.initial_credits)))
        await writer.drain()
        consumed = 0
        try:
            while True:
                t0 = _time.monotonic()
                try:
                    tag, payload = await _read_frame(reader)
                except asyncio.IncompleteReadError:
                    return                      # upstream closed
                finally:
                    self.idle_wait_s += _time.monotonic() - t0
                if tag == b"D":
                    consumed += 1
                    if consumed >= self.credit_batch:
                        writer.write(_frame(b"C", struct.pack(
                            ">I", consumed)))
                        await writer.drain()
                        consumed = 0
                    yield decode_chunk(payload, self.schema)
                elif tag == b"B":
                    barrier = decode_barrier(payload)
                    # cross-worker causal edge: links this process's
                    # spans under the sender's inject span
                    record_remote_transfer(payload, self.up, self.down)
                    yield barrier
                    if barrier.is_stop(self.down):
                        return
                elif tag == b"W":
                    yield decode_watermark(payload)
        finally:
            writer.close()


async def _read_frame(reader: asyncio.StreamReader
                      ) -> Tuple[bytes, bytes]:
    hdr = await reader.readexactly(5)
    ln = struct.unpack(">I", hdr[1:5])[0]
    return hdr[0:1], await reader.readexactly(ln)
