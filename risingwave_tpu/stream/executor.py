"""The Executor protocol: pull-based async message streams.

Reference parity: src/stream/src/executor/mod.rs:173 (``Executor`` trait —
``execute() -> BoxedMessageStream`` plus schema/pk/identity metadata).

TPU re-design: executors are async generators. An executor chain is a
single-consumer pull pipeline; barriers flowing through it are the only
synchronization points. Stateful executors buffer device work between
barriers and flush on ``Barrier`` — one fused device step per epoch where
possible, so Python overhead amortizes over the whole micro-batch.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import AsyncIterator, List, Optional, Sequence

from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.message import Message


@dataclass
class ExecutorInfo:
    """Schema + pk + display identity of an executor's output."""

    schema: Schema
    pk_indices: List[int] = field(default_factory=list)
    identity: str = "Executor"


class Executor(abc.ABC):
    """Base for all stream executors (mod.rs:173 analog)."""

    def __init__(self, info: ExecutorInfo):
        self._info = info

    @property
    def schema(self) -> Schema:
        return self._info.schema

    @property
    def pk_indices(self) -> List[int]:
        return self._info.pk_indices

    @property
    def identity(self) -> str:
        return self._info.identity

    @abc.abstractmethod
    def execute(self) -> AsyncIterator[Message]:
        """Async generator of Messages, ending after a Stop barrier."""

    def __repr__(self) -> str:
        return f"{self.identity}({self.schema!r})"
