"""The Executor protocol: pull-based async message streams.

Reference parity: src/stream/src/executor/mod.rs:173 (``Executor`` trait —
``execute() -> BoxedMessageStream`` plus schema/pk/identity metadata).

TPU re-design: executors are async generators. An executor chain is a
single-consumer pull pipeline; barriers flowing through it are the only
synchronization points. Stateful executors buffer device work between
barriers and flush on ``Barrier`` — one fused device step per epoch where
possible, so Python overhead amortizes over the whole micro-batch.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import AsyncIterator, List, Optional, Sequence, Tuple

from risingwave_tpu.common.types import Schema
from risingwave_tpu.stream.message import Message


@dataclass
class ExecutorInfo:
    """Schema + pk + display identity of an executor's output."""

    schema: Schema
    pk_indices: List[int] = field(default_factory=list)
    identity: str = "Executor"


class Executor(abc.ABC):
    """Base for all stream executors (mod.rs:173 analog)."""

    def __init__(self, info: ExecutorInfo):
        self._info = info

    @property
    def schema(self) -> Schema:
        return self._info.schema

    @property
    def pk_indices(self) -> List[int]:
        return self._info.pk_indices

    @property
    def identity(self) -> str:
        return self._info.identity

    @abc.abstractmethod
    def execute(self) -> AsyncIterator[Message]:
        """Async generator of Messages, ending after a Stop barrier."""

    def __repr__(self) -> str:
        return f"{self.identity}({self.schema!r})"


# attributes under which executors hold their input executors, in plan
# order (the conventional names every executor in stream/executors
# uses; `inputs` is the list form — UnionExecutor)
_CHILD_ATTRS = ("input", "upstream", "left_in", "right_in")
_CHILD_LIST_ATTRS = ("inputs",)


def executor_children(ex) -> List[Tuple[str, Optional[int],
                                        "Executor"]]:
    """(attr, list-index-or-None, child) per input executor of `ex`.

    THE shared tree walk: explain_tree renders with it and
    install_monitoring wraps with it — two drifting copies of this
    list would silently drop a subtree out of monitoring (its parent's
    'exclusive' time then absorbs the whole unwrapped subtree)."""
    out: List[Tuple[str, Optional[int], Executor]] = []
    for attr in _CHILD_ATTRS:
        c = getattr(ex, attr, None)
        if isinstance(c, Executor):
            out.append((attr, None, c))
    for attr in _CHILD_LIST_ATTRS:
        cs = getattr(ex, attr, None)
        if isinstance(cs, list):
            for i, c in enumerate(cs):
                if isinstance(c, Executor):
                    out.append((attr, i, c))
    return out
