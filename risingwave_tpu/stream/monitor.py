"""MonitoredExecutor: per-(fragment, actor, executor) instrumentation.

Reference parity: src/stream/src/executor/monitor/streaming_stats.rs —
every executor in a deployed chain is wrapped so row/chunk throughput
and processing time land in the process registry under a
`fragment/actor/executor` label scheme, and the await-registry always
knows which executor an actor is currently parked in (the await-tree
dump a stalled barrier attributes against).

Exclusive processing time: in a pull pipeline, awaiting an inner
executor's `__anext__` includes the whole upstream chain's work. Every
node in the chain is wrapped, so a wrapper's *exclusive* time is its
own cumulative pull time minus its wrapped inputs' — computed per
epoch at each barrier passage (both sides of the subtraction observe
the same barrier boundary: an input's clock only advances while its
consumer awaits it).
"""

from __future__ import annotations

import time
from typing import AsyncIterator, List, Optional

from risingwave_tpu.stream.executor import (
    Executor, ExecutorInfo, executor_children,
)
from risingwave_tpu.stream.message import (
    Barrier, Message, is_barrier, is_chunk,
)
from risingwave_tpu.utils import ledger as _ledger
from risingwave_tpu.utils import spans as _spans
from risingwave_tpu.utils.failpoint import fail_point
from risingwave_tpu.utils.metrics import STREAMING as _METRICS
from risingwave_tpu.utils.trace import GLOBAL_AWAITS as _AWAITS


# assertion mode for zero-visible-row emissions: the spine suppresses
# empty chunks end-to-end (dispatchers, filters, coalescers), so a
# monitored executor emitting one is a regression. Tests flip this on
# (tests/conftest.py) to REJECT empties; production only counts them.
STRICT_EMPTY_CHUNKS = False


def set_strict_empty_chunks(on: bool) -> None:
    global STRICT_EMPTY_CHUNKS
    STRICT_EMPTY_CHUNKS = bool(on)


class MonitoredExecutor(Executor):
    """Transparent metrics wrapper around one executor node."""

    def __init__(self, inner: Executor, fragment: str, actor_id: int,
                 node: int,
                 children: Optional[List["MonitoredExecutor"]] = None):
        super().__init__(ExecutorInfo(inner.schema,
                                      list(inner.pk_indices),
                                      inner.identity))
        self.inner = inner
        self.children = list(children or [])
        self.labels = {"fragment": fragment, "actor": str(actor_id),
                       "executor": inner.identity, "node": str(node)}
        self.total_busy_s = 0.0     # cumulative time inside inner pulls
        self._mark_own = 0.0        # totals at the last barrier
        self._mark_kids = 0.0
        self._mark_idle = 0.0       # inner.idle_wait_s at last barrier
        self._who = f"actor-{actor_id}/{node}:{inner.identity}"
        # phase-ledger attribution cell: named phases recorded during
        # THIS executor's pulls land here (asyncio-context scoped, so
        # interleaved actors never cross-charge); the barrier flush
        # commits it epoch-exactly and classifies the residue
        self._cell = _ledger.AttributionCell()
        self._fallback_phase = (
            "host_ingest"
            if "Source" in inner.identity
            or "Source" in type(inner).__name__ else "host_emit")

    def __getattr__(self, name: str):
        # transparent introspection: chain walkers (tests, debuggers)
        # reach the inner executor's attributes (.input, .kernel,
        # .sides, .table, …) through the wrapper
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    def _flush_epoch(self, barrier: Barrier) -> None:
        epoch = barrier.epoch.curr.value
        own = self.total_busy_s
        kids = sum(c.total_busy_s for c in self.children)
        excl = max(0.0, (own - self._mark_own)
                   - (kids - self._mark_kids))
        self._mark_own, self._mark_kids = own, kids
        # sources (no wrapped inputs to subtract) expose the time they
        # spent PARKED on the barrier channel — idle, not processing:
        # without this, a source waiting out a slow downstream epoch
        # reads as the busiest executor in the chain
        idle = getattr(self.inner, "idle_wait_s", None)
        idle_delta = 0.0
        if idle is not None:
            idle_delta = max(0.0, idle - self._mark_idle)
            excl = max(0.0, excl - idle_delta)
            self._mark_idle = idle
        _METRICS.executor_busy.inc(excl, **self.labels)
        _METRICS.executor_epoch_seconds.observe(excl, **self.labels)
        if _ledger.enabled():
            # phase ledger: named phases recorded during this
            # executor's pulls commit epoch-exactly; the exclusive
            # residue is host work that is provably NOT pack/transfer/
            # compute — source decode loops (host_ingest) or downstream
            # reassembly/state writes/dispatch (host_emit); the barrier
            # park is barrier_wait
            named = self._cell.named_total()
            _ledger.LEDGER.commit_cell(epoch, self._cell)
            resid = excl - named
            if resid > 0:
                _ledger.LEDGER.attribute(self._fallback_phase, resid,
                                         epoch)
            if idle_delta > 0:
                # keyed per source: parallel sources park CONCURRENTLY
                # and the ledger folds the across-source max, not the
                # sum, into barrier_wait at seal (share > 1.0 was the
                # BENCH_r10 ad-ctr attribution bug)
                _ledger.LEDGER.attribute_idle(idle_delta, epoch,
                                              source=self._who)
        else:
            # drain even while off: seconds recorded before a mid-
            # epoch SET stream_ledger=off must not leak into whatever
            # epoch is current when the ledger comes back on
            self._cell.take()
        if _spans.enabled():
            # one actor-phase span per (executor, barrier): exclusive
            # processing time for the epoch this barrier ends, keyed by
            # the barrier's CURR epoch (the rw_barrier_latency key) and
            # parented to its inject span — the causal timeline the
            # straggler diagnosis reads
            import time as _t
            _spans.EPOCH_TRACER.record(
                self.labels["executor"], "actor", epoch=epoch,
                start_s=_t.time() - excl, dur_s=excl,
                actor=int(self.labels["actor"]),
                node=self.labels["node"],
                fragment=self.labels["fragment"])
        # per-LOGICAL-executor attribution inside fused blocks
        # (ops/fused.py): a fused run is ONE node in the chain, but
        # rw_actor_metrics keeps a row per absorbed stage — visible-row
        # counts come from the traced step itself (filter selectivity
        # stays observable after fusion)
        drain = getattr(self.inner, "drain_stage_metrics", None)
        if drain is None:
            return
        for ident, rows, chunks in drain():
            labels = dict(self.labels)
            labels["executor"] = f"{self.labels['executor']}::{ident}"
            _METRICS.executor_rows.inc(rows, **labels)
            if chunks:
                _METRICS.executor_chunks.inc(chunks, **labels)

    async def execute(self) -> AsyncIterator[Message]:
        it = self.inner.execute()
        try:
            while True:
                t0 = time.perf_counter()
                _AWAITS.enter(self._who, "poll_next")
                # ledger cell: scopes fired while the INNER executor
                # works (pack/h2d/dispatch/d2h inside this pull) are
                # charged to this node — a nested wrapped child swaps
                # its own cell in for its pulls, mirroring exactly how
                # exclusive busy time nests
                ctok = _ledger.LEDGER.push_cell(self._cell) \
                    if _ledger.enabled() else None
                try:
                    msg = await it.__anext__()
                except StopAsyncIteration:
                    break
                finally:
                    if ctok is not None:
                        _ledger.LEDGER.pop_cell(ctok)
                    _AWAITS.exit(self._who)
                    self.total_busy_s += time.perf_counter() - t0
                if is_chunk(msg):
                    card = msg.cardinality()
                    if card == 0:
                        _METRICS.executor_empty_chunks.inc(
                            1, **self.labels)
                        if STRICT_EMPTY_CHUNKS:
                            raise AssertionError(
                                f"{self._who} emitted a zero-visible-"
                                "row chunk (the spine suppresses "
                                "empties end-to-end)")
                    _METRICS.executor_rows.inc(card, **self.labels)
                    _METRICS.executor_chunks.inc(1, **self.labels)
                elif is_barrier(msg):
                    # armable per-executor-class delay (sleep-spec
                    # failpoint): chaos/trace tests inject a laggard
                    # here; the slept time counts as THIS executor's
                    # busy time so attribution names the right actor
                    t1 = time.perf_counter()
                    fail_point("trace.slow."
                               + type(self.inner).__name__)
                    self.total_busy_s += time.perf_counter() - t1
                    self._flush_epoch(msg)
                yield msg
        finally:
            _AWAITS.exit(self._who)


def install_monitoring(root: Executor, fragment: str,
                       actor_id: int) -> Executor:
    """Wrap every node of an executor tree in a MonitoredExecutor.

    Walks the chain with the shared `executor_children` helper (the
    same walk explain_tree renders with), REPLACES each child
    reference with its wrapper (executors pull from whatever their
    attribute points at), and returns the wrapped root for the actor
    to drive.
    """
    counter = [0]

    def wrap(ex: Executor) -> MonitoredExecutor:
        node = counter[0]
        counter[0] += 1
        children: List[MonitoredExecutor] = []
        for attr, idx, child in executor_children(ex):
            w = wrap(child)
            if idx is None:
                setattr(ex, attr, w)
            else:
                getattr(ex, attr)[idx] = w
            children.append(w)
        return MonitoredExecutor(ex, fragment, actor_id, node,
                                 children)

    return wrap(root)
