"""MonitoredExecutor: per-(fragment, actor, executor) instrumentation.

Reference parity: src/stream/src/executor/monitor/streaming_stats.rs —
every executor in a deployed chain is wrapped so row/chunk throughput
and processing time land in the process registry under a
`fragment/actor/executor` label scheme, and the await-registry always
knows which executor an actor is currently parked in (the await-tree
dump a stalled barrier attributes against).

Exclusive processing time: in a pull pipeline, awaiting an inner
executor's `__anext__` includes the whole upstream chain's work. Every
node in the chain is wrapped, so a wrapper's *exclusive* time is its
own cumulative pull time minus its wrapped inputs' — computed per
epoch at each barrier passage (both sides of the subtraction observe
the same barrier boundary: an input's clock only advances while its
consumer awaits it).
"""

from __future__ import annotations

import threading
import time
from typing import AsyncIterator, Dict, List, Optional, Tuple

from risingwave_tpu.stream import exchange as _xchg
from risingwave_tpu.stream.executor import (
    Executor, ExecutorInfo, executor_children,
)
from risingwave_tpu.stream.message import (
    Barrier, Message, is_barrier, is_chunk,
)
from risingwave_tpu.stream import costs as _costs
from risingwave_tpu.stream import hotkeys as _hotkeys
from risingwave_tpu.utils import ledger as _ledger
from risingwave_tpu.utils import spans as _spans
from risingwave_tpu.utils.failpoint import fail_point
from risingwave_tpu.utils.metrics import STREAMING as _METRICS
from risingwave_tpu.utils.trace import GLOBAL_AWAITS as _AWAITS


# assertion mode for zero-visible-row emissions: the spine suppresses
# empty chunks end-to-end (dispatchers, filters, coalescers), so a
# monitored executor emitting one is a regression. Tests flip this on
# (tests/conftest.py) to REJECT empties; production only counts them.
STRICT_EMPTY_CHUNKS = False


def set_strict_empty_chunks(on: bool) -> None:
    global STRICT_EMPTY_CHUNKS
    STRICT_EMPTY_CHUNKS = bool(on)


# utilization tricolor toggle (ISSUE 14): SET stream_tricolor = off
# reduces the per-barrier ratio bookkeeping (and the per-pull park-cell
# context swap) to a predicate check — the observability-tax control
# arm the bench's q7_tricolor_off lane measures.
TRICOLOR = True


def set_tricolor(on: bool) -> None:
    global TRICOLOR
    TRICOLOR = bool(on)


def parse_tricolor(spec: str) -> bool:
    """'on'|'off' → bool (SET stream_tricolor validator)."""
    s = str(spec).strip().lower()
    if s in ("on", "true", "1"):
        return True
    if s in ("off", "false", "0"):
        return False
    from risingwave_tpu.frontend.planner import PlanError
    raise PlanError(f"stream_tricolor must be on|off, got {spec!r}")


class UtilizationTable:
    """Last-barrier utilization tricolor per (fragment, actor, node):
    busy / backpressure / idle shares of the barrier interval — the
    Flink-style triple, kept as a process-global snapshot the
    bottleneck walker, ``rw_actor_utilization`` and ``ctl top`` read.

    Accounting identity (gated in tier-1 strict mode, like the phase
    ledger's conservation check): each triple sums to ≤ 1.0 + ε. Busy
    is the node's EXCLUSIVE pull time minus its idle park (source /
    RemoteInput / Receiver input waits) minus its credit park
    (exchange backpressure), so the three parts partition disjoint
    wall time inside one interval by construction — a sum above 1 is
    a double-count bug, not noise."""

    EPSILON = 0.05

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (fragment, actor_id, node) → (executor, epoch, interval_s,
        #                               busy, backpressure, idle)
        self._rows: Dict[Tuple[str, int, int], tuple] = {}
        self._violations: List[tuple] = []

    def observe(self, labels: Dict[str, str], epoch: int,
                interval_s: float, busy_s: float, bp_s: float,
                idle_s: float) -> None:
        if interval_s <= 0:
            return
        busy = busy_s / interval_s
        bp = bp_s / interval_s
        idle = idle_s / interval_s
        key = (labels["fragment"], int(labels["actor"]),
               int(labels["node"]))
        with self._lock:
            if busy + bp + idle > 1.0 + self.EPSILON:
                self._violations.append(
                    (key, labels["executor"], epoch,
                     round(busy, 4), round(bp, 4), round(idle, 4)))
            self._rows[key] = (labels["executor"], int(epoch),
                               interval_s, busy, bp, idle)
        for state, v in (("busy", busy), ("backpressure", bp),
                         ("idle", idle)):
            _METRICS.executor_utilization.set(v, state=state, **labels)

    def get(self, fragment: str, actor_id: int, node: int
            ) -> Optional[tuple]:
        with self._lock:
            return self._rows.get((fragment, actor_id, node))

    def ingest_rows(self, rows) -> int:
        """Merge another process's utilization snapshot (the worker
        ``signals`` drain): rows in the ``rows()`` wire shape land in
        this table keyed exactly like local ones — actor ids are
        cluster-unique, so worker and coordinator rows never collide.
        Ratios arrive pre-computed; the accounting gate ran in the
        process that measured them, so no re-validation here."""
        n = 0
        with self._lock:
            for (a, f, node, ex, e, interval, busy, bp, idle) in rows:
                self._rows[(str(f), int(a), int(node))] = (
                    str(ex), int(e), float(interval), float(busy),
                    float(bp), float(idle))
                n += 1
        return n

    def prune(self, keep_actors) -> int:
        """Drop rows for actors outside ``keep_actors`` — the merged
        coordinator view's eviction path: workers drop their own rows
        at actor exit, but ingested copies would otherwise outlive
        every rescale/recovery (fresh actor ids each redeploy) and
        grow the table without bound."""
        keep = set(keep_actors)
        with self._lock:
            dead = [k for k in self._rows if k[1] not in keep]
            for k in dead:
                del self._rows[k]
        return len(dead)

    def rows(self) -> List[tuple]:
        """(actor_id, fragment, node, executor, epoch, interval_s,
        busy_ratio, backpressure_ratio, idle_ratio) sorted by busy
        desc — the rw_actor_utilization payload and ctl top's sort."""
        with self._lock:
            out = [(a, f, n, ex, e, round(i, 6), round(b, 6),
                    round(bp, 6), round(idl, 6))
                   for (f, a, n), (ex, e, i, b, bp, idl)
                   in self._rows.items()]
        return sorted(out, key=lambda r: -r[6])

    def drop_actor(self, actor_id: int) -> None:
        with self._lock:
            dead = [k for k in self._rows if k[1] == actor_id]
            for k in dead:
                ex = self._rows.pop(k)[0]
                for state in ("busy", "backpressure", "idle"):
                    _METRICS.executor_utilization.remove(
                        state=state, fragment=k[0],
                        actor=str(actor_id), node=str(k[2]),
                        executor=ex)

    def gate_violations(self) -> List[tuple]:
        with self._lock:
            return list(self._violations)

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._violations.clear()


UTILIZATION = UtilizationTable()


class Topology:
    """Deployed monitored chains by actor: (fragment, root wrapper) —
    the graph the bottleneck walker descends (wrapper .children edges
    are exactly the dataflow's upstream edges, input-channel nodes
    included). Registered by install_monitoring, dropped at actor
    exit."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._actors: Dict[int, Tuple[str, "MonitoredExecutor"]] = {}

    def register(self, actor_id: int, fragment: str,
                 root: "MonitoredExecutor") -> None:
        with self._lock:
            self._actors[actor_id] = (fragment, root)

    def drop_actor(self, actor_id: int) -> None:
        with self._lock:
            self._actors.pop(actor_id, None)
        UTILIZATION.drop_actor(actor_id)

    def roots(self, fragments=None, actors=None) -> List[tuple]:
        """[(actor_id, fragment, root wrapper)]; ``fragments`` (a set
        of job names) restricts to one barrier domain's chains, and
        ``actors`` (a set of actor ids — the barrier-domain frame's
        actor filter) restricts to that domain's actors on THIS
        process (a worker hosts several domains' chains in one
        registry)."""
        with self._lock:
            items = list(self._actors.items())
        return [(a, f, r) for a, (f, r) in items
                if (fragments is None or f in fragments)
                and (actors is None or a in actors)]

    def clear(self) -> None:
        with self._lock:
            self._actors.clear()


TOPOLOGY = Topology()


class MonitoredExecutor(Executor):
    """Transparent metrics wrapper around one executor node."""

    def __init__(self, inner: Executor, fragment: str, actor_id: int,
                 node: int,
                 children: Optional[List["MonitoredExecutor"]] = None):
        super().__init__(ExecutorInfo(inner.schema,
                                      list(inner.pk_indices),
                                      inner.identity))
        self.inner = inner
        self.children = list(children or [])
        self.labels = {"fragment": fragment, "actor": str(actor_id),
                       "executor": inner.identity, "node": str(node)}
        self.total_busy_s = 0.0     # cumulative time inside inner pulls
        self._mark_own = 0.0        # totals at the last barrier
        self._mark_kids = 0.0
        self._mark_idle = 0.0       # inner.idle_wait_s at last barrier
        # exchange-credit park time recorded during THIS node's pulls
        # (stream/exchange.py cell contract, mirroring the ledger
        # cells) — subtracted from busy and published as the tricolor's
        # backpressure share
        self._park_cell = [0.0]
        self._mark_park = 0.0
        self._mark_meter = 0.0      # actor-loop meter mark (root only)
        self._last_flush_pc: Optional[float] = None
        self._who = f"actor-{actor_id}/{node}:{inner.identity}"
        # phase-ledger attribution cell: named phases recorded during
        # THIS executor's pulls land here (asyncio-context scoped, so
        # interleaved actors never cross-charge); the barrier flush
        # commits it epoch-exactly and classifies the residue
        self._cell = _ledger.AttributionCell()
        self._fallback_phase = (
            "host_ingest"
            if "Source" in inner.identity
            or "Source" in type(inner).__name__ else "host_emit")

    def __getattr__(self, name: str):
        # transparent introspection: chain walkers (tests, debuggers)
        # reach the inner executor's attributes (.input, .kernel,
        # .sides, .table, …) through the wrapper
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    def _flush_epoch(self, barrier: Barrier) -> None:
        epoch = barrier.epoch.curr.value
        own = self.total_busy_s
        kids = sum(c.total_busy_s for c in self.children)
        excl = max(0.0, (own - self._mark_own)
                   - (kids - self._mark_kids))
        self._mark_own, self._mark_kids = own, kids
        # sources (no wrapped inputs to subtract) expose the time they
        # spent PARKED on the barrier channel — idle, not processing:
        # without this, a source waiting out a slow downstream epoch
        # reads as the busiest executor in the chain
        idle = getattr(self.inner, "idle_wait_s", None)
        idle_delta = 0.0
        if idle is not None:
            idle_delta = max(0.0, idle - self._mark_idle)
            excl = max(0.0, excl - idle_delta)
            self._mark_idle = idle
        # sender-side credit park (ISSUE 14): time this node's pulls
        # spent BLOCKED for exchange credits is backpressure, not
        # processing — without the subtraction a straggler diagnosis
        # blames the victim of a slow consumer. Only the IN-PULL park
        # (the cell) comes out of busy: the actor-loop meter's
        # dispatch parks happen BETWEEN pulls and were never in
        # total_busy_s — subtracting them too would deflate the
        # root's real work. The root (node 0) still drains the meter
        # into its backpressure share (the park is that actor's wall
        # time either way).
        park_pull = max(0.0, self._park_cell[0] - self._mark_park)
        self._mark_park = self._park_cell[0]
        if park_pull > 0:
            excl = max(0.0, excl - park_pull)
        park_delta = park_pull
        if self.labels["node"] == "0":
            meter = _xchg.current_actor_meter()
            if meter is not None:
                park_delta += max(0.0, meter[0] - self._mark_meter)
                self._mark_meter = meter[0]
        _METRICS.executor_busy.inc(excl, **self.labels)
        _METRICS.executor_epoch_seconds.observe(excl, **self.labels)
        if TRICOLOR:
            # utilization tricolor: busy / backpressure / idle shares
            # of THIS node's barrier-to-barrier interval (its own
            # flush-to-flush wall clock — all three parts are disjoint
            # wall time inside it, so the triple sums to ≤ 1)
            now_pc = time.perf_counter()
            if self._last_flush_pc is not None:
                UTILIZATION.observe(
                    self.labels, epoch,
                    interval_s=now_pc - self._last_flush_pc,
                    busy_s=excl, bp_s=park_delta, idle_s=idle_delta)
            self._last_flush_pc = now_pc
        if _ledger.enabled():
            # phase ledger: named phases recorded during this
            # executor's pulls commit epoch-exactly; the exclusive
            # residue is host work that is provably NOT pack/transfer/
            # compute — source decode loops (host_ingest) or downstream
            # reassembly/state writes/dispatch (host_emit); the barrier
            # park is barrier_wait
            named = self._cell.named_total()
            if _costs.enabled():
                # per-MV split of the SAME cell the ledger is about to
                # commit: the fragment label is the MV/job name, and
                # cells nest exclusively, so summing fragments can
                # never mint device time the domain didn't ledger
                _costs.COSTS.observe_cell(
                    self.labels["fragment"], epoch,
                    self._cell.seconds.get("device_compute", 0.0),
                    self._cell.h2d_bytes, self._cell.d2h_bytes)
            _ledger.LEDGER.commit_cell(epoch, self._cell)
            resid = excl - named
            if resid > 0:
                _ledger.LEDGER.attribute(self._fallback_phase, resid,
                                         epoch)
            if park_delta > 0:
                # credit parks are their own ledger phase: the wall
                # time subtracted from busy must still be conserved
                _ledger.LEDGER.attribute("backpressure_wait",
                                         park_delta, epoch)
            if idle_delta > 0:
                # keyed per source: parallel sources park CONCURRENTLY
                # and the ledger folds the across-source max, not the
                # sum, into barrier_wait at seal (share > 1.0 was the
                # BENCH_r10 ad-ctr attribution bug)
                _ledger.LEDGER.attribute_idle(idle_delta, epoch,
                                              source=self._who)
        else:
            # drain even while off: seconds recorded before a mid-
            # epoch SET stream_ledger=off must not leak into whatever
            # epoch is current when the ledger comes back on
            self._cell.take()
        if _spans.enabled():
            # one actor-phase span per (executor, barrier): exclusive
            # processing time for the epoch this barrier ends, keyed by
            # the barrier's CURR epoch (the rw_barrier_latency key) and
            # parented to its inject span — the causal timeline the
            # straggler diagnosis reads
            import time as _t
            _spans.EPOCH_TRACER.record(
                self.labels["executor"], "actor", epoch=epoch,
                start_s=_t.time() - excl, dur_s=excl,
                actor=int(self.labels["actor"]),
                node=self.labels["node"],
                fragment=self.labels["fragment"])
        # per-LOGICAL-executor attribution inside fused blocks
        # (ops/fused.py): a fused run is ONE node in the chain, but
        # rw_actor_metrics keeps a row per absorbed stage — visible-row
        # counts come from the traced step itself (filter selectivity
        # stays observable after fusion)
        drain = getattr(self.inner, "drain_stage_metrics", None)
        if drain is None:
            return
        for ident, rows, chunks in drain():
            labels = dict(self.labels)
            labels["executor"] = f"{self.labels['executor']}::{ident}"
            _METRICS.executor_rows.inc(rows, **labels)
            if chunks:
                _METRICS.executor_chunks.inc(chunks, **labels)

    async def execute(self) -> AsyncIterator[Message]:
        it = self.inner.execute()
        try:
            while True:
                t0 = time.perf_counter()
                _AWAITS.enter(self._who, "poll_next")
                # ledger cell: scopes fired while the INNER executor
                # works (pack/h2d/dispatch/d2h inside this pull) are
                # charged to this node — a nested wrapped child swaps
                # its own cell in for its pulls, mirroring exactly how
                # exclusive busy time nests
                ctok = _ledger.LEDGER.push_cell(self._cell) \
                    if _ledger.enabled() else None
                # compile-cache ownership: anything traced while this
                # pull runs bills the pulling MV (first tracer pays,
                # later MVs record shared hits — stream/costs.py)
                mtok = _costs.push_mv(self.labels["fragment"]) \
                    if _costs.enabled() else None
                # park cell: exchange-credit parks fired while the
                # inner executor works charge THIS node (a nested
                # wrapped child swaps its own cell in for its pulls,
                # mirroring the ledger cells)
                ptok = _xchg.push_park_cell(self._park_cell) \
                    if TRICOLOR else None
                try:
                    msg = await it.__anext__()
                except StopAsyncIteration:
                    break
                finally:
                    if ptok is not None:
                        _xchg.pop_park_cell(ptok)
                    if mtok is not None:
                        _costs.pop_mv(mtok)
                    if ctok is not None:
                        _ledger.LEDGER.pop_cell(ctok)
                    _AWAITS.exit(self._who)
                    self.total_busy_s += time.perf_counter() - t0
                if is_chunk(msg):
                    card = msg.cardinality()
                    if card == 0:
                        _METRICS.executor_empty_chunks.inc(
                            1, **self.labels)
                        if STRICT_EMPTY_CHUNKS:
                            raise AssertionError(
                                f"{self._who} emitted a zero-visible-"
                                "row chunk (the spine suppresses "
                                "empties end-to-end)")
                    _METRICS.executor_rows.inc(card, **self.labels)
                    _METRICS.executor_chunks.inc(1, **self.labels)
                elif is_barrier(msg):
                    # armable per-executor-class delay (sleep-spec
                    # failpoint): chaos/trace tests inject a laggard
                    # here; the slept time counts as THIS executor's
                    # busy time so attribution names the right actor
                    t1 = time.perf_counter()
                    fail_point("trace.slow."
                               + type(self.inner).__name__)
                    self.total_busy_s += time.perf_counter() - t1
                    self._flush_epoch(msg)
                yield msg
        finally:
            _AWAITS.exit(self._who)


def install_monitoring(root: Executor, fragment: str,
                       actor_id: int) -> Executor:
    """Wrap every node of an executor tree in a MonitoredExecutor.

    Walks the chain with the shared `executor_children` helper (the
    same walk explain_tree renders with), REPLACES each child
    reference with its wrapper (executors pull from whatever their
    attribute points at), and returns the wrapped root for the actor
    to drive.
    """
    counter = [0]

    def wrap(ex: Executor) -> MonitoredExecutor:
        node = counter[0]
        counter[0] += 1
        children: List[MonitoredExecutor] = []
        for attr, idx, child in executor_children(ex):
            w = wrap(child)
            if idx is None:
                setattr(ex, attr, w)
            else:
                getattr(ex, attr)[idx] = w
            children.append(w)
        # hot-key sketches key by executor identity; the fragment
        # binding is what lets rw_hot_keys name the owning MV
        _hotkeys.HOTKEYS.bind_fragment(ex.identity, fragment)
        return MonitoredExecutor(ex, fragment, actor_id, node,
                                 children)

    wrapped = wrap(root)
    # the wrapped chain IS the dataflow graph the bottleneck walker
    # descends — register it (actor teardown drops the entry)
    TOPOLOGY.register(actor_id, fragment, wrapped)
    return wrapped
