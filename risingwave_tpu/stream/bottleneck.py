"""Backpressure-graph bottleneck walker: name the sustained culprit.

The utilization tricolor (stream/monitor.py) says how every (actor,
executor) spent each barrier; this module turns those per-node shares
into ONE name per barrier domain — the operator a capacity change
should target, which is exactly the input signal the ROADMAP-item-3
autoscaler consumes (the per-operator saturation evidence arxiv
1904.03800 argues scaling needs, not aggregate throughput).

The walk, per domain per barrier (Flink's backpressure diagnosis
adapted to a pull pipeline):

- Within an actor chain, pull edges carry implicit backpressure: a
  parent pulling a slow child shows near-zero exclusive busy while the
  child's subtree absorbs the interval. The walk therefore descends
  from the materialize root toward the child subtree holding the most
  busy time until the current node's own busy share dominates every
  input subtree — the first busy-dominated operator walking upstream.
- Across actor chains (MV-on-MV chain edges, remote exchange), the
  explicit signal takes over: a sender whose tricolor shows credit
  park time is the VICTIM of its consumer — chains fed by parked
  senders are implicated first, and the walk runs in the implicated
  chain (never blaming the parked upstream).

The streak machine only ticks on SLOW barriers (``SLOW_INTERVAL_S``):
a domain holding sub-half-second barriers is healthy — its hottest
operator is a fact, not a problem. On a slow barrier a candidate must
hold ``busy ≥ BUSY_DOMINANT`` to count (an evenly-spread slow domain
has no single bottleneck), and the same operator must repeat for
``SUSTAINED_STREAK`` contiguous slow barriers to be called
*sustained* — one hot barrier is an anecdote, a streak is a target.
Each row carries a one-line human diagnosis, cross-checked against the
phase ledger: a device_compute-dominated domain whose walk names an
operator that never dispatches kernels is flagged as a mismatch
(either the walk or the ledger is lying — say so instead of papering
over it).

Surfaces: the ``rw_bottlenecks`` system table,
``stream_bottleneck_streak{domain,operator}``, the bench
``bottleneck`` block per lane, and ``ctl top``.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# a node only qualifies as a bottleneck while it holds at least this
# share of its barrier interval busy
BUSY_DOMINANT = 0.35
# a sender counts as backpressured (its consumer implicated) above
# this credit-park share of the interval
EDGE_BP = 0.10
# contiguous SLOW barriers naming one operator before it is "sustained"
SUSTAINED_STREAK = 3
# the streak machine only ticks on barriers at least this long: a
# domain holding sub-half-second barriers is HEALTHY — its hottest
# operator is a fact, not a problem, and naming it would page the
# autoscaler on every fast pipeline. Fast and idle barriers leave the
# machine frozen (a drained domain keeps the verdict its last slow
# barrier earned; the `epoch` column dates it).
SLOW_INTERVAL_S = 0.5
# a single key above this guaranteed input share earns the diagnosis a
# skew:<key> clause (stream/hotkeys.py sketches; the share used is the
# sketch's LOWER bound, so an overcounted cold key cannot fire it)
SKEW_SHARE = 0.25


class _DomainState:
    __slots__ = ("op", "fragment", "actor", "node", "streak", "busy",
                 "downstream_bp", "diagnosis", "epoch", "barriers")

    def __init__(self) -> None:
        self.op: Optional[str] = None
        self.fragment = ""
        self.actor = 0
        self.node = 0
        self.streak = 0
        self.busy = 0.0
        self.downstream_bp = 0.0
        self.diagnosis = ""
        self.epoch = 0
        self.barriers = 0


def _dispatches_kernels(wrapper) -> bool:
    """Does this (monitored) operator launch device kernels? Checked
    against the live dispatch counters first, falling back to the
    executor carrying a sharded kernel object (mesh kernels label
    dispatches by kernel, not executor)."""
    from risingwave_tpu.utils.metrics import STREAMING
    ident = wrapper.labels["executor"]
    for labels, v in STREAMING.device_dispatch.series():
        ex = labels.get("executor", "")
        if v > 0 and (ex == ident or ex.startswith(ident)):
            return True
    inner = wrapper.inner
    if getattr(inner, "kernel", None) is not None:
        return True
    return "Fused" in type(inner).__name__


class BottleneckAnalyzer:
    """Process-global walker state (one streak machine per domain)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._domains: Dict[str, _DomainState] = {}
        # (worker, domain) → remote row in the rows() wire shape:
        # worker processes run their own walkers per barrier (the
        # coordinator hosts no monitored actors on a distributed
        # session); Cluster.drain_signals lands their snapshots here
        self._remote: Dict[tuple, tuple] = {}

    # -- per-barrier observation ---------------------------------------
    def observe(self, domain: str, epoch: int, interval_s: float,
                phase_seconds: Optional[dict] = None,
                fragments=None, actors=None) -> None:
        """One sealed barrier of ``domain``: walk its chains and
        advance/reset the streak machine. ``fragments`` restricts the
        topology to the domain's jobs (None = every registered chain
        — the single-loop pipelines), ``actors`` to the domain's actor
        ids (the worker-side walk, where the barrier frame carries the
        actor filter but not the job list); ``phase_seconds`` is the
        sealed ledger record's phase dict for the cross-check."""
        from risingwave_tpu.stream.monitor import TOPOLOGY, UTILIZATION

        roots = TOPOLOGY.roots(fragments, actors=actors)
        if not roots:
            return
        cand = None
        if interval_s >= SLOW_INTERVAL_S:
            cand = self._walk_domain(roots, UTILIZATION)
        with self._lock:
            st = self._domains.setdefault(domain, _DomainState())
            st.barriers += 1
            if interval_s < SLOW_INTERVAL_S:
                # fast/idle barrier: the domain is keeping up — freeze
                # the machine (don't advance, don't forget)
                return
            st.epoch = int(epoch)
            if cand is None or cand["busy"] < BUSY_DOMINANT:
                self._reset_locked(domain, st)
                return
            same = (st.op == cand["op"]
                    and st.actor == cand["actor"]
                    and st.node == cand["node"])
            if not same and st.op is not None:
                self._drop_gauge(domain, st.op)
            st.streak = st.streak + 1 if same else 1
            st.op = cand["op"]
            st.fragment = cand["fragment"]
            st.actor = cand["actor"]
            st.node = cand["node"]
            st.busy = cand["busy"]
            st.downstream_bp = cand["downstream_bp"]
            st.diagnosis = self._diagnose(st, cand, interval_s,
                                          phase_seconds)
            from risingwave_tpu.utils.metrics import STREAMING
            STREAMING.bottleneck_streak.set(st.streak, domain=domain,
                                            operator=st.op)

    def _reset_locked(self, domain: str, st: _DomainState) -> None:
        if st.op is not None:
            self._drop_gauge(domain, st.op)
        st.op = None
        st.streak = 0
        st.busy = 0.0
        st.downstream_bp = 0.0
        st.diagnosis = ""

    @staticmethod
    def _drop_gauge(domain: str, op: str) -> None:
        from risingwave_tpu.utils.metrics import STREAMING
        STREAMING.bottleneck_streak.remove(domain=domain, operator=op)

    # -- the walk ------------------------------------------------------
    def _walk_domain(self, roots, util) -> Optional[dict]:
        """Pick the domain's candidate: chains fed by backpressured
        senders are implicated first; the walk then descends the
        implicated (else every) chain from its materialize root."""
        by_fragment = {f: (a, r) for a, f, r in roots}
        # sender-side park share per chain root — the explicit
        # cross-chain backpressure evidence
        root_bp: Dict[str, float] = {}
        for a, f, r in roots:
            row = util.get(f, a, 0)
            root_bp[f] = row[4] if row is not None else 0.0
        max_bp = max(root_bp.values(), default=0.0)
        implicated = set(by_fragment)
        if max_bp >= EDGE_BP:
            # some sender parks: only chains that CONSUME a parked
            # upstream (identified by the chain hop below) — or, when
            # the hop graph is invisible, every chain that is not
            # itself parked — stay implicated
            consumers = {f for f, (a, r) in by_fragment.items()
                         if self._consumes_parked(r, root_bp)}
            if consumers:
                implicated = consumers
            else:
                implicated = {f for f, bp in root_bp.items()
                              if bp < EDGE_BP}
                if not implicated:
                    implicated = set(by_fragment)
        best = None
        for f in implicated:
            a, r = by_fragment[f]
            cand = self._walk_chain(f, a, r, util)
            if cand is not None and (best is None
                                     or cand["busy"] > best["busy"]):
                best = cand
        if best is not None:
            best["downstream_bp"] = round(max_bp, 4)
        return best

    @staticmethod
    def _consumes_parked(root, root_bp: Dict[str, float]) -> bool:
        """Does this chain read (Chain/Backfill hop) an upstream
        fragment whose sender is parked?"""
        hops: List[str] = []

        def scan(w) -> None:
            ident = w.labels["executor"]
            for tag in ("Chain(", "Backfill("):
                if tag in ident:
                    hops.append(
                        ident.split(tag, 1)[1].split(")", 1)[0])
            for c in w.children:
                scan(c)

        scan(root)
        return any(root_bp.get(h, 0.0) >= EDGE_BP for h in hops)

    def _walk_chain(self, fragment: str, actor_id: int, root,
                    util) -> Optional[dict]:
        """Descend from the materialize root toward the busiest input
        subtree until the current node's own busy share dominates every
        input — the first busy-dominated operator walking upstream
        along the pull graph's implicit backpressure."""
        def busy_of(w) -> float:
            row = util.get(fragment, actor_id, int(w.labels["node"]))
            return row[3] if row is not None else 0.0

        def subtree_busy(w) -> float:
            return busy_of(w) + sum(subtree_busy(c)
                                    for c in w.children)

        cur = root
        while cur.children:
            kid = max(cur.children, key=subtree_busy)
            if busy_of(cur) >= subtree_busy(kid):
                break
            cur = kid
        # the dominated stop may overshoot into a cheap leaf whose
        # subtree carried the time in a MIDDLE node — take the busiest
        # node on the walked spine instead of the stop point alone
        spine = []
        w = root
        while True:
            spine.append(w)
            if w is cur or not w.children:
                break
            w = max(w.children, key=subtree_busy)
        top = max(spine, key=busy_of)
        b = busy_of(top)
        if b <= 0.0:
            return None
        return {"op": top.labels["executor"], "fragment": fragment,
                "actor": actor_id, "node": int(top.labels["node"]),
                "busy": round(b, 4), "downstream_bp": 0.0,
                "wrapper": top}

    # -- diagnosis -----------------------------------------------------
    def _diagnose(self, st: _DomainState, cand: dict,
                  interval_s: float,
                  phase_seconds: Optional[dict]) -> str:
        parts = [f"{st.op} (actor {st.actor}) busy "
                 f"{st.busy:.0%} of the barrier"]
        if st.downstream_bp >= EDGE_BP:
            parts.append(f"upstream senders parked "
                         f"{st.downstream_bp:.0%} for credits")
        kernels = _dispatches_kernels(cand["wrapper"])
        if phase_seconds and interval_s > 0:
            # capped at 1: pipelined/overlapped epochs can attribute
            # more than one barrier's compute to one interval
            dc = min(1.0, phase_seconds.get("device_compute", 0.0)
                     / interval_s)
            if dc >= 0.25:
                if kernels:
                    parts.append(
                        f"consistent with the ledger: device_compute "
                        f"{dc:.0%} and the operator dispatches kernels")
                else:
                    parts.append(
                        f"LEDGER MISMATCH: device_compute {dc:.0%} "
                        f"but the walked operator dispatches no "
                        f"kernels")
        if st.streak >= SUSTAINED_STREAK:
            parts.append(f"sustained {st.streak} barriers — scale "
                         f"this operator first")
        # skew verdict (ISSUE 16): a hot key holding ≥ SKEW_SHARE of
        # the walked operator's input concentrates its work on ONE
        # shard — name the key so the autoscaler can veto a futile
        # parallelism scale-up instead of rescaling into the wall
        from risingwave_tpu.stream.hotkeys import HOTKEYS
        hot = HOTKEYS.hot_share(cand["wrapper"].labels["executor"],
                                min_share=SKEW_SHARE)
        if hot is not None:
            key, share = hot
            parts.append(f"skew:{key} ({share:.0%} of input keys — "
                         f"parallelism won't help)")
        return "; ".join(parts)

    # -- cross-process merge -------------------------------------------
    def ingest(self, rows, worker: str) -> int:
        """Merge one worker's walker snapshot (rows in the ``rows()``
        wire shape). Streak machines live where the chains live — each
        worker sustains its own candidates; ``rows()`` then reports
        the strongest candidate per domain across processes. Replaces
        the worker's previous snapshot wholesale (the rows are
        last-barrier state, not a log), dropping domains the worker no
        longer reports."""
        with self._lock:
            for key in [k for k in self._remote if k[0] == worker]:
                del self._remote[key]
            n = 0
            for r in rows:
                if len(r) != 11:
                    continue
                self._remote[(worker, str(r[0]))] = tuple(r)
                n += 1
        return n

    # -- reads ---------------------------------------------------------
    def rows(self) -> List[tuple]:
        """(domain, operator, fragment, actor_id, node, busy_ratio,
        downstream_backpressure, streak, sustained, epoch, diagnosis)
        ranked most-suspect first — the rw_bottlenecks payload. Local
        walker state and ingested worker snapshots merge per domain:
        the row with the longest streak (busy share breaking ties)
        wins — the strongest sustained evidence across processes."""
        with self._lock:
            cand: Dict[str, tuple] = {}
            for domain in sorted(self._domains):
                st = self._domains[domain]
                if st.op is None:
                    cand[domain] = (domain, None, "", 0, 0, 0.0, 0.0,
                                    0, 0, st.epoch,
                                    "no sustained bottleneck")
                    continue
                cand[domain] = (domain, st.op, st.fragment, st.actor,
                                st.node, st.busy, st.downstream_bp,
                                st.streak,
                                int(st.streak >= SUSTAINED_STREAK),
                                st.epoch, st.diagnosis)
            for (_w, domain), r in self._remote.items():
                cur = cand.get(domain)
                if cur is None or (r[7], r[5]) > (cur[7], cur[5]):
                    cand[domain] = tuple(r)
            out = list(cand.values())
        return sorted(out, key=lambda r: (-(r[7] * max(r[5], 1e-9)),
                                          r[0]))

    def summary(self) -> Dict[str, dict]:
        """Per-domain block for bench lanes and ctl top."""
        out: Dict[str, dict] = {}
        for (domain, op, fragment, actor, node, busy, bp, streak,
             sustained, epoch, diag) in self.rows():
            out[domain or "(global)"] = {
                "operator": op, "fragment": fragment, "actor": actor,
                "busy_ratio": busy, "downstream_backpressure": bp,
                "streak": streak, "sustained": bool(sustained),
                "diagnosis": diag}
        return out

    def clear(self) -> None:
        from risingwave_tpu.utils.metrics import STREAMING
        with self._lock:
            for domain, st in self._domains.items():
                if st.op is not None:
                    STREAMING.bottleneck_streak.remove(
                        domain=domain, operator=st.op)
            self._domains.clear()
            self._remote.clear()


# the process-global analyzer (coordinator-side: the walker reads the
# coordinator's topology/utilization views)
BOTTLENECKS = BottleneckAnalyzer()
