"""Exchange channels: bounded, permit-based message passing between actors.

Reference parity: src/stream/src/executor/exchange/permit.rs:35,75,111,152 —
bounded channels with *separate* budgets for data chunks (cost = row
cardinality, so big chunks consume proportional credit) and barriers (their
own small budget so backpressure on data never blocks checkpoints for long).

TPU re-design: asyncio is the tokio analog. The same Sender/Receiver pair is
the local exchange; a remote exchange (multi-host DCN) would put a serializer
behind the same interface — collectives over ICI replace hash-exchange
*within* a mesh (see parallel/), so these channels only carry host-edge
traffic: source ingestion, cross-fragment pipes, sink output.
"""

from __future__ import annotations

import asyncio
import time
from contextvars import ContextVar
from typing import AsyncIterator, List, Optional, Tuple

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.stream.message import Barrier, Message, Watermark
from risingwave_tpu.utils.metrics import STREAMING as _METRICS


class ChannelClosed(Exception):
    """Send on a channel whose receiver is gone, or recv after close+drain."""


# -- sender-side backpressure accounting (ISSUE 14) -----------------------
# Credit park time used to disappear into whoever awaited the send: a
# straggler diagnosis then blames the VICTIM of a slow consumer. Every
# park is now (a) metered per channel (stream_backpressure_wait_seconds)
# and (b) charged to the context's accumulator so the utilization
# tricolor can subtract it from busy. Two ContextVar scopes:
#   _PARK  — innermost MonitoredExecutor pull (stream/monitor.py pushes
#            its cell around each inner __anext__, exactly like the
#            phase-ledger cells), for sends that happen INSIDE a pull;
#   _METER — the owning actor's task-scoped meter (stream/actor.py sets
#            it for the whole run), for dispatch sends between pulls.
# ContextVars are asyncio-task aware, so interleaved actors never
# cross-charge; merge pumps inherit their parent actor's context.
_PARK: ContextVar[Optional[List[float]]] = ContextVar(
    "exchange_park_cell", default=None)
_METER: ContextVar[Optional[List[float]]] = ContextVar(
    "exchange_actor_meter", default=None)


def set_actor_meter(meter: Optional[List[float]]):
    """Bind the actor-task backpressure meter (stream/actor.py)."""
    return _METER.set(meter)


def current_actor_meter() -> Optional[List[float]]:
    """The running actor task's meter (the monitor's root wrapper
    drains it at each barrier flush)."""
    return _METER.get()


def push_park_cell(cell: List[float]):
    return _PARK.set(cell)


def pop_park_cell(token) -> None:
    _PARK.reset(token)


def note_backpressure(seconds: float,
                      channel: Optional[str] = None) -> None:
    """Record one sender park: per-channel Prometheus counter plus the
    context's tricolor accumulator (shared with stream/remote.py)."""
    if seconds <= 0:
        return
    if channel:
        _METRICS.backpressure_wait.inc(seconds, channel=channel)
    cell = _PARK.get()
    if cell is not None:
        cell[0] += seconds
        return
    meter = _METER.get()
    if meter is not None:
        meter[0] += seconds


class _Shared:
    def __init__(self, chunk_permits: int, barrier_permits: int,
                 max_chunk_cost: int, edge: Optional[str] = None):
        self.queue: asyncio.Queue = asyncio.Queue()
        self.chunk_permits = chunk_permits
        self.barrier_permits = barrier_permits
        self.max_chunk_cost = max_chunk_cost
        self.cond = asyncio.Condition()
        self.closed = False
        # labeled edges feed the back-pressure/queue-depth series
        # (stream_exchange_backpressure analog); anonymous channels
        # (unit-test plumbing) skip the metric path entirely. Series
        # handles cache the label key — sends are per-message.
        self.edge = edge
        if edge:
            self.m_backpressure = \
                _METRICS.exchange_backpressure.labeled(edge=edge)
            self.m_sends = _METRICS.exchange_send_count.labeled(
                edge=edge)
            self.m_depth = _METRICS.exchange_queue_depth.labeled(
                edge=edge)


def _chunk_cost(shared: _Shared, chunk: StreamChunk) -> int:
    # Compacted/coalesced chunks KNOW their visible cardinality
    # (dense_rows, no host sum) — charge the true row count so a
    # post-dispatch sliver no longer burns capacity-x credit and
    # stalls its upstream early. For unestablished chunks cardinality()
    # would be a host sync per send; capacity is free and is the true
    # memory footprint of the padded arrays, so those keep paying
    # capacity.
    cost = chunk.dense_rows if chunk.dense_rows is not None \
        else chunk.capacity
    return max(1, min(cost, shared.max_chunk_cost))


class Sender:
    def __init__(self, shared: _Shared):
        self._s = shared

    async def send(self, msg: Message) -> None:
        s = self._s
        t0 = time.perf_counter() if s.edge else 0.0
        if isinstance(msg, StreamChunk):
            cost = _chunk_cost(s, msg)
            park0 = 0.0
            async with s.cond:
                if not (s.closed or s.chunk_permits >= cost):
                    # the sender is about to PARK for credits: that
                    # wall time is backpressure, not processing — meter
                    # it per channel and charge the context's tricolor
                    # accumulator (the fast path pays only this branch)
                    park0 = time.perf_counter()
                    await s.cond.wait_for(
                        lambda: s.closed or s.chunk_permits >= cost)
                if s.closed:
                    if park0:
                        note_backpressure(time.perf_counter() - park0,
                                          s.edge)
                    raise ChannelClosed
                s.chunk_permits -= cost
            if park0:
                note_backpressure(time.perf_counter() - park0, s.edge)
            s.queue.put_nowait(("chunk", cost, msg))
        elif isinstance(msg, Barrier):
            park0 = 0.0
            async with s.cond:
                if not (s.closed or s.barrier_permits >= 1):
                    park0 = time.perf_counter()
                    await s.cond.wait_for(
                        lambda: s.closed or s.barrier_permits >= 1)
                if s.closed:
                    if park0:
                        note_backpressure(time.perf_counter() - park0,
                                          s.edge)
                    raise ChannelClosed
                s.barrier_permits -= 1
            if park0:
                note_backpressure(time.perf_counter() - park0, s.edge)
            s.queue.put_nowait(("barrier", 1, msg))
        else:  # watermarks are control-plane: unmetered
            if s.closed:
                raise ChannelClosed
            s.queue.put_nowait(("watermark", 0, msg))
        if s.edge:
            # permit-acquisition time IS the back-pressure signal: a
            # full downstream queue shows up as senders parked here
            s.m_backpressure.inc(time.perf_counter() - t0)
            s.m_sends.inc()
            s.m_depth.set(s.queue.qsize())

    def close(self) -> None:
        self._s.queue.put_nowait(("eos", 0, None))


class Receiver:
    def __init__(self, shared: _Shared):
        self._s = shared

    async def recv(self) -> Message:
        s = self._s
        kind, cost, msg = await s.queue.get()
        if kind == "eos":
            if s.edge:     # the edge is dead: no stale gauge series
                _METRICS.exchange_queue_depth.remove(edge=s.edge)
            raise ChannelClosed
        if s.edge and not s.closed:
            s.m_depth.set(s.queue.qsize())
        if cost:
            async with s.cond:
                if kind == "chunk":
                    s.chunk_permits += cost
                else:
                    s.barrier_permits += 1
                s.cond.notify_all()
        return msg

    def try_recv(self) -> Optional[Message]:
        """Non-blocking recv: None if empty (source barrier-select path)."""
        s = self._s
        try:
            kind, cost, msg = s.queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if kind == "eos":
            if s.edge:
                _METRICS.exchange_queue_depth.remove(edge=s.edge)
            raise ChannelClosed
        if cost:
            # return permits without blocking: schedule the notify
            if kind == "chunk":
                s.chunk_permits += cost
            else:
                s.barrier_permits += 1
            try:
                loop = asyncio.get_running_loop()
                loop.create_task(self._notify())
            except RuntimeError:
                pass
        return msg

    async def _notify(self) -> None:
        async with self._s.cond:
            self._s.cond.notify_all()

    def close(self) -> None:
        """Receiver drop: unblock any sender waiting for permits."""
        s = self._s

        async def _close():
            async with s.cond:
                s.closed = True
                s.cond.notify_all()

        s.closed = True
        if s.edge:
            # stale gauge series would keep reporting a dead edge
            _METRICS.exchange_queue_depth.remove(edge=s.edge)
        try:
            loop = asyncio.get_running_loop()
            loop.create_task(_close())
        except RuntimeError:
            pass  # no loop: flag alone is enough

    async def __aiter__(self) -> AsyncIterator[Message]:
        while True:
            try:
                yield await self.recv()
            except ChannelClosed:
                return


def channel(chunk_permits: int = 32768, barrier_permits: int = 4,
            max_chunk_cost: Optional[int] = None,
            edge: Optional[str] = None) -> Tuple[Sender, Receiver]:
    """Bounded exchange channel (permit.rs:35 `channel` analog).

    max_chunk_cost caps a single chunk's cost below the full budget so one
    oversized chunk can always eventually pass. `edge` names the channel
    in the exchange metric families (back-pressure time, send count,
    queue depth); unnamed channels are unmetered.
    """
    if max_chunk_cost is None:
        max_chunk_cost = max(1, chunk_permits // 2)
    shared = _Shared(chunk_permits, barrier_permits, max_chunk_cost,
                     edge=edge)
    return Sender(shared), Receiver(shared)


def channel_for_test(edge: Optional[str] = None
                     ) -> Tuple[Sender, Receiver]:
    return channel(chunk_permits=1 << 20, barrier_permits=64, edge=edge)
