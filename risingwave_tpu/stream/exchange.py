"""Exchange channels: bounded, permit-based message passing between actors.

Reference parity: src/stream/src/executor/exchange/permit.rs:35,75,111,152 —
bounded channels with *separate* budgets for data chunks (cost = row
cardinality, so big chunks consume proportional credit) and barriers (their
own small budget so backpressure on data never blocks checkpoints for long).

TPU re-design: asyncio is the tokio analog. The same Sender/Receiver pair is
the local exchange; a remote exchange (multi-host DCN) would put a serializer
behind the same interface — collectives over ICI replace hash-exchange
*within* a mesh (see parallel/), so these channels only carry host-edge
traffic: source ingestion, cross-fragment pipes, sink output.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Optional, Tuple

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.stream.message import Barrier, Message, Watermark


class ChannelClosed(Exception):
    """Send on a channel whose receiver is gone, or recv after close+drain."""


class _Shared:
    def __init__(self, chunk_permits: int, barrier_permits: int,
                 max_chunk_cost: int):
        self.queue: asyncio.Queue = asyncio.Queue()
        self.chunk_permits = chunk_permits
        self.barrier_permits = barrier_permits
        self.max_chunk_cost = max_chunk_cost
        self.cond = asyncio.Condition()
        self.closed = False


def _chunk_cost(shared: _Shared, chunk: StreamChunk) -> int:
    # cardinality() is a host sync; capacity is free and is the true memory
    # footprint of the padded device arrays, so credit by capacity.
    return min(chunk.capacity, shared.max_chunk_cost)


class Sender:
    def __init__(self, shared: _Shared):
        self._s = shared

    async def send(self, msg: Message) -> None:
        s = self._s
        if isinstance(msg, StreamChunk):
            cost = _chunk_cost(s, msg)
            async with s.cond:
                await s.cond.wait_for(
                    lambda: s.closed or s.chunk_permits >= cost)
                if s.closed:
                    raise ChannelClosed
                s.chunk_permits -= cost
            s.queue.put_nowait(("chunk", cost, msg))
        elif isinstance(msg, Barrier):
            async with s.cond:
                await s.cond.wait_for(
                    lambda: s.closed or s.barrier_permits >= 1)
                if s.closed:
                    raise ChannelClosed
                s.barrier_permits -= 1
            s.queue.put_nowait(("barrier", 1, msg))
        else:  # watermarks are control-plane: unmetered
            if s.closed:
                raise ChannelClosed
            s.queue.put_nowait(("watermark", 0, msg))

    def close(self) -> None:
        self._s.queue.put_nowait(("eos", 0, None))


class Receiver:
    def __init__(self, shared: _Shared):
        self._s = shared

    async def recv(self) -> Message:
        s = self._s
        kind, cost, msg = await s.queue.get()
        if kind == "eos":
            raise ChannelClosed
        if cost:
            async with s.cond:
                if kind == "chunk":
                    s.chunk_permits += cost
                else:
                    s.barrier_permits += 1
                s.cond.notify_all()
        return msg

    def try_recv(self) -> Optional[Message]:
        """Non-blocking recv: None if empty (source barrier-select path)."""
        s = self._s
        try:
            kind, cost, msg = s.queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if kind == "eos":
            raise ChannelClosed
        if cost:
            # return permits without blocking: schedule the notify
            if kind == "chunk":
                s.chunk_permits += cost
            else:
                s.barrier_permits += 1
            try:
                loop = asyncio.get_running_loop()
                loop.create_task(self._notify())
            except RuntimeError:
                pass
        return msg

    async def _notify(self) -> None:
        async with self._s.cond:
            self._s.cond.notify_all()

    def close(self) -> None:
        """Receiver drop: unblock any sender waiting for permits."""
        s = self._s

        async def _close():
            async with s.cond:
                s.closed = True
                s.cond.notify_all()

        s.closed = True
        try:
            loop = asyncio.get_running_loop()
            loop.create_task(_close())
        except RuntimeError:
            pass  # no loop: flag alone is enough

    async def __aiter__(self) -> AsyncIterator[Message]:
        while True:
            try:
                yield await self.recv()
            except ChannelClosed:
                return


def channel(chunk_permits: int = 32768, barrier_permits: int = 4,
            max_chunk_cost: Optional[int] = None
            ) -> Tuple[Sender, Receiver]:
    """Bounded exchange channel (permit.rs:35 `channel` analog).

    max_chunk_cost caps a single chunk's cost below the full budget so one
    oversized chunk can always eventually pass.
    """
    if max_chunk_cost is None:
        max_chunk_cost = max(1, chunk_permits // 2)
    shared = _Shared(chunk_permits, barrier_permits, max_chunk_cost)
    return Sender(shared), Receiver(shared)


def channel_for_test() -> Tuple[Sender, Receiver]:
    return channel(chunk_permits=1 << 20, barrier_permits=64)
