"""Stream messages: the protocol every executor speaks.

Reference parity: src/stream/src/executor/mod.rs:173 (``Message::{Chunk,
Barrier, Watermark}``), :223-246 (``Mutation``), :622 (``Barrier``);
proto/stream_plan.proto:85-122 (Barrier/Watermark wire shape);
BarrierKind: proto/stream_plan.proto:86-92.

TPU re-design notes: messages are host-side control objects — the device
only ever sees the arrays inside a ``StreamChunk``. A ``Barrier`` is the
global synchronization token; everything between two barriers is one
"micro-batch" that kernels may process as a single fused device step.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Union

import numpy as np

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.epoch import EpochPair
from risingwave_tpu.common.types import DataType


class BarrierKind(enum.Enum):
    """proto/stream_plan.proto:86-92: not every barrier is a checkpoint."""

    INITIAL = "initial"        # first barrier after boot/recovery
    BARRIER = "barrier"        # flush memtables, no durable sync
    CHECKPOINT = "checkpoint"  # flush + sync: durable recovery point

    @property
    def is_checkpoint(self) -> bool:
        return self in (BarrierKind.INITIAL, BarrierKind.CHECKPOINT)


# ---------------------------------------------------------------------------
# Mutations: control-plane commands piggybacked on barriers
# (src/stream/src/executor/mod.rs:223 — Add/Update/Stop/Pause/Resume)


@dataclass(frozen=True)
class AddMutation:
    """New downstream actors added to dispatchers (job creation)."""

    # dispatcher updates keyed by upstream actor id: list of new outputs
    adds: Dict[int, list] = field(default_factory=dict)


@dataclass(frozen=True)
class UpdateMutation:
    """Scaling / reschedule: vnode bitmaps + dispatcher output swaps."""

    # actor_id -> new vnode ownership bitmap (np.bool_[VNODE_COUNT])
    vnode_bitmaps: Dict[int, np.ndarray] = field(default_factory=dict)
    # actor_id -> replacement output lists for its dispatcher
    dispatcher_updates: Dict[int, list] = field(default_factory=dict)
    dropped_actors: FrozenSet[int] = frozenset()


@dataclass(frozen=True)
class StopMutation:
    """Actors to stop (job drop). Actors in the set terminate after this
    barrier; their downstream channels close."""

    actors: FrozenSet[int] = frozenset()


@dataclass(frozen=True)
class PauseMutation:
    """Pause sources (no data until Resume; barriers still flow)."""


@dataclass(frozen=True)
class ResumeMutation:
    """Resume paused sources."""


@dataclass(frozen=True)
class SourceChangeSplitMutation:
    """Reassign source splits to actors (actor_id -> split id list)."""

    assignments: Dict[int, tuple] = field(default_factory=dict)


Mutation = Union[AddMutation, UpdateMutation, StopMutation, PauseMutation,
                 ResumeMutation, SourceChangeSplitMutation]


# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Barrier:
    """The checkpoint token (executor/mod.rs:622 analog).

    Flows from sources to sinks through every channel; aligned at fan-in.
    Carrying `epoch = EpochPair(curr, prev)`: data after this barrier lands
    at `curr`; state committed by this barrier is readable at `prev`.
    """

    epoch: EpochPair
    kind: BarrierKind = BarrierKind.CHECKPOINT
    mutation: Optional[Mutation] = None
    passed_actors: tuple = ()  # debug trail, actor ids appended in transit

    @property
    def is_checkpoint(self) -> bool:
        return self.kind.is_checkpoint

    def is_stop(self, actor_id: int) -> bool:
        return (isinstance(self.mutation, StopMutation)
                and actor_id in self.mutation.actors)

    def is_pause(self) -> bool:
        return isinstance(self.mutation, PauseMutation)

    def is_resume(self) -> bool:
        return isinstance(self.mutation, ResumeMutation)

    def with_passed(self, actor_id: int) -> "Barrier":
        return Barrier(self.epoch, self.kind, self.mutation,
                       self.passed_actors + (actor_id,))

    def __repr__(self) -> str:
        m = f", {type(self.mutation).__name__}" if self.mutation else ""
        return f"Barrier({self.epoch.curr.value:#x}, {self.kind.value}{m})"


@dataclass(frozen=True)
class Watermark:
    """Monotonic lower bound on future values of one column
    (executor/mod.rs watermark; used for state cleaning and EOWC)."""

    col_idx: int
    data_type: DataType
    value: object  # host scalar in the column's logical domain

    def with_idx(self, idx: int) -> "Watermark":
        return Watermark(idx, self.data_type, self.value)

    def __repr__(self) -> str:
        return f"Watermark(col={self.col_idx}, {self.value})"


Message = Union[StreamChunk, Barrier, Watermark]


def is_chunk(m: Message) -> bool:
    return isinstance(m, StreamChunk)


def is_barrier(m: Message) -> bool:
    return isinstance(m, Barrier)


def is_watermark(m: Message) -> bool:
    return isinstance(m, Watermark)
