"""Per-MV event-time freshness: what an MV's consumer experiences.

The phase ledger (utils/ledger.py) explains where a barrier's wall
time went; nothing there measures what a *reader* of the MV sees —
how far the materialized result lags the data's own timestamps. This
module closes that gap with barrier-lineage freshness accounting
(the Hazelcast-Jet stance of arxiv 2103.10169 applied to staleness:
a lag you cannot attribute per barrier is a lag you cannot budget):

- **Ingest high-watermark.** Every source executor reports, per chunk,
  the max event-time it has ingested (the first TIMESTAMP column of
  its schema; sources without one fall back to arrival wall-clock, so
  freshness degrades to processing lag instead of vanishing).
- **Epoch frontiers.** When a source passes barrier X, it stamps
  ``frontier[source][X] = (hwm, wall)``: everything ingested before
  barrier X carries event-time ≤ hwm and entered by ``wall``.
- **Visibility.** When a MaterializeExecutor passes barrier X, all
  data ingested before X has been applied and commits with X's
  collection — the MV's visible event frontier IS the source frontier
  at X. Per-barrier lag samples follow:

      freshness_lag_s  = current ingest hwm − frontier hwm at X
      wall_lag_s       = now − frontier wall stamp at X

  (event-time seconds and wall seconds respectively; multi-source MVs
  take the worst source). This is lineage freshness: an EOWC gate's
  deliberate watermark holdback is not counted against the pipeline.

Cross-process merge: workers drain their RAW parts (hwms, frontiers,
visibility events) to the coordinator — ``drain_dict``/``ingest`` —
which resolves pending visibility events against merged frontiers, so
a source fragment on worker 0 and its materialize on worker 1 still
produce one coherent per-MV lag series.

Output surfaces: ``stream_mv_freshness_lag_seconds{mv}`` +
``stream_mv_freshness_wall_lag_seconds{mv}`` gauges, the
``rw_mv_freshness`` system table, per-barrier ``freshness_lag_s.<mv>``
rows in ``rw_metrics_history`` (folded in at ledger seal), the bench
``freshness`` block per lane, and ``ctl top``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

# bounded per-source epoch-frontier window: epochs outlive their
# usefulness once the MV passed them; the bound guards epochs that
# never materialize (dropped jobs, recovery rollbacks)
FRONTIER_WINDOW = 512
SAMPLE_WINDOW = 1024
PENDING_WINDOW = 256

_ENABLED = True


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


class _MvState:
    __slots__ = ("sources", "domain", "samples", "last")

    def __init__(self, sources: Tuple[str, ...], domain: str):
        self.sources = sources
        self.domain = domain
        # (epoch, lag_s, wall_lag_s, ts) rings — percentile source
        self.samples: deque = deque(maxlen=SAMPLE_WINDOW)
        self.last: Optional[Tuple[int, float, float, float]] = None


class FreshnessTracker:
    """Process-global freshness registry (workers drain theirs to the
    coordinator, like the span tracer and the phase ledger)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # source → (hwm_us, wall_s at last ingest)
        self._hwm: Dict[str, Tuple[int, float]] = {}
        # source → OrderedDict(epoch → (hwm_us, wall_s))
        self._frontiers: Dict[str, "OrderedDict[int, Tuple[int, float]]"] = {}
        self._mvs: Dict[str, _MvState] = {}
        # visibility events whose frontiers haven't arrived yet
        # (cross-process: materialize on a different worker than the
        # source) — resolved during ingest()
        self._pending: deque = deque(maxlen=PENDING_WINDOW)
        # strict-mode evidence (tests/conftest.py): lag samples must be
        # finite and non-negative once the first frontier resolves
        self._violations: List[tuple] = []

    # -- source side ---------------------------------------------------
    def note_ingest(self, source: str, hwm_us: Optional[int],
                    wall_s: Optional[float] = None) -> None:
        """One chunk ingested: advance the source's event-time high
        watermark (None = no event-time column: arrival wall-clock
        stands in, microseconds)."""
        if not _ENABLED:
            return
        now = time.time() if wall_s is None else wall_s
        if hwm_us is None:
            hwm_us = int(now * 1e6)
        with self._lock:
            prev = self._hwm.get(source)
            if prev is None or hwm_us > prev[0]:
                self._hwm[source] = (int(hwm_us), now)
            else:                       # hwm monotone; wall still moves
                self._hwm[source] = (prev[0], now)

    def note_source_barrier(self, source: str, epoch: int) -> None:
        """The source passed barrier ``epoch``: everything it ingested
        so far precedes that barrier. Parallel splits of one source
        each call this — the frontier keeps the MINIMUM hwm (the
        conservative cross-split frontier)."""
        if not _ENABLED:
            return
        now = time.time()
        with self._lock:
            hwm = self._hwm.get(source)
            if hwm is None:
                # nothing ingested yet: an EMPTY frontier, marked with
                # hwm=None — NOT an arrival-clock stand-in, which would
                # compare a wall-clock microsecond value against later
                # historical event times and mint a huge negative lag
                hwm = (None, now)
            fr = self._frontiers.setdefault(source, OrderedDict())
            cur = fr.get(epoch)
            if cur is None or (hwm[0] is not None
                               and (cur[0] is None or hwm[0] < cur[0])):
                # the frontier's wall stamp is when its NEWEST data
                # was ingested (the hwm's stamp), so wall_lag measures
                # ingest→visible latency, not barrier bookkeeping time.
                # A real hwm replaces an empty sibling-split marker,
                # never the other way around (approximation: one empty
                # split must not zero a populated source's frontier).
                fr[epoch] = hwm
            while len(fr) > FRONTIER_WINDOW:
                fr.popitem(last=False)

    # -- MV side -------------------------------------------------------
    def register_mv(self, mv: str, sources, domain: str = "") -> None:
        """Associate one materialized job with the sources whose
        frontiers bound its visible data (called at deploy; re-register
        on reschedule overwrites)."""
        with self._lock:
            self._mvs[mv] = _MvState(tuple(sources), domain)

    def unregister_mv(self, mv: str) -> None:
        with self._lock:
            self._mvs.pop(mv, None)
        from risingwave_tpu.utils.metrics import STREAMING
        STREAMING.mv_freshness_lag.remove(mv=mv)
        STREAMING.mv_freshness_wall_lag.remove(mv=mv)

    def set_domain(self, mv: str, domain: str) -> None:
        with self._lock:
            st = self._mvs.get(mv)
            if st is not None:
                st.domain = domain

    def note_visible(self, mv: str, epoch: int,
                     wall_s: Optional[float] = None) -> None:
        """The MV's materialize executor passed barrier ``epoch``:
        every chunk ingested before that barrier is applied (and
        commits with the barrier's collection)."""
        if not _ENABLED:
            return
        now = time.time() if wall_s is None else wall_s
        with self._lock:
            if not self._resolve_locked(mv, epoch, now):
                self._pending.append((mv, int(epoch), now))

    def _resolve_locked(self, mv: str, epoch: int, now: float) -> bool:
        """Compute one lag sample if every source frontier for the
        epoch is known. Returns False when a frontier is missing (the
        cross-process case — ingest() retries it)."""
        st = self._mvs.get(mv)
        if st is None:
            # not registered HERE: park it — on a worker process the
            # registration lives on the coordinator, and dropping the
            # event would make the whole drain/merge chain a no-op
            # (bounded ring; never-registered test pipelines just age
            # out of it)
            return False
        if st.last is not None and st.last[0] == epoch:
            # N distributed slices of one MV each pass the barrier:
            # one sample per (mv, epoch), not one per slice
            return True
        lag = wall_lag = 0.0
        for src in st.sources or ():
            fr = self._frontiers.get(src, {}).get(epoch)
            if fr is None:
                return False
            f_hwm, f_wall = fr
            if f_hwm is not None:
                cur = self._hwm.get(src, (f_hwm, f_wall))
                lag = max(lag, (cur[0] - f_hwm) / 1e6)
            # empty frontier (nothing ingested before the barrier):
            # the MV is behind by no visible event-time span — only
            # the wall clock moves
            wall_lag = max(wall_lag, now - f_wall)
        if not (lag >= 0.0 and wall_lag >= 0.0
                and lag == lag and wall_lag == wall_lag
                and lag != float("inf") and wall_lag != float("inf")):
            self._violations.append((mv, epoch, lag, wall_lag))
            lag, wall_lag = max(lag, 0.0), max(wall_lag, 0.0)
        st.samples.append((int(epoch), lag, wall_lag, now))
        st.last = (int(epoch), lag, wall_lag, now)
        from risingwave_tpu.utils.metrics import STREAMING
        STREAMING.mv_freshness_lag.set(lag, mv=mv)
        STREAMING.mv_freshness_wall_lag.set(wall_lag, mv=mv)
        return True

    # -- reads ---------------------------------------------------------
    def history_extra(self, epoch: int, domain: str) -> Dict[str, float]:
        """Per-barrier rw_metrics_history payload: the freshness
        samples of the sealed domain's MVs at this epoch (folded into
        the ledger seal's ``extra`` dict)."""
        out: Dict[str, float] = {}
        with self._lock:
            for mv, st in self._mvs.items():
                if st.domain != domain or st.last is None:
                    continue
                e, lag, wall_lag, _ts = st.last
                if e == epoch:
                    out[f"freshness_lag_s.{mv}"] = round(lag, 6)
                    out[f"freshness_wall_lag_s.{mv}"] = round(wall_lag, 6)
        return out

    def percentile(self, mv: str, q: float,
                   wall: bool = False) -> Optional[float]:
        from risingwave_tpu.utils.metrics import exact_quantile
        with self._lock:
            st = self._mvs.get(mv)
            if st is None or not st.samples:
                return None
            idx = 2 if wall else 1
            return exact_quantile([s[idx] for s in st.samples], q)

    def rows(self) -> List[tuple]:
        """(mv, domain, samples, epoch, lag_s, wall_lag_s, lag_p50_s,
        lag_p99_s, wall_lag_p99_s) — the rw_mv_freshness payload."""
        from risingwave_tpu.utils.metrics import exact_quantile
        out = []
        with self._lock:
            for mv in sorted(self._mvs):
                st = self._mvs[mv]
                if st.last is None:
                    out.append((mv, st.domain, 0, 0, None, None,
                                None, None, None))
                    continue
                e, lag, wall_lag, _ts = st.last
                lags = [s[1] for s in st.samples]
                walls = [s[2] for s in st.samples]
                out.append((mv, st.domain, len(st.samples), e,
                            round(lag, 6), round(wall_lag, 6),
                            round(exact_quantile(lags, 0.5), 6),
                            round(exact_quantile(lags, 0.99), 6),
                            round(exact_quantile(walls, 0.99), 6)))
        return out

    def summary(self) -> Dict[str, dict]:
        """Per-MV freshness block (bench lanes, ctl top)."""
        out: Dict[str, dict] = {}
        for (mv, domain, n, _e, lag, wall_lag, p50, p99,
             wall_p99) in self.rows():
            if not n:
                continue
            out[mv] = {"domain": domain, "samples": n,
                       "lag_s": lag, "wall_lag_s": wall_lag,
                       "lag_p50_s": p50, "lag_p99_s": p99,
                       "wall_lag_p99_s": wall_p99}
        return out

    # -- strict-mode gate (tests/conftest.py) --------------------------
    def gate_violations(self) -> List[tuple]:
        with self._lock:
            return list(self._violations)

    # -- cross-process merge -------------------------------------------
    def drain_dict(self) -> dict:
        """Pop this process's raw parts for the coordinator (samples
        stay local — the coordinator recomputes them from the parts, so
        repeated drains never double-count)."""
        with self._lock:
            out = {
                "hwm": {s: [h, w] for s, (h, w) in self._hwm.items()},
                "frontiers": {
                    s: {str(e): [h, w] for e, (h, w) in fr.items()}
                    for s, fr in self._frontiers.items()},
                "visible": [[mv, e, w] for mv, e, w in self._pending],
                "mvs": {mv: {"sources": list(st.sources),
                             "domain": st.domain}
                        for mv, st in self._mvs.items()},
            }
            self._pending.clear()
        return out

    def ingest(self, d: dict, default_now: Optional[float] = None
               ) -> int:
        """Merge one worker's drained parts; resolve any visibility
        events (theirs and ours) the merged frontiers now cover."""
        n = 0
        now = time.time() if default_now is None else default_now
        with self._lock:
            for mv, spec in (d.get("mvs") or {}).items():
                if mv not in self._mvs:
                    self._mvs[mv] = _MvState(
                        tuple(spec.get("sources") or ()),
                        spec.get("domain", ""))
            for s, (h, w) in (d.get("hwm") or {}).items():
                cur = self._hwm.get(s)
                if cur is None or int(h) > cur[0]:
                    self._hwm[s] = (int(h), float(w))
            for s, fr in (d.get("frontiers") or {}).items():
                mine = self._frontiers.setdefault(s, OrderedDict())
                for e, (h, w) in fr.items():
                    e = int(e)
                    cur = mine.get(e)
                    # same min-merge as note_source_barrier: reals
                    # keep the minimum, a real replaces an empty
                    # (None) marker, an empty never replaces a real
                    if cur is None or (h is not None
                                       and (cur[0] is None
                                            or int(h) < cur[0])):
                        mine[e] = (None if h is None else int(h),
                                   float(w))
                while len(mine) > FRONTIER_WINDOW:
                    mine.popitem(last=False)
            pend = list(self._pending)
            self._pending.clear()
            for mv, e, w in (d.get("visible") or ()):
                pend.append((mv, int(e), float(w)))
            for mv, e, w in pend:
                if self._resolve_locked(mv, e, w if w else now):
                    n += 1
                else:
                    self._pending.append((mv, e, w))
        return n

    def clear(self) -> None:
        with self._lock:
            self._hwm.clear()
            self._frontiers.clear()
            self._mvs.clear()
            self._pending.clear()
            self._violations.clear()


# the process-global tracker (workers drain to the coordinator)
FRESHNESS = FreshnessTracker()


def event_time_index(schema) -> Optional[int]:
    """First TIMESTAMP/TIMESTAMPTZ column of a source schema — the
    event-time heuristic sources derive their ingest hwm from (None:
    arrival-clock fallback)."""
    from risingwave_tpu.common.types import DataType
    for i, f in enumerate(schema):
        if f.data_type in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
            return i
    return None


def chunk_event_hwm(chunk, col_idx: Optional[int]) -> Optional[int]:
    """Max event-time (microseconds) over a chunk's visible rows; None
    when the schema has no event-time column or nothing is visible."""
    if col_idx is None:
        return None
    import numpy as np
    vis = np.asarray(chunk.visibility)
    if not vis.any():
        return None
    vals = np.asarray(chunk.columns[col_idx].values)
    validity = chunk.columns[col_idx].validity
    if validity is not None:
        vis = vis & np.asarray(validity)
        if not vis.any():
            return None
    return int(vals[vis].max())
