"""Fan-out: DispatchExecutor with hash/broadcast/simple dispatchers.

Reference parity: src/stream/src/executor/dispatch.rs:45 (DispatchExecutor
drives one upstream into N dispatchers), :343 (dispatcher enum), :507
(Broadcast), :582-690 (HashDataDispatcher — vnode of dist key → output via
ActorMapping, per-output visibility masks, Update pairs kept atomic);
DispatcherType proto/stream_plan.proto:671.

TPU re-design: hashing the whole chunk is ONE vectorized device pass
(`vnodes_of`); each downstream gets its vnode slice COMPACTED to a
dense chunk (stream/coalesce.compact) — at parallelism N a masked
full-capacity chunk would otherwise charge N× its true exchange
credit, ship N× its wire bytes and cost N full device dispatches
downstream. Zero-visible-row slices are suppressed entirely. On a
multi-chip mesh the same vnode math becomes the all-to-all permutation
in parallel/ (this host dispatcher serves single-host fan-out and
tests).
"""

from __future__ import annotations

import abc
from typing import AsyncIterator, Dict, List, Optional, Sequence

import numpy as np

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.hash import VnodeMapping
from risingwave_tpu.stream.exchange import ChannelClosed, Sender
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.message import (
    Barrier, Message, UpdateMutation, Watermark, is_barrier, is_chunk,
)


class Output:
    """One downstream edge: a named sender (dispatch.rs `Output` analog)."""

    def __init__(self, downstream_actor_id: int, sender: Sender):
        self.actor_id = downstream_actor_id
        self.sender = sender

    async def send(self, msg: Message) -> None:
        await self.sender.send(msg)

    def close(self) -> None:
        self.sender.close()


class Dispatcher(abc.ABC):
    dispatcher_id: int = 0

    @abc.abstractmethod
    async def dispatch_data(self, chunk: StreamChunk) -> None: ...

    @abc.abstractmethod
    async def dispatch_barrier(self, barrier: Barrier) -> None: ...

    async def dispatch_watermark(self, wm: Watermark) -> None:
        for out in self.outputs():
            await out.send(wm)

    @abc.abstractmethod
    def outputs(self) -> List[Output]: ...

    def update_outputs(self, new_outputs: List[Output]) -> None:
        """Swap downstream set at a barrier (scaling)."""
        self._set_outputs(new_outputs)

    @abc.abstractmethod
    def _set_outputs(self, outputs: List[Output]) -> None: ...

    def close(self) -> None:
        for out in self.outputs():
            out.close()


def _is_empty(chunk: StreamChunk) -> bool:
    """Zero visible rows: nothing downstream could do with it but pay
    a send + a recv + (for keyed executors) a device dispatch."""
    from risingwave_tpu.stream.coalesce import is_empty
    return is_empty(chunk)


class SimpleDispatcher(Dispatcher):
    """Single downstream (DispatcherType::SIMPLE)."""

    def __init__(self, output: Output, dispatcher_id: int = 0):
        self._output = output
        self.dispatcher_id = dispatcher_id

    async def dispatch_data(self, chunk: StreamChunk) -> None:
        if _is_empty(chunk):
            return
        await self._output.send(chunk)

    async def dispatch_barrier(self, barrier: Barrier) -> None:
        await self._output.send(barrier)

    def outputs(self) -> List[Output]:
        return [self._output]

    def _set_outputs(self, outputs: List[Output]) -> None:
        assert len(outputs) == 1
        self._output = outputs[0]


class BroadcastDispatcher(Dispatcher):
    """Replicate everything to every downstream (dispatch.rs:507)."""

    def __init__(self, outputs: Sequence[Output], dispatcher_id: int = 0):
        self._outputs = list(outputs)
        self.dispatcher_id = dispatcher_id

    async def dispatch_data(self, chunk: StreamChunk) -> None:
        if _is_empty(chunk):
            return
        for out in self._outputs:
            await out.send(chunk)

    async def dispatch_barrier(self, barrier: Barrier) -> None:
        for out in self._outputs:
            await out.send(barrier)

    def outputs(self) -> List[Output]:
        return list(self._outputs)

    def _set_outputs(self, outputs: List[Output]) -> None:
        self._outputs = list(outputs)


class RoundRobinDispatcher(Dispatcher):
    """Rotate chunks across outputs (stateless fragments only)."""

    def __init__(self, outputs: Sequence[Output], dispatcher_id: int = 0):
        self._outputs = list(outputs)
        self._cur = 0
        self.dispatcher_id = dispatcher_id

    async def dispatch_data(self, chunk: StreamChunk) -> None:
        if _is_empty(chunk):
            return
        await self._outputs[self._cur].send(chunk)
        self._cur = (self._cur + 1) % len(self._outputs)

    async def dispatch_barrier(self, barrier: Barrier) -> None:
        for out in self._outputs:
            await out.send(barrier)

    def outputs(self) -> List[Output]:
        return list(self._outputs)

    def _set_outputs(self, outputs: List[Output]) -> None:
        self._outputs = list(outputs)
        self._cur = 0


class HashDispatcher(Dispatcher):
    """Route rows by vnode of the distribution key (dispatch.rs:582).

    The chunk is hashed once (vectorized); each output receives the chunk
    with visibility restricted to its vnodes. UpdateDelete/UpdateInsert
    pairs whose halves would land on different outputs are degraded to
    Delete+Insert (dispatch.rs:640-ish invariant: a downstream must never
    see half an update pair).
    """

    def __init__(self, outputs: Sequence[Output], dist_key_indices: List[int],
                 mapping: Optional[VnodeMapping] = None,
                 dispatcher_id: int = 0):
        self._outputs = list(outputs)
        self.dist_key_indices = list(dist_key_indices)
        self.mapping = mapping or VnodeMapping.new_uniform(len(self._outputs))
        self.dispatcher_id = dispatcher_id

    def _route(self, chunk: StreamChunk) -> np.ndarray:
        """vnode → output index per row (one vectorized host pass).

        Chunks are host-resident here; the device twin of this routing is
        the all-to-all permutation in parallel/ (same hash bits).
        """
        from risingwave_tpu.common.hash import hash_strings_host, \
            vnodes_of_host
        key_cols = []
        for i in self.dist_key_indices:
            col = chunk.columns[i]
            if col.is_device:
                key_cols.append(np.asarray(col.values))
            else:
                key_cols.append(hash_strings_host(
                    np.asarray(col.values), chunk.capacity))
        vn = vnodes_of_host(key_cols)
        return np.asarray(self.mapping.owners)[vn]

    async def dispatch_data(self, chunk: StreamChunk) -> None:
        owner = self._route(chunk)
        ops = np.asarray(chunk.ops)
        vis = np.asarray(chunk.visibility)
        # atomicity of update pairs: U- at i pairs with U+ at i+1
        new_ops = ops.copy()
        idx = np.flatnonzero(vis & (ops == int(Op.UPDATE_DELETE)))
        for i in idx:
            j = i + 1
            if j < len(ops) and ops[j] == int(Op.UPDATE_INSERT) \
                    and owner[i] != owner[j]:
                new_ops[i] = int(Op.DELETE)
                new_ops[j] = int(Op.INSERT)
        out_ops = new_ops if (new_ops != ops).any() else chunk.ops
        vis_host = np.asarray(chunk.visibility)
        from risingwave_tpu.stream.coalesce import compact
        for oi, out in enumerate(self._outputs):
            sub_vis = vis_host & (owner == oi)
            # compact each slice: a 1/N-visible full-capacity chunk
            # would charge N× its true exchange credit, ship N× its
            # wire bytes and cost a full device dispatch downstream.
            # Slices with zero visible rows are suppressed entirely.
            sub = compact(StreamChunk(chunk.schema, chunk.columns,
                                      sub_vis, out_ops))
            if sub is None:
                continue
            await out.send(sub)

    async def dispatch_barrier(self, barrier: Barrier) -> None:
        # apply mapping updates carried by the barrier BEFORE forwarding:
        # post-barrier chunks must use the new routing
        m = barrier.mutation
        if isinstance(m, UpdateMutation) and \
                self.dispatcher_id in m.dispatcher_updates:
            self.update_outputs(m.dispatcher_updates[self.dispatcher_id])
        for out in self._outputs:
            await out.send(barrier)

    def outputs(self) -> List[Output]:
        return list(self._outputs)

    def _set_outputs(self, outputs: List[Output]) -> None:
        if len(outputs) != self.mapping.num_owners():
            self.mapping = self.mapping.rebalance(len(outputs))
        self._outputs = list(outputs)


class DispatchExecutor:
    """Drives one upstream executor into N dispatchers (dispatch.rs:45)."""

    def __init__(self, upstream: Executor, dispatchers: Sequence[Dispatcher],
                 actor_id: int = 0):
        self.upstream = upstream
        self.dispatchers = list(dispatchers)
        self.actor_id = actor_id

    async def run(self) -> None:
        try:
            async for msg in self.upstream.execute():
                for d in self.dispatchers:
                    if is_chunk(msg):
                        await d.dispatch_data(msg)
                    elif is_barrier(msg):
                        await d.dispatch_barrier(msg)
                    else:
                        await d.dispatch_watermark(msg)
                if is_barrier(msg) and msg.is_stop(self.actor_id):
                    break
        finally:
            for d in self.dispatchers:
                d.close()
