"""Trace-context propagation: dispatch spans + span ctx on the wire.

The stream-facing half of the epoch tracer (utils/spans.py): executors
and kernels stamp device dispatches into the current epoch's trace, and
remote exchange barriers carry a span context trailer so the receiving
worker's spans link causally to the coordinator's inject span.

Wire shape (appended to the 'B' barrier frame payload ONLY when
tracing is enabled — tracing off leaves frames byte-identical):

    trailer = magic(2B b"TC") ++ epoch(u64) ++ parent_span(u64)
              ++ send_wall_ts(f64)      — struct ">2sQQd", 26 bytes
"""

from __future__ import annotations

import struct
import time
from typing import Optional, Tuple

from risingwave_tpu.utils import spans as _spans
from risingwave_tpu.utils.spans import dispatch_span  # noqa: F401
#                     (re-export: the executors' natural import home)

_TRAILER = struct.Struct(">2sQQd")
_MAGIC = b"TC"


# -- remote-exchange span context ------------------------------------------


def barrier_trailer(barrier) -> bytes:
    """Span-context bytes to append to an outgoing 'B' frame payload
    (empty when tracing is off — the frame stays byte-identical)."""
    if not _spans.enabled():
        return b""
    epoch = barrier.epoch.curr.value
    parent = _spans.EPOCH_TRACER.root_id(epoch) or 0
    return _TRAILER.pack(_MAGIC, epoch, parent, time.time())


def decode_trailer(payload: bytes) -> Optional[Tuple[int, int, float]]:
    """(epoch, parent_span_id, send_wall_ts) if the payload ends in a
    span-context trailer, else None. The magic guards against a stop
    mutation's actor list happening to leave 26 trailing bytes."""
    if len(payload) < _TRAILER.size:
        return None
    magic, epoch, parent, ts = _TRAILER.unpack_from(
        payload, len(payload) - _TRAILER.size)
    if magic != _MAGIC:
        return None
    return epoch, parent, ts


def record_remote_transfer(payload: bytes, up: int, down: int) -> None:
    """Receiver side of one remote barrier frame: if the sender shipped
    a span context, record the exchange-transfer span — parented to the
    SENDER's inject span, so the cross-worker edge links causally —
    and adopt the sender's epoch/root for spans this process records
    next (a pure-executor worker has no barrier loop to set them)."""
    if not _spans.enabled():
        return
    ctx = decode_trailer(payload)
    if ctx is None:
        return
    epoch, parent, sent = ctx
    now = time.time()
    _spans.EPOCH_TRACER.record(
        f"exchange {up}->{down}", "exchange", epoch=epoch,
        start_s=sent, dur_s=max(0.0, now - sent),
        parent=parent or None, edge=f"{up}->{down}")
    if parent and _spans.EPOCH_TRACER.root_id(epoch) is None:
        _spans.EPOCH_TRACER.set_root(epoch, parent)
    if epoch > _spans.current_epoch():
        _spans.set_current_epoch(epoch)
