"""Per-MV resource ledger (ISSUE 16): where device-seconds, transfer
bytes, state bytes and compile traces actually go, per MV.

The phase ledger (utils/ledger.py) conserves a barrier interval's wall
clock across phases; this module splits the device-facing share of
those books BY OWNER. The split costs no new timers: every
MonitoredExecutor already scopes an AttributionCell around its pulls
(exclusive nesting — a wrapped child swaps its own cell in), and the
wrapper's ``fragment`` label IS the MV/job name. At barrier flush the
cell's device_compute seconds and h2d/d2h bytes are recorded here
against that MV before the cell folds into the phase ledger — so
Σ per-MV device-seconds ≤ the domain's device_compute by construction
(the ledger gets the same cells plus everything uncelled), which the
tier-1 attribution gate asserts per sealed epoch.

Ownership rules for shared compile caches: the module-level
``_STEP_CACHE``/``_PROG_CACHE`` dicts (parallel/join.py, parallel/agg.py)
are wrapped in :class:`CompileCache`, which bills the MV *currently
pulling* (a ContextVar the monitor sets around pulls): the first MV to
trace a program pays the miss; later MVs that reuse the entry record a
hit — a ``shared`` hit when somebody else paid the trace. That is the
marginal-compile-cost question ROADMAP item 5 asks.

Recovery/rescale charge-back is read, not hooked: ``rw_autoscaler``
rows carry their MV and duration; ``rw_recovery`` durations split
evenly across registered MVs (a documented approximation — recovery
replays every job).
"""

from __future__ import annotations

import threading
from collections import deque
from contextvars import ContextVar
from typing import Dict, List, Optional, Tuple

# one knob for the whole attribution subsystem (SET stream_costs):
# per-MV rollup, hot-key sketches and state topology flip together —
# the q7_costs_off bench arm measures every hook reduced to a
# predicate check
ENABLED = True


def enabled() -> bool:
    return ENABLED


def set_enabled(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)
    from risingwave_tpu.state import topology as _topo
    from risingwave_tpu.stream import hotkeys as _hot
    _topo.set_enabled(on)
    _hot.set_enabled(on)


def parse_costs(spec: str) -> bool:
    s = (spec or "").strip().lower()
    return s not in ("off", "0", "false", "none")


# the MV whose executor chain is currently pulling (set by
# MonitoredExecutor around inner pulls — asyncio-context scoped, so
# interleaved actors never cross-bill a compile)
_MV: ContextVar[Optional[str]] = ContextVar("rw_costs_mv",
                                            default=None)


def push_mv(mv: str):
    return _MV.set(mv)


def pop_mv(token) -> None:
    _MV.reset(token)


def current_mv() -> Optional[str]:
    return _MV.get()


class CompileCache(dict):
    """A module compile cache that bills hits/misses to the pulling MV.

    Drop-in for the plain dicts: ``get`` notes a hit when it finds a
    compiled step; ``__setitem__`` notes the miss (a fresh trace was
    paid). The key records which MV first paid each entry, so a later
    hit by a different MV counts as *shared* — compiled-program reuse
    across tenants, the serving-density win."""

    def __init__(self, kind: str):
        super().__init__()
        self.kind = kind

    def get(self, key, default=None):
        step = super().get(key, default)
        if step is not None and ENABLED:
            COSTS.note_compile(self.kind, key, hit=True)
        return step

    def __setitem__(self, key, step) -> None:
        if ENABLED:
            COSTS.note_compile(self.kind, key, hit=False)
        super().__setitem__(key, step)


class MVCosts:
    """Process-global per-MV resource totals + per-epoch pending cells."""

    # retained sealed-epoch attribution rows (the gate's evidence)
    SEALED_WINDOW = 512

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # mv -> {device_s, h2d_bytes, d2h_bytes, compile_hits,
        #         compile_misses, shared_hits, domain}
        self._mvs: Dict[str, dict] = {}
        # epoch -> mv -> [device_s, h2d_bytes, d2h_bytes] (cells
        # committed at barrier flush, folded into totals at seal)
        self._pending: Dict[int, Dict[str, List[float]]] = {}
        # (kind, key) -> first MV that paid the trace
        self._cache_owner: Dict[tuple, str] = {}
        # sealed (epoch, domain, sum_mv_device_s, domain_device_s)
        self._sealed = deque(maxlen=self.SEALED_WINDOW)

    def _mv(self, mv: str) -> dict:
        d = self._mvs.get(mv)
        if d is None:
            d = {"device_s": 0.0, "h2d_bytes": 0, "d2h_bytes": 0,
                 "compile_hits": 0, "compile_misses": 0,
                 "shared_hits": 0, "domain": ""}
            self._mvs[mv] = d
        return d

    # -- hot-path hooks -------------------------------------------------
    def observe_cell(self, mv: str, epoch: int, device_s: float,
                     h2d_bytes: int, d2h_bytes: int) -> None:
        """One executor cell's device share at barrier flush (called
        by MonitoredExecutor BEFORE the cell commits to the phase
        ledger — same numbers, split by owner)."""
        if not ENABLED:
            return
        if device_s <= 0 and not h2d_bytes and not d2h_bytes:
            return
        with self._lock:
            acc = self._pending.setdefault(epoch, {}) \
                .setdefault(mv, [0.0, 0, 0])
            acc[0] += device_s
            acc[1] += h2d_bytes
            acc[2] += d2h_bytes
            while len(self._pending) > 256:
                # discarded epochs never seal — drop their cells
                # rather than hold them forever
                self._pending.pop(next(iter(self._pending)))

    def note_compile(self, kind: str, key, hit: bool) -> None:
        mv = _MV.get() or ""
        with self._lock:
            d = self._mv(mv)
            if hit:
                d["compile_hits"] += 1
                owner = self._cache_owner.get((kind, key))
                if owner is not None and owner != mv:
                    d["shared_hits"] += 1
            else:
                d["compile_misses"] += 1
                self._cache_owner.setdefault((kind, key), mv)

    # -- seal-time rollup (phase-ledger _publish) ------------------------
    def history_extra(self, rec) -> Dict[str, float]:
        """Fold the record's epoch's pending cells into the per-MV
        totals, publish the Prometheus families, retain the gate row,
        and return ``mv_device_s.<mv>`` entries for the
        rw_metrics_history row the seal is about to write."""
        if not ENABLED:
            return {}
        with self._lock:
            cells = self._pending.pop(rec.epoch, None) or {}
            extra: Dict[str, float] = {}
            total_dev = 0.0
            for mv, (dev, h2d, d2h) in cells.items():
                d = self._mv(mv)
                d["device_s"] += dev
                d["h2d_bytes"] += h2d
                d["d2h_bytes"] += d2h
                if rec.domain:
                    d["domain"] = rec.domain
                total_dev += dev
                extra[f"mv_device_s.{mv}"] = round(dev, 6)
            if not rec.distributed:
                # distributed epochs merge worker books later — the
                # coordinator's own seal undercounts by design. A
                # cell-less epoch still lands (0.0 attributed): its
                # device time belongs in the coverage denominator
                self._sealed.append(
                    (rec.epoch, rec.domain, total_dev,
                     rec.seconds.get("device_compute", 0.0)))
            if not cells:
                return {}
        from risingwave_tpu.utils.metrics import STREAMING
        for mv, (dev, h2d, d2h) in cells.items():
            STREAMING.mv_device_seconds.inc(dev, mv=mv)
            if h2d:
                STREAMING.mv_transfer_bytes.inc(h2d, mv=mv,
                                                direction="h2d")
            if d2h:
                STREAMING.mv_transfer_bytes.inc(d2h, mv=mv,
                                                direction="d2h")
        return extra

    def publish_state_bytes(self) -> None:
        """Refresh the stream_mv_state_bytes gauge from the topology
        books (checkpoint cadence — state only moves at checkpoints)."""
        if not ENABLED:
            return
        from risingwave_tpu.state.topology import TOPOLOGY
        from risingwave_tpu.utils.metrics import STREAMING
        for mv, nbytes in TOPOLOGY.bytes_by_mv().items():
            if mv:
                STREAMING.mv_state_bytes.set(float(nbytes), mv=mv)

    # -- recovery / rescale charge-back ---------------------------------
    def _chargeback(self) -> Dict[str, List[float]]:
        """mv -> [rescale_s, recovery_s] read from the autoscaler and
        supervisor event logs (not hooked: the logs are already
        per-event, re-derived on read so the books can't drift)."""
        out: Dict[str, List[float]] = {}
        try:
            from risingwave_tpu.meta.autoscaler import autoscaler_rows
            for row in autoscaler_rows():
                mv, dur = str(row[1]), float(row[10] or 0.0)
                out.setdefault(mv, [0.0, 0.0])[0] += dur
        except Exception:               # noqa: BLE001 — log optional
            pass
        try:
            from risingwave_tpu.meta.supervisor import recovery_rows
            rec_total = sum(float(r[5] or 0.0) for r in recovery_rows())
        except Exception:               # noqa: BLE001
            rec_total = 0.0
        if rec_total > 0:
            with self._lock:
                mvs = [m for m in self._mvs if m]
            # recovery replays every registered job: split evenly (a
            # documented approximation — per-job replay time is not
            # individually measured)
            for mv in mvs:
                out.setdefault(mv, [0.0, 0.0])[1] += \
                    rec_total / len(mvs)
        return out

    # -- read side ------------------------------------------------------
    def rows(self) -> List[tuple]:
        """rw_mv_costs payload: (mv, domain, device_seconds,
        h2d_bytes, d2h_bytes, state_bytes, compile_hits,
        compile_misses, shared_compile_hits, rescale_s, recovery_s)."""
        from risingwave_tpu.state.topology import TOPOLOGY
        state = TOPOLOGY.bytes_by_mv()
        charge = self._chargeback()
        with self._lock:
            items = [(mv, dict(d)) for mv, d in self._mvs.items()]
        rows = []
        for mv, d in sorted(items):
            rs, cs = charge.get(mv, (0.0, 0.0))
            rows.append((mv, d["domain"], round(d["device_s"], 6),
                         int(d["h2d_bytes"]), int(d["d2h_bytes"]),
                         int(state.get(mv, 0)),
                         int(d["compile_hits"]),
                         int(d["compile_misses"]),
                         int(d["shared_hits"]),
                         round(rs, 4), round(cs, 4)))
        return rows

    def summary(self) -> Dict[str, dict]:
        """mv -> totals dict (the bench marginal_cost block)."""
        from risingwave_tpu.state.topology import TOPOLOGY
        state = TOPOLOGY.bytes_by_mv()
        with self._lock:
            items = [(mv, dict(d)) for mv, d in self._mvs.items()]
        return {mv: {**d, "state_bytes": int(state.get(mv, 0))}
                for mv, d in items}

    def coverage(self) -> Tuple[float, float]:
        """(attributed_device_s, ledgered_device_s) summed over the
        sealed-epoch window — BOTH sides windowed identically
        (``SEALED_WINDOW`` epochs), so the ratio is the bench's
        attribution-coverage claim. Comparing the cumulative per-MV
        totals against the ledger's bounded record deque instead
        would inflate past 1.0 as records age out."""
        with self._lock:
            att = sum(r[2] for r in self._sealed)
            led = sum(r[3] for r in self._sealed)
        return att, led

    # -- attribution-conservation gate ----------------------------------
    def gate_violations(self) -> List[tuple]:
        """(epoch, domain, sum_mv_device_s, domain_device_s) for every
        sealed epoch where the per-MV split exceeds the domain's
        ledgered device_compute + ε — an owner split can redistribute
        the books but never mint device time."""
        out = []
        with self._lock:
            for epoch, domain, mv_sum, dom_dev in self._sealed:
                eps = 1e-6 + 0.01 * dom_dev
                if mv_sum > dom_dev + eps:
                    out.append((epoch, domain, mv_sum, dom_dev))
        return out

    # -- series lifecycle (DROP MV / failed CREATE) ----------------------
    def unregister_mv(self, mv: str) -> None:
        from risingwave_tpu.utils.metrics import STREAMING
        with self._lock:
            self._mvs.pop(mv, None)
            for epoch in list(self._pending):
                self._pending[epoch].pop(mv, None)
        STREAMING.mv_device_seconds.remove(mv=mv)
        STREAMING.mv_state_bytes.remove(mv=mv)
        for direction in ("h2d", "d2h"):
            STREAMING.mv_transfer_bytes.remove(mv=mv,
                                               direction=direction)

    # -- cross-process merge (cluster `signals` drain) -------------------
    def drain_dict(self) -> dict:
        """Pop this worker's totals and pending cells (a drain:
        deltas ship once; the coordinator owns the merged books)."""
        with self._lock:
            mvs = {mv: dict(d) for mv, d in self._mvs.items()}
            pending = {e: {mv: list(acc) for mv, acc in cells.items()}
                       for e, cells in self._pending.items()}
            self._mvs.clear()
            self._pending.clear()
        return {"mvs": mvs, "pending": pending}

    def ingest(self, parts: dict, worker: str = "") -> int:
        """Fold one worker's drained books into this process's totals
        (pending worker cells fold directly — their epochs sealed on
        the coordinator already, under the distributed exemption)."""
        if not parts:
            return 0
        n = 0
        from risingwave_tpu.utils.metrics import STREAMING
        deltas: Dict[str, List[float]] = {}
        with self._lock:
            for mv, d in (parts.get("mvs") or {}).items():
                t = self._mv(mv)
                for k in ("device_s", "h2d_bytes", "d2h_bytes",
                          "compile_hits", "compile_misses",
                          "shared_hits"):
                    t[k] += d.get(k, 0)
                if d.get("domain"):
                    t["domain"] = d["domain"]
                acc = deltas.setdefault(mv, [0.0, 0, 0])
                acc[0] += d.get("device_s", 0.0)
                acc[1] += d.get("h2d_bytes", 0)
                acc[2] += d.get("d2h_bytes", 0)
                n += 1
            for _e, cells in (parts.get("pending") or {}).items():
                for mv, (dev, h2d, d2h) in cells.items():
                    t = self._mv(mv)
                    t["device_s"] += dev
                    t["h2d_bytes"] += h2d
                    t["d2h_bytes"] += d2h
                    acc = deltas.setdefault(mv, [0.0, 0, 0])
                    acc[0] += dev
                    acc[1] += h2d
                    acc[2] += d2h
                    n += 1
        for mv, (dev, h2d, d2h) in deltas.items():
            if dev:
                STREAMING.mv_device_seconds.inc(dev, mv=mv)
            if h2d:
                STREAMING.mv_transfer_bytes.inc(h2d, mv=mv,
                                                direction="h2d")
            if d2h:
                STREAMING.mv_transfer_bytes.inc(d2h, mv=mv,
                                                direction="d2h")
        return n

    def clear(self) -> None:
        with self._lock:
            self._mvs.clear()
            self._pending.clear()
            self._cache_owner.clear()
            self._sealed.clear()


COSTS = MVCosts()


def purge_mv_series(mv: str) -> None:
    """Central series-lifecycle teardown for one MV: DROP MATERIALIZED
    VIEW and failed CREATE both route here so no `{mv=...}` labeled
    series — freshness, costs, hot keys, topology — outlives the job
    in the exposition."""
    from risingwave_tpu.state.topology import TOPOLOGY
    from risingwave_tpu.stream.freshness import FRESHNESS
    from risingwave_tpu.stream.hotkeys import HOTKEYS
    FRESHNESS.unregister_mv(mv)
    COSTS.unregister_mv(mv)
    HOTKEYS.unregister_fragment(mv)
    TOPOLOGY.unbind_mv(mv)
