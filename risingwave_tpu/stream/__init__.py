"""Streaming engine: executors, actors, exchange, barriers.

The TPU-native analog of the reference's src/stream/ crate (SURVEY §2.6):
pull-based async executors over columnar device chunks, permit-based
exchange channels, Chandy-Lamport aligned barriers, actors as asyncio
tasks. Stateful operators (ops/) flush device state through StateTable at
every barrier.
"""

from risingwave_tpu.stream.message import (
    AddMutation, Barrier, BarrierKind, Message, Mutation, PauseMutation,
    ResumeMutation, SourceChangeSplitMutation, StopMutation, UpdateMutation,
    Watermark, is_barrier, is_chunk, is_watermark,
)
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.exchange import (
    ChannelClosed, Receiver, Sender, channel, channel_for_test,
)
from risingwave_tpu.stream.coalesce import (
    ChunkCoalescer, CoalesceExecutor, compact, merge_chunks,
)
from risingwave_tpu.stream.merge import MergeExecutor, barrier_align_2
from risingwave_tpu.stream.dispatch import (
    BroadcastDispatcher, DispatchExecutor, Dispatcher, HashDispatcher,
    Output, RoundRobinDispatcher, SimpleDispatcher,
)
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager

__all__ = [
    "AddMutation", "Barrier", "BarrierKind", "Message", "Mutation",
    "PauseMutation", "ResumeMutation", "SourceChangeSplitMutation",
    "StopMutation", "UpdateMutation", "Watermark",
    "is_barrier", "is_chunk", "is_watermark",
    "Executor", "ExecutorInfo",
    "ChannelClosed", "Receiver", "Sender", "channel", "channel_for_test",
    "ChunkCoalescer", "CoalesceExecutor", "compact", "merge_chunks",
    "MergeExecutor", "barrier_align_2",
    "BroadcastDispatcher", "DispatchExecutor", "Dispatcher",
    "HashDispatcher", "Output", "RoundRobinDispatcher", "SimpleDispatcher",
    "Actor", "LocalBarrierManager",
]
