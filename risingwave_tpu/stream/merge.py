"""Fan-in: MergeExecutor with N-way barrier alignment.

Reference parity: src/stream/src/executor/merge.rs:36,112 (select-all over
upstream inputs; an input that reaches a barrier is blocked until every
input reaches the same barrier, then one aligned barrier is emitted) and
src/stream/src/executor/barrier_align.rs:34,43 (the 2-way variant joins use).
Watermarks follow the reference's BufferedWatermarks: emit the min across
inputs, monotonically.

This alignment is the Chandy-Lamport cut: everything before the barrier on
every input is in epoch N, everything after in N+1.

Both merge variants optionally COALESCE the merged data stream
(`coalesce_rows`): a parallel upstream fan-in delivers N compacted
slivers per upstream chunk, and merging them back into dense
target-sized batches here is what keeps the downstream keyed
executor's device dispatch count independent of upstream parallelism.
Barriers/watermarks flush the buffer first — the coalescer never
delays a control message (stream/coalesce.py contract).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Dict, List, Optional

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.stream.coalesce import (
    DEFAULT_MAX_CHUNKS, ChunkCoalescer,
)
from risingwave_tpu.stream.exchange import ChannelClosed, Receiver
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Barrier, Message, Watermark, is_barrier,
)


class _WatermarkAligner:
    """Per-column min-watermark across N inputs (monotonic output)."""

    def __init__(self, n_inputs: int):
        self.n = n_inputs
        self.per_col: Dict[int, Dict[int, object]] = {}
        self.emitted: Dict[int, object] = {}

    def update(self, input_idx: int, wm: Watermark) -> Optional[Watermark]:
        seen = self.per_col.setdefault(wm.col_idx, {})
        seen[input_idx] = wm.value
        if len(seen) < self.n:
            return None
        lo = min(seen.values())
        if wm.col_idx in self.emitted and lo <= self.emitted[wm.col_idx]:
            return None
        self.emitted[wm.col_idx] = lo
        return Watermark(wm.col_idx, wm.data_type, lo)

    def remove_input(self, input_idx: int) -> None:
        for seen in self.per_col.values():
            seen.pop(input_idx, None)


class MergeExecutor(Executor):
    """Merge N upstream channels into one aligned stream.

    ``coalesce_rows`` (None = off) merges consecutive small data
    chunks up to that cardinality before yielding; any barrier or
    watermark flushes first."""

    def __init__(self, info: ExecutorInfo, inputs: List[Receiver],
                 actor_id: int = 0,
                 coalesce_rows: Optional[int] = None,
                 coalesce_chunks: int = DEFAULT_MAX_CHUNKS):
        super().__init__(info)
        self.inputs = list(inputs)
        self.actor_id = actor_id
        self.coalesce_rows = coalesce_rows
        self.coalesce_chunks = coalesce_chunks

    def _coalescer(self) -> Optional[ChunkCoalescer]:
        if not self.coalesce_rows or self.coalesce_rows <= 0:
            return None
        return ChunkCoalescer(self.coalesce_rows, self.coalesce_chunks)

    async def execute(self) -> AsyncIterator[Message]:
        n = len(self.inputs)
        assert n > 0, "MergeExecutor needs at least one input"
        wm_align = _WatermarkAligner(n)
        co = self._coalescer()
        out: asyncio.Queue = asyncio.Queue(maxsize=16)
        # per-input gate: the pump may proceed past a barrier only when the
        # aligner releases it for the next epoch
        gates = [asyncio.Event() for _ in range(n)]
        barrier_box: List[Optional[Barrier]] = [None] * n
        arrived = asyncio.Queue()  # input indices that hit a barrier

        async def pump(i: int, rx: Receiver):
            try:
                while True:
                    msg = await rx.recv()
                    if is_barrier(msg):
                        barrier_box[i] = msg
                        gates[i].clear()
                        arrived.put_nowait(i)
                        await gates[i].wait()  # blocked until all aligned
                        if barrier_box[i] is StopIteration:  # closed
                            return
                    else:
                        await out.put((i, msg))
            except ChannelClosed:
                arrived.put_nowait((i, "closed"))

        def handle(i: int, msg) -> List[Message]:
            """Route one data/watermark message through the aligner
            and (optionally) the coalescer; returns what to yield."""
            if isinstance(msg, Watermark):
                w = wm_align.update(i, msg)
                if w is None:
                    return []
                if co is None:
                    return [w]
                # re-sequence to the next flush — watermark-per-chunk
                # upstreams must not force per-sliver batches
                # (coalesce.py contract)
                return co.push_watermark(w)
            if co is None:
                return [msg]
            outs: List[Message] = co.push(msg)
            if outs:
                outs += co.drain_watermarks()
            return outs

        pumps = [asyncio.ensure_future(pump(i, rx))
                 for i, rx in enumerate(self.inputs)]
        live = set(range(n))
        try:
            while live:
                pending_barrier: Dict[int, Barrier] = {}
                closed: set = set()
                # drain data until every live input parks at a barrier
                while len(pending_barrier) + len(closed) < len(live):
                    getter = asyncio.ensure_future(out.get())
                    arr = asyncio.ensure_future(arrived.get())
                    done, _ = await asyncio.wait(
                        {getter, arr}, return_when=asyncio.FIRST_COMPLETED)
                    if getter in done:
                        i, msg = getter.result()
                        for m in handle(i, msg):
                            yield m
                    else:
                        getter.cancel()
                    if arr in done:
                        ev = arr.result()
                        if isinstance(ev, tuple):  # (i, "closed")
                            closed.add(ev[0])
                        else:
                            pending_barrier[ev] = barrier_box[ev]
                    else:
                        arr.cancel()
                # every live input is parked at its gate (or closed),
                # so no pump can enqueue concurrently — drain whatever
                # the alignment race left in the queue: those messages
                # PRECEDE the barriers (pumps are sequential) and must
                # never slip into the next epoch
                while True:
                    try:
                        i, msg = out.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    for m in handle(i, msg):
                        yield m
                # all inputs aligned (or closed): emit one barrier
                for i in closed:
                    live.discard(i)
                    wm_align.remove_input(i)
                    wm_align.n = max(1, len(live))
                if co is not None:
                    # flush-on-barrier (and on close): the aligned
                    # barrier below must never trail a lingering batch
                    # or a held watermark
                    f = co.flush()
                    if f is not None:
                        yield f
                    for wm in co.drain_watermarks():
                        yield wm
                if not pending_barrier:
                    return  # every upstream closed without a barrier
                barriers = list(pending_barrier.values())
                epochs = {b.epoch.curr.value for b in barriers}
                assert len(epochs) == 1, \
                    f"misaligned barriers across inputs: {barriers}"
                yield barriers[0].with_passed(self.actor_id)
                stop = barriers[0].is_stop(self.actor_id)
                for i in pending_barrier:
                    if stop:
                        barrier_box[i] = StopIteration
                    gates[i].set()
                if stop:
                    return
        finally:
            for p in pumps:
                p.cancel()
            for rx in self.inputs:
                rx.close()


class MergeExecutors(Executor):
    """Merge N upstream EXECUTORS (typically RemoteInputs pulling one
    exchange edge each) into one barrier-aligned stream.

    Reference parity: merge.rs:36 built over exchange/input.rs inputs —
    the fan-in side of a cross-worker hash exchange. The channel-based
    MergeExecutor above serves in-process wiring; this variant drives
    executor streams directly so a shipped plan-IR fragment can merge
    its remote_input nodes without an adapter task per input.
    """

    def __init__(self, info: ExecutorInfo, inputs: List[Executor],
                 actor_id: int = 0,
                 coalesce_rows: Optional[int] = None,
                 coalesce_chunks: int = DEFAULT_MAX_CHUNKS):
        super().__init__(info)
        self.inputs = list(inputs)
        self.actor_id = actor_id
        self.coalesce_rows = coalesce_rows
        self.coalesce_chunks = coalesce_chunks

    async def execute(self) -> AsyncIterator[Message]:
        assert self.inputs, "MergeExecutors needs at least one input"
        wm_align = _WatermarkAligner(len(self.inputs))
        co = None
        if self.coalesce_rows and self.coalesce_rows > 0:
            co = ChunkCoalescer(self.coalesce_rows,
                                self.coalesce_chunks)
        async for tag, msg in barrier_align_n(
                [i.execute() for i in self.inputs]):
            if tag == "barrier":
                if co is not None:
                    f = co.flush()    # a barrier never waits on lingering rows
                    if f is not None:
                        yield f
                    for wm in co.drain_watermarks():
                        yield wm
                yield msg.with_passed(self.actor_id)
                if msg.is_stop(self.actor_id):
                    return
            elif isinstance(msg, Watermark):
                w = wm_align.update(tag, msg)
                if w is not None:
                    if co is None:
                        yield w
                    else:
                        # re-sequence to the next flush point (see
                        # coalesce.py: monotone bound stays valid)
                        for m in co.push_watermark(w):
                            yield m
            elif co is not None:
                outs = co.push(msg)
                for merged in outs:
                    yield merged
                if outs:
                    for wm in co.drain_watermarks():
                        yield wm
            else:
                yield msg


async def barrier_align_n(inputs: List[AsyncIterator[Message]]
                          ) -> AsyncIterator[tuple]:
    """N-way alignment over executor streams (barrier_align.rs:34 analog).

    Yields (input_idx, msg) for data and ("barrier", Barrier) once per
    aligned set. An input that reaches a barrier is not pulled again
    until every input reaches the same barrier. Ends when any input ends.
    """
    async def nxt(it):
        try:
            return await it.__anext__()
        except StopAsyncIteration:
            return None

    n = len(inputs)
    futs = [asyncio.ensure_future(nxt(it)) for it in inputs]
    parked: List[Optional[Barrier]] = [None] * n
    try:
        while True:
            if all(b is not None for b in parked):
                epochs = {b.epoch.curr.value for b in parked}
                assert len(epochs) == 1, \
                    f"misaligned barriers across inputs: {parked}"
                yield ("barrier", parked[0])
                parked = [None] * n
                futs = [asyncio.ensure_future(nxt(it)) for it in inputs]
                continue
            waits = {futs[i] for i in range(n) if parked[i] is None}
            done, _ = await asyncio.wait(
                waits, return_when=asyncio.FIRST_COMPLETED)
            for i in range(n):
                if parked[i] is not None or futs[i] not in done:
                    continue
                msg = futs[i].result()
                if msg is None:
                    return
                if is_barrier(msg):
                    parked[i] = msg
                else:
                    yield (i, msg)
                    futs[i] = asyncio.ensure_future(nxt(inputs[i]))
    finally:
        for f in futs:
            f.cancel()


async def barrier_align_2(left: AsyncIterator[Message],
                          right: AsyncIterator[Message]
                          ) -> AsyncIterator[tuple]:
    """2-way alignment for binary operators: ("left"|"right"|"barrier",
    msg) — thin wrapper over barrier_align_n."""
    tags = {0: "left", 1: "right"}
    async for tag, msg in barrier_align_n([left, right]):
        yield (tags.get(tag, tag), msg)
