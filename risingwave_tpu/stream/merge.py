"""Fan-in: MergeExecutor with N-way barrier alignment.

Reference parity: src/stream/src/executor/merge.rs:36,112 (select-all over
upstream inputs; an input that reaches a barrier is blocked until every
input reaches the same barrier, then one aligned barrier is emitted) and
src/stream/src/executor/barrier_align.rs:34,43 (the 2-way variant joins use).
Watermarks follow the reference's BufferedWatermarks: emit the min across
inputs, monotonically.

This alignment is the Chandy-Lamport cut: everything before the barrier on
every input is in epoch N, everything after in N+1.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Dict, List, Optional

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.stream.exchange import ChannelClosed, Receiver
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import (
    Barrier, Message, Watermark, is_barrier,
)


class _WatermarkAligner:
    """Per-column min-watermark across N inputs (monotonic output)."""

    def __init__(self, n_inputs: int):
        self.n = n_inputs
        self.per_col: Dict[int, Dict[int, object]] = {}
        self.emitted: Dict[int, object] = {}

    def update(self, input_idx: int, wm: Watermark) -> Optional[Watermark]:
        seen = self.per_col.setdefault(wm.col_idx, {})
        seen[input_idx] = wm.value
        if len(seen) < self.n:
            return None
        lo = min(seen.values())
        if wm.col_idx in self.emitted and lo <= self.emitted[wm.col_idx]:
            return None
        self.emitted[wm.col_idx] = lo
        return Watermark(wm.col_idx, wm.data_type, lo)

    def remove_input(self, input_idx: int) -> None:
        for seen in self.per_col.values():
            seen.pop(input_idx, None)


class MergeExecutor(Executor):
    """Merge N upstream channels into one aligned stream."""

    def __init__(self, info: ExecutorInfo, inputs: List[Receiver],
                 actor_id: int = 0):
        super().__init__(info)
        self.inputs = list(inputs)
        self.actor_id = actor_id

    async def execute(self) -> AsyncIterator[Message]:
        n = len(self.inputs)
        assert n > 0, "MergeExecutor needs at least one input"
        wm_align = _WatermarkAligner(n)
        out: asyncio.Queue = asyncio.Queue(maxsize=16)
        # per-input gate: the pump may proceed past a barrier only when the
        # aligner releases it for the next epoch
        gates = [asyncio.Event() for _ in range(n)]
        barrier_box: List[Optional[Barrier]] = [None] * n
        arrived = asyncio.Queue()  # input indices that hit a barrier

        async def pump(i: int, rx: Receiver):
            try:
                while True:
                    msg = await rx.recv()
                    if is_barrier(msg):
                        barrier_box[i] = msg
                        gates[i].clear()
                        arrived.put_nowait(i)
                        await gates[i].wait()  # blocked until all aligned
                        if barrier_box[i] is StopIteration:  # closed
                            return
                    else:
                        await out.put((i, msg))
            except ChannelClosed:
                arrived.put_nowait((i, "closed"))

        pumps = [asyncio.ensure_future(pump(i, rx))
                 for i, rx in enumerate(self.inputs)]
        live = set(range(n))
        try:
            while live:
                pending_barrier: Dict[int, Barrier] = {}
                closed: set = set()
                # drain data until every live input parks at a barrier
                while len(pending_barrier) + len(closed) < len(live):
                    getter = asyncio.ensure_future(out.get())
                    arr = asyncio.ensure_future(arrived.get())
                    done, _ = await asyncio.wait(
                        {getter, arr}, return_when=asyncio.FIRST_COMPLETED)
                    if getter in done:
                        i, msg = getter.result()
                        if isinstance(msg, Watermark):
                            w = wm_align.update(i, msg)
                            if w is not None:
                                yield w
                        else:
                            yield msg
                    else:
                        getter.cancel()
                    if arr in done:
                        ev = arr.result()
                        if isinstance(ev, tuple):  # (i, "closed")
                            closed.add(ev[0])
                        else:
                            pending_barrier[ev] = barrier_box[ev]
                    else:
                        arr.cancel()
                # all inputs aligned (or closed): emit one barrier
                for i in closed:
                    live.discard(i)
                    wm_align.remove_input(i)
                    wm_align.n = max(1, len(live))
                if not pending_barrier:
                    return  # every upstream closed without a barrier
                barriers = list(pending_barrier.values())
                epochs = {b.epoch.curr.value for b in barriers}
                assert len(epochs) == 1, \
                    f"misaligned barriers across inputs: {barriers}"
                yield barriers[0].with_passed(self.actor_id)
                stop = barriers[0].is_stop(self.actor_id)
                for i in pending_barrier:
                    if stop:
                        barrier_box[i] = StopIteration
                    gates[i].set()
                if stop:
                    return
        finally:
            for p in pumps:
                p.cancel()
            for rx in self.inputs:
                rx.close()


async def barrier_align_2(left: AsyncIterator[Message],
                          right: AsyncIterator[Message]
                          ) -> AsyncIterator[tuple]:
    """2-way alignment for binary operators (barrier_align.rs:34 analog).

    Yields ("left"|"right", msg) for data and ("barrier", Barrier) once per
    aligned pair. Ends when either side ends.
    """
    async def nxt(it):
        try:
            return await it.__anext__()
        except StopAsyncIteration:
            return None

    lt = asyncio.ensure_future(nxt(left))
    rt = asyncio.ensure_future(nxt(right))
    l_barrier: Optional[Barrier] = None
    r_barrier: Optional[Barrier] = None
    try:
        while True:
            if l_barrier is not None and r_barrier is not None:
                assert l_barrier.epoch == r_barrier.epoch, \
                    (l_barrier, r_barrier)
                yield ("barrier", l_barrier)
                l_barrier = r_barrier = None
                lt = asyncio.ensure_future(nxt(left))
                rt = asyncio.ensure_future(nxt(right))
                continue
            waits = set()
            if l_barrier is None:
                waits.add(lt)
            if r_barrier is None:
                waits.add(rt)
            done, _ = await asyncio.wait(
                waits, return_when=asyncio.FIRST_COMPLETED)
            if lt in done and l_barrier is None:
                msg = lt.result()
                if msg is None:
                    return
                if is_barrier(msg):
                    l_barrier = msg
                else:
                    yield ("left", msg)
                    lt = asyncio.ensure_future(nxt(left))
            if rt in done and r_barrier is None:
                msg = rt.result()
                if msg is None:
                    return
                if is_barrier(msg):
                    r_barrier = msg
                else:
                    yield ("right", msg)
                    rt = asyncio.ensure_future(nxt(right))
    finally:
        lt.cancel()
        rt.cancel()
