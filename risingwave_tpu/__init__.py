"""risingwave_tpu: a TPU-native distributed SQL streaming framework.

A from-scratch re-design of the capabilities of RisingWave (reference:
/root/reference, racevedoo/risingwave) for TPU hardware:

- columnar ``DataChunk``/``StreamChunk`` batches living as JAX device arrays
- stateful stream operators (hash join, hash agg) as jit/XLA/Pallas kernels
  over device-resident hash tables
- consistent-hash (256-vnode) data parallelism mapped onto a
  ``jax.sharding.Mesh``; hash dispatch rides ICI collectives
- Chandy-Lamport aligned-barrier checkpoints; an LSM state store
  ("hummock-lite") over object storage
- a PostgreSQL-flavoured SQL frontend compiling CREATE MATERIALIZED VIEW
  into actor dataflow graphs

Layering (mirrors SURVEY.md section 1):

    common/      foundation: types, arrays, chunks, hashing, epochs, config
    ops/         jit device kernels (hash tables, grouped agg, join match)
    state/       state-store interface + relational StateTable (epoch MVCC)
    stream/      executors, actors, barriers, local + remote exchange
    parallel/    device-mesh SPMD: all_to_all dispatch, sharded agg/join,
                 elastic resharding
    storage/     hummock-lite LSM over object storage (SSTs, compaction)
    batch/       snapshot scans + batch executor tree (SELECT serving)
    frontend/    SQL parser -> binder -> planner; session; pgwire server
    meta/        barrier/checkpoint loop (epoch issue, collect, commit)
    connectors/  sources: nexmark, datagen (replayable, vectorized)
    models/      pre-built flagship pipelines (nexmark q1/q7/q8)
    native/      C++ runtime kernels (SST block codec, bloom) + loader
    utils/       metrics, tracing, JAX runtime knobs
"""

import jax

# A streaming SQL engine needs real 64-bit ints (timestamps in ms, row ids).
# JAX defaults to 32-bit; opt into x64 before any array is created. Hot-path
# kernels still request bf16/f32/int32 explicitly where it matters for MXU/VPU.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
