"""risingwave_tpu: a TPU-native distributed SQL streaming framework.

A from-scratch re-design of the capabilities of RisingWave (reference:
/root/reference, racevedoo/risingwave) for TPU hardware:

- columnar ``DataChunk``/``StreamChunk`` batches living as JAX device arrays
- stateful stream operators (hash join, hash agg) as jit/XLA/Pallas kernels
  over device-resident hash tables
- consistent-hash (256-vnode) data parallelism mapped onto a
  ``jax.sharding.Mesh``; hash dispatch rides ICI collectives
- Chandy-Lamport aligned-barrier checkpoints; an LSM state store
  ("hummock-lite") over object storage
- a PostgreSQL-flavoured SQL frontend compiling CREATE MATERIALIZED VIEW
  into actor dataflow graphs

Layering (mirrors SURVEY.md section 1):

    common/      foundation: types, arrays, chunks, hashing, epochs, config
    ops/         jit + pallas device kernels (vnode hash, hash tables, aggs)
    state/       state store + relational StateTable (epoch MVCC)
    stream/      executors, actors, barrier manager, exchange
    parallel/    device mesh, shardings, collective dispatch
    storage/     hummock-lite LSM over object store
    frontend/    SQL parser -> binder -> planner -> fragmenter
    meta/        catalog, DDL, global barrier manager, recovery, scaling
    connectors/  sources (nexmark, datagen, kafka-shaped) and sinks
    models/      pre-built flagship pipelines (nexmark q1/q7/q8, tpch)
    utils/       logging, metrics, misc
"""

import jax

# A streaming SQL engine needs real 64-bit ints (timestamps in ms, row ids).
# JAX defaults to 32-bit; opt into x64 before any array is created. Hot-path
# kernels still request bf16/f32/int32 explicitly where it matters for MXU/VPU.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
