"""Foundation layer: types, columnar chunks, hashing, epochs, config.

Reference parity: src/common/ (types/mod.rs, array/, hash/, util/epoch.rs,
config.rs) — re-designed for JAX device arrays rather than ported.
"""

from risingwave_tpu.common.types import (
    DataType, Field, Interval, Schema, DECIMAL_SCALE, decimal_to_scaled,
    scaled_to_decimal,
)
from risingwave_tpu.common.chunk import Column, DataChunk, StreamChunk, Op
from risingwave_tpu.common.epoch import Epoch, EpochPair, set_clock
from risingwave_tpu.common.hash import (
    VNODE_COUNT, VNODE_BITS, VnodeMapping, hash_columns, hash_strings_host,
    vnodes_of,
)
from risingwave_tpu.common.config import RwConfig, StreamingConfig, StorageConfig

__all__ = [
    "DataType",
    "Field",
    "Interval",
    "Schema",
    "DECIMAL_SCALE",
    "decimal_to_scaled",
    "scaled_to_decimal",
    "Column",
    "DataChunk",
    "StreamChunk",
    "Op",
    "Epoch",
    "EpochPair",
    "set_clock",
    "VNODE_COUNT",
    "VNODE_BITS",
    "VnodeMapping",
    "hash_columns",
    "hash_strings_host",
    "vnodes_of",
    "RwConfig",
    "StreamingConfig",
    "StorageConfig",
]
