"""Foundation layer: types, columnar chunks, hashing, epochs, config.

Reference parity: src/common/ (types/mod.rs, array/, hash/, util/epoch.rs,
config.rs) — re-designed for JAX device arrays rather than ported.
"""

from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.common.chunk import DataChunk, StreamChunk, Op
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.hash import VNODE_COUNT, VNODE_BITS, hash_columns, vnodes_of
from risingwave_tpu.common.config import RwConfig, StreamingConfig, StorageConfig

__all__ = [
    "DataType",
    "Field",
    "Schema",
    "DataChunk",
    "StreamChunk",
    "Op",
    "Epoch",
    "EpochPair",
    "VNODE_COUNT",
    "VNODE_BITS",
    "hash_columns",
    "vnodes_of",
    "RwConfig",
    "StreamingConfig",
    "StorageConfig",
]
