"""Consistent hashing: 256 virtual nodes, jittable hash kernels.

Reference parity: src/common/src/hash/consistent_hash/vnode.rs:54-57
(VirtualNode::BITS=8, COUNT=256, Crc32 of distribution keys) and
src/common/src/hash/key.rs (HashKey). TPU-first re-design: instead of Crc32
over row-serialized keys (a per-row scalar loop), we use a vectorized
integer mix (murmur3 finalizer) over the key columns — the whole chunk is
hashed in one VPU pass. The exact hash need not match the reference; only
the *consistency* property matters (same key → same vnode everywhere).

``vnodes_of`` is the routing primitive used by both the hash dispatcher
(dispatch.rs:645 analog) and state-table key partitioning.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

VNODE_BITS = 8
VNODE_COUNT = 1 << VNODE_BITS  # 256, matches reference vnode.rs:56


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    """Murmur3 fmix32 — good avalanche, 5 VPU ops, uint32 in/out."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _to_u32_lanes(col: jnp.ndarray) -> List[jnp.ndarray]:
    """Decompose a column into one or two uint32 lanes for hashing."""
    dt = col.dtype
    if dt == jnp.bool_:
        return [col.astype(jnp.uint32)]
    if jnp.issubdtype(dt, jnp.floating):
        # Hash the bit pattern; normalize -0.0 to 0.0 first.
        col = jnp.where(col == 0, jnp.zeros_like(col), col)
        # Hash the f32 bit pattern even for f64 keys: the TPU x64-rewrite
        # pass has no f64<->u64 bitcast, and a hash only needs consistency —
        # nearby-double collisions are resolved by full-key equality checks.
        bits = jax.lax.bitcast_convert_type(col.astype(jnp.float32), jnp.uint32)
        return [bits]
    if dt.itemsize <= 4:
        return [col.astype(jnp.uint32)]
    u = col.astype(jnp.uint64)
    return [(u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32),
            (u >> jnp.uint64(32)).astype(jnp.uint32)]


def hash_columns(cols: Sequence[jnp.ndarray],
                 seed: int = 0x9E3779B9) -> jnp.ndarray:
    """Vectorized row hash over key columns → uint32 [n].

    Combine rule is boost-style hash_combine folded through fmix32, applied
    lane-wise; all columns must share the leading dimension.
    """
    assert len(cols) > 0, "hash_columns needs at least one key column"
    n = cols[0].shape[0]
    h = jnp.full((n,), jnp.uint32(seed))
    for col in cols:
        for lane in _to_u32_lanes(col):
            h = _mix32(h ^ (lane + jnp.uint32(0x9E3779B9) +
                            (h << 6) + (h >> 2)))
    return h


def vnodes_of(cols: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Row → vnode in [0, 256) (VirtualNode::compute_chunk analog)."""
    return (hash_columns(cols) & jnp.uint32(VNODE_COUNT - 1)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Host (numpy) twins — bit-identical to the device kernels so that host-side
# state partitioning (StateTable) always agrees with device-side dispatch.
# test_hash_host_device_consistency locks this in.


def _mix32_np(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint32(16))
        x = x * np.uint32(0x85EBCA6B)
        x = x ^ (x >> np.uint32(13))
        x = x * np.uint32(0xC2B2AE35)
        x = x ^ (x >> np.uint32(16))
    return x


def _to_u32_lanes_np(col: np.ndarray) -> List[np.ndarray]:
    dt = col.dtype
    if dt == np.bool_:
        return [col.astype(np.uint32)]
    if np.issubdtype(dt, np.floating):
        col = np.where(col == 0, np.zeros_like(col), col)
        return [col.astype(np.float32).view(np.uint32)]
    if dt.itemsize <= 4:
        return [col.astype(np.uint32)]
    u = col.astype(np.uint64)
    return [(u & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            (u >> np.uint64(32)).astype(np.uint32)]


def hash_columns_host(cols: Sequence[np.ndarray],
                      seed: int = 0x9E3779B9) -> np.ndarray:
    """Numpy mirror of ``hash_columns`` — same bits, host arrays."""
    assert len(cols) > 0
    n = cols[0].shape[0]
    h = np.full((n,), np.uint32(seed))
    with np.errstate(over="ignore"):
        for col in cols:
            for lane in _to_u32_lanes_np(np.asarray(col)):
                h = _mix32_np(h ^ (lane + np.uint32(0x9E3779B9) +
                                   (h << np.uint32(6)) + (h >> np.uint32(2))))
    return h


def vnodes_of_host(cols: Sequence[np.ndarray]) -> np.ndarray:
    return (hash_columns_host(cols) &
            np.uint32(VNODE_COUNT - 1)).astype(np.int32)


_STR_HASH_WIDTH = 16  # codepoints of prefix hashed (+ length); longer strings
#                       sharing prefix AND length collide — skew-only concern,
#                       correctness restored by full-key equality checks.


def hash_strings_host(values: np.ndarray, n: int) -> np.ndarray:
    """Host-side stable hash for varchar key columns → uint32 [n].

    Strings never ship to device; when a distribution key includes a varchar
    column we hash it on host and feed the lane into `hash_columns` as a
    uint32 column. Vectorized: fixed-width codepoint matrix + Horner fold —
    no per-row Python. Hashes the first 16 codepoints plus the exact length.
    """
    if n == 0:
        return np.zeros(len(values), dtype=np.uint32)
    vals = np.asarray(values[:n], dtype=object)
    null_mask = vals == None  # noqa: E711
    if null_mask.any():
        vals = vals.copy()
        vals[null_mask] = ""
    u = vals.astype(str)                       # UCS4 unicode matrix
    lengths = np.char.str_len(u).astype(np.uint32)
    w = _STR_HASH_WIDTH
    uw = np.ascontiguousarray(u.astype(f"U{w}"))   # truncate/pad to w chars
    mat = uw.view(np.uint32).reshape(n, w)         # codepoints, 0-padded
    h = lengths.copy()
    with np.errstate(over="ignore"):
        for j in range(w):  # w whole-column numpy ops, not per-row python
            h = h * np.uint32(31) + mat[:, j]
    h[null_mask] = 0
    out = np.zeros(len(values), dtype=np.uint32)
    out[:n] = h
    return out


class VnodeMapping:
    """vnode → owner (actor or worker) mapping with rebalance support.

    Reference parity: src/common/src/hash/consistent_hash/mapping.rs
    (ActorMapping / WorkerMapping) and the bitmap math in
    src/meta/src/stream/scale.rs:174. Stored dense: int32[256].
    """

    def __init__(self, owners: np.ndarray):
        owners = np.asarray(owners, dtype=np.int32)
        assert owners.shape == (VNODE_COUNT,)
        self.owners = owners

    @staticmethod
    def new_uniform(num_owners: int) -> "VnodeMapping":
        """Contiguous even split of 256 vnodes over `num_owners`."""
        assert num_owners >= 1
        base = VNODE_COUNT // num_owners
        rem = VNODE_COUNT % num_owners
        owners = np.repeat(np.arange(num_owners, dtype=np.int32),
                           np.asarray([base + (i < rem)
                                       for i in range(num_owners)]))
        return VnodeMapping(owners)

    def owner_of(self, vnode: int) -> int:
        return int(self.owners[vnode])

    def bitmap_of(self, owner: int) -> np.ndarray:
        """bool[256] ownership bitmap for one owner (state-table vnodes)."""
        return self.owners == owner

    def num_owners(self) -> int:
        return int(self.owners.max()) + 1 if len(self.owners) else 0

    def rebalance(self, new_num_owners: int) -> "VnodeMapping":
        """Minimal-movement rebalance to a new owner count.

        Mirrors rebalance_actor_vnode (scale.rs:174): move just enough
        vnodes from over-loaded owners to under-loaded ones.
        """
        target = [VNODE_COUNT // new_num_owners +
                  (i < VNODE_COUNT % new_num_owners)
                  for i in range(new_num_owners)]
        owners = self.owners.copy()
        # Clamp removed owners to -1 (to be redistributed).
        owners[owners >= new_num_owners] = -1
        counts = [int((owners == i).sum()) for i in range(new_num_owners)]
        surplus: List[int] = []  # vnode indices to reassign
        for i in range(new_num_owners):
            if counts[i] > target[i]:
                idxs = np.flatnonzero(owners == i)[: counts[i] - target[i]]
                surplus.extend(idxs.tolist())
        surplus.extend(np.flatnonzero(owners == -1).tolist())
        k = 0
        for i in range(new_num_owners):
            while counts[i] < target[i]:
                owners[surplus[k]] = i
                counts[i] += 1
                k += 1
        return VnodeMapping(owners)

    def to_device(self) -> jnp.ndarray:
        return jnp.asarray(self.owners)
