"""Epochs: the global logical clock driven by barriers.

Reference parity: src/common/src/util/epoch.rs — a 64-bit epoch is
``physical_time_ms << 16``; the low 16 bits are a sequence number so multiple
barriers within one millisecond stay ordered. ``EpochPair`` carries
{curr, prev} across a barrier.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, ClassVar

EPOCH_PHYSICAL_SHIFT = 16

# Keep our own epoch-zero so numbers stay small and readable in tests.
UNIX_RISINGWAVE_DATE_EPOCH_MS = 1_617_235_200_000  # 2021-04-01, like reference

# Injectable time source (seconds, like time.time) so the deterministic
# simulation harness (SURVEY.md §4 madsim analog) can drive virtual time.
_clock: Callable[[], float] = time.time


def set_clock(clock: Callable[[], float]) -> Callable[[], float]:
    """Swap the global time source; returns the previous one."""
    global _clock
    prev = _clock
    _clock = clock
    return prev


def physical_now_ms() -> int:
    return int(_clock() * 1000) - UNIX_RISINGWAVE_DATE_EPOCH_MS


@dataclass(frozen=True, order=True)
class Epoch:
    value: int

    INVALID: ClassVar["Epoch"]  # patched below

    @staticmethod
    def from_physical(ms: int, seq: int = 0) -> "Epoch":
        return Epoch((ms << EPOCH_PHYSICAL_SHIFT) | seq)

    @staticmethod
    def now() -> "Epoch":
        return Epoch.from_physical(physical_now_ms())

    @property
    def physical_ms(self) -> int:
        return self.value >> EPOCH_PHYSICAL_SHIFT

    def next(self) -> "Epoch":
        """Next epoch: physical now if clock advanced, else +1 sequence."""
        ms = physical_now_ms()
        if ms > self.physical_ms:
            return Epoch.from_physical(ms)
        return Epoch(self.value + 1)

    def is_valid(self) -> bool:
        return self.value > 0

    def __repr__(self) -> str:
        return f"Epoch({self.value})"


Epoch.INVALID = Epoch(0)


@dataclass(frozen=True)
class EpochPair:
    """{curr, prev} as carried by every barrier (epoch.rs EpochPair)."""

    curr: Epoch
    prev: Epoch

    @staticmethod
    def new_initial(curr: Epoch) -> "EpochPair":
        return EpochPair(curr=curr, prev=Epoch.INVALID)

    def advance(self, new_curr: Epoch) -> "EpochPair":
        assert new_curr.value > self.curr.value
        return EpochPair(curr=new_curr, prev=self.curr)

    def __repr__(self) -> str:
        return f"EpochPair(curr={self.curr.value}, prev={self.prev.value})"
