"""Layered configuration system.

Reference parity: src/common/src/config.rs:133 (RwConfig{server, meta, batch,
streaming, storage, system}) + runtime-mutable SystemParams
(src/common/src/system_param/). Python re-design: frozen dataclasses with a
TOML loader and override dicts; SystemParams mutable + versioned for the
meta notification channel.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ServerConfig:
    heartbeat_interval_ms: int = 1000
    connection_pool_size: int = 16
    metrics_level: int = 1


@dataclass
class MetaConfig:
    barrier_interval_ms: int = 1000          # system heartbeat (meta config)
    in_flight_barrier_nums: int = 10         # concurrent barrier window
    checkpoint_frequency: int = 1            # every Nth barrier is a checkpoint
    max_heartbeat_interval_secs: int = 300   # worker expiry
    enable_recovery: bool = True


@dataclass
class StreamingConfig:
    actor_runtime_worker_threads: Optional[int] = None
    # permit-based exchange budgets (exchange/permit.rs:35 analog)
    exchange_max_chunk_permits: int = 2048
    exchange_max_barrier_permits: int = 128
    exchange_rows_per_permit: int = 256
    # device chunk shaping
    chunk_capacity: int = 4096               # max rows per StreamChunk bucket
    hash_table_load_factor: float = 0.5
    unique_user_stream_errors: int = 10


@dataclass
class StorageConfig:
    shared_buffer_capacity_mb: int = 1024
    block_size_kb: int = 64
    bloom_false_positive: float = 0.001
    object_store_url: str = "memory://"
    sstable_size_mb: int = 256
    imm_merge_threshold: int = 4
    data_directory: str = "hummock_001"


@dataclass
class BatchConfig:
    worker_threads_num: Optional[int] = None
    chunk_size: int = 1024


@dataclass
class SystemParams:
    """Runtime-mutable cluster params, versioned (system_param/ analog)."""

    barrier_interval_ms: int = 1000
    checkpoint_frequency: int = 1
    sstable_size_mb: int = 256
    block_size_kb: int = 64
    bloom_false_positive: float = 0.001
    state_store: str = "hummock+memory://"
    data_directory: str = "hummock_001"
    parallel_compact_size_mb: int = 512
    version: int = 1

    def set(self, name: str, value: Any) -> "SystemParams":
        out = dataclasses.replace(self, **{name: value})
        out.version = self.version + 1
        return out


@dataclass
class RwConfig:
    """Top-level layered config (config.rs:133 RwConfig analog)."""

    server: ServerConfig = field(default_factory=ServerConfig)
    meta: MetaConfig = field(default_factory=MetaConfig)
    streaming: StreamingConfig = field(default_factory=StreamingConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    batch: BatchConfig = field(default_factory=BatchConfig)
    system: SystemParams = field(default_factory=SystemParams)

    @staticmethod
    def from_toml(path: str, overrides: Optional[Dict[str, Any]] = None
                  ) -> "RwConfig":
        try:
            import tomllib
        except ModuleNotFoundError:      # Python < 3.11
            import tomli as tomllib
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        cfg = RwConfig()
        for section, cls_field in (
            ("server", "server"), ("meta", "meta"),
            ("streaming", "streaming"), ("storage", "storage"),
            ("batch", "batch"), ("system", "system"),
        ):
            if section in raw:
                cur = getattr(cfg, cls_field)
                known = {f.name for f in dataclasses.fields(cur)}
                for k, v in raw[section].items():
                    if k in known:
                        setattr(cur, k, v)
        for dotted, v in (overrides or {}).items():
            section, key = dotted.split(".", 1)
            target = getattr(cfg, section)
            known = {f.name for f in dataclasses.fields(target)}
            if key not in known:
                raise KeyError(f"unknown config key {dotted!r}; "
                               f"known: {sorted(known)}")
            setattr(target, key, v)
        return cfg
