"""Logical SQL types and their physical device representation.

Reference parity: ``DataType`` in src/common/src/types/mod.rs:99-160 (17 SQL
types). TPU-first design: every type picks a *physical* representation that is
either a JAX dtype (device-resident, participates in kernels) or a host-side
object column (varchar/jsonb — strings never ship to the device; they are
dictionary-encoded or carried on host alongside the device columns).
"""

from __future__ import annotations

import decimal
import enum
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

# DECIMAL physical representation: fixed-point int64, 4 fractional decimal
# digits (like SQL money). Exact for add/sub/compare — the operations money
# aggregates need — with documented bounds: |value| < 9.2e14 and products
# must fit int64 before rescale. The reference's Decimal (types/decimal.rs)
# is 28-digit arbitrary-scale; we trade generality for a representation the
# MXU/VPU can aggregate natively with retraction-exact sums.
DECIMAL_SCALE_DIGITS = 4
DECIMAL_SCALE = 10 ** DECIMAL_SCALE_DIGITS


# int64 bound of the scaled domain: |value| < 2^63 / 10^4 ≈ 9.2e14.
# Beyond it the fixed-point payload would WRAP silently (VERDICT r5
# weak #6) — every ingest/cast boundary funnels through
# decimal_to_scaled, so the check lives here, once.
_SCALED_MAX = (1 << 63) - 1


class DecimalOverflowError(ValueError):
    """A DECIMAL value left the int64 fixed-point domain."""


def decimal_to_scaled(v) -> int:
    """Python number → scaled int64 payload (banker-free, half-up
    round). Raises DecimalOverflowError instead of silently wrapping
    when |scaled| exceeds int64 (~9.2e14 in value units)."""
    if isinstance(v, int):
        scaled = v * DECIMAL_SCALE
    else:
        d = v if isinstance(v, decimal.Decimal) \
            else decimal.Decimal(str(v))
        scaled = int((d * DECIMAL_SCALE).to_integral_value(
            rounding=decimal.ROUND_HALF_UP))
    if not -_SCALED_MAX <= scaled <= _SCALED_MAX:
        raise DecimalOverflowError(
            f"DECIMAL value {v} overflows the int64 fixed-point "
            f"domain (|value| must stay under "
            f"{_SCALED_MAX // DECIMAL_SCALE})")
    return scaled


def scaled_to_decimal(raw: int) -> decimal.Decimal:
    return decimal.Decimal(int(raw)) / DECIMAL_SCALE


@dataclass(frozen=True)
class Interval:
    """Calendar interval: (months, days, microseconds) triple.

    Reference parity: src/common/src/types/interval.rs — the three components
    do NOT fold into each other (a month is not a fixed number of days).
    Comparison/equality use the *justified* value (month = 30 days), matching
    the reference's IntervalCmpValue: INTERVAL '30 days' == INTERVAL
    '1 month'. Interval columns live on host; device window arithmetic uses
    ``exact_usecs()`` of *literal* intervals at plan-build time.
    """

    months: int = 0
    days: int = 0
    usecs: int = 0

    USECS_PER_DAY = 86_400_000_000
    USECS_PER_MONTH_APPROX = 30 * 86_400_000_000  # justified comparison

    def _justified_usecs(self) -> int:
        return (self.months * Interval.USECS_PER_MONTH_APPROX
                + self.days * Interval.USECS_PER_DAY + self.usecs)

    def __eq__(self, other):
        if not isinstance(other, Interval):
            return NotImplemented
        return self._justified_usecs() == other._justified_usecs()

    def __hash__(self):
        return hash(self._justified_usecs())

    def __lt__(self, other: "Interval"):
        return self._justified_usecs() < other._justified_usecs()

    def __le__(self, other: "Interval"):
        return self._justified_usecs() <= other._justified_usecs()

    def __gt__(self, other: "Interval"):
        return self._justified_usecs() > other._justified_usecs()

    def __ge__(self, other: "Interval"):
        return self._justified_usecs() >= other._justified_usecs()

    @staticmethod
    def from_duration(*, weeks: int = 0, days: int = 0, hours: int = 0,
                      minutes: int = 0, seconds: float = 0,
                      millis: int = 0, usecs: int = 0) -> "Interval":
        return Interval(0, weeks * 7 + days,
                        usecs + millis * 1000 + int(seconds * 1_000_000)
                        + minutes * 60_000_000 + hours * 3_600_000_000)

    def exact_usecs(self) -> int:
        """Total µs for month-free intervals; raises if months != 0."""
        if self.months:
            raise ValueError(
                f"interval {self!r} has calendar months; no exact µs length")
        return self.days * Interval.USECS_PER_DAY + self.usecs

    def __add__(self, other: "Interval") -> "Interval":
        return Interval(self.months + other.months, self.days + other.days,
                        self.usecs + other.usecs)

    def __neg__(self) -> "Interval":
        return Interval(-self.months, -self.days, -self.usecs)


class DataType(enum.Enum):
    """Logical SQL data types (reference: src/common/src/types/mod.rs:99)."""

    BOOLEAN = "boolean"
    INT16 = "smallint"
    INT32 = "int"
    INT64 = "bigint"
    FLOAT32 = "real"
    FLOAT64 = "double precision"
    DECIMAL = "numeric"          # physical: scaled int64 fixed-point (exact)
    DATE = "date"                # days since epoch, int32
    TIME = "time"                # microseconds since midnight, int64
    TIMESTAMP = "timestamp"      # microseconds since unix epoch, int64
    TIMESTAMPTZ = "timestamptz"  # microseconds since unix epoch (UTC), int64
    INTERVAL = "interval"        # host column of Interval triples
    VARCHAR = "varchar"          # host column (numpy object)
    BYTEA = "bytea"              # host column
    JSONB = "jsonb"              # host column
    SERIAL = "serial"            # int64 row id
    INT256 = "rw_int256"         # host column (python int); device later
    STRUCT = "struct"            # host column of tuples
    LIST = "list"                # host column of lists

    # ------------------------------------------------------------------
    @property
    def is_device(self) -> bool:
        """Whether columns of this type live on device (JAX array)."""
        return self not in _HOST_TYPES

    @property
    def dtype(self) -> Optional[jnp.dtype]:
        """Physical JAX dtype for device types; None for host types."""
        return _PHYSICAL.get(self)

    @property
    def np_dtype(self):
        d = _PHYSICAL.get(self)
        return np.dtype(object) if d is None else np.dtype(d)

    @property
    def is_numeric(self) -> bool:
        return self in (
            DataType.INT16, DataType.INT32, DataType.INT64,
            DataType.FLOAT32, DataType.FLOAT64, DataType.DECIMAL,
        )

    @property
    def is_integral(self) -> bool:
        return self in (DataType.INT16, DataType.INT32, DataType.INT64,
                        DataType.SERIAL)

    def zero_value(self):
        """Padding value used in fixed-capacity device buffers."""
        if self.is_device:
            return np.zeros((), dtype=self.np_dtype)[()]
        return None

    @staticmethod
    def from_sql(name: str) -> "DataType":
        return _SQL_NAMES[name.strip().lower()]


_HOST_TYPES = frozenset({DataType.VARCHAR, DataType.BYTEA, DataType.JSONB,
                         DataType.INTERVAL, DataType.INT256, DataType.STRUCT,
                         DataType.LIST})

_PHYSICAL = {
    DataType.BOOLEAN: jnp.bool_,
    DataType.INT16: jnp.int16,
    DataType.INT32: jnp.int32,
    DataType.INT64: jnp.int64,
    DataType.FLOAT32: jnp.float32,
    DataType.FLOAT64: jnp.float64,
    DataType.DECIMAL: jnp.int64,
    DataType.DATE: jnp.int32,
    DataType.TIME: jnp.int64,
    DataType.TIMESTAMP: jnp.int64,
    DataType.TIMESTAMPTZ: jnp.int64,
    DataType.SERIAL: jnp.int64,
}

_SQL_NAMES = {
    "boolean": DataType.BOOLEAN, "bool": DataType.BOOLEAN,
    "smallint": DataType.INT16, "int2": DataType.INT16,
    "int": DataType.INT32, "integer": DataType.INT32, "int4": DataType.INT32,
    "bigint": DataType.INT64, "int8": DataType.INT64,
    "real": DataType.FLOAT32, "float4": DataType.FLOAT32,
    "double precision": DataType.FLOAT64, "double": DataType.FLOAT64,
    "float8": DataType.FLOAT64, "float": DataType.FLOAT64,
    "numeric": DataType.DECIMAL, "decimal": DataType.DECIMAL,
    "date": DataType.DATE,
    "time": DataType.TIME,
    "timestamp": DataType.TIMESTAMP,
    "timestamptz": DataType.TIMESTAMPTZ,
    "timestamp with time zone": DataType.TIMESTAMPTZ,
    "interval": DataType.INTERVAL,
    "varchar": DataType.VARCHAR, "text": DataType.VARCHAR,
    "string": DataType.VARCHAR, "character varying": DataType.VARCHAR,
    "bytea": DataType.BYTEA,
    "jsonb": DataType.JSONB,
    "serial": DataType.SERIAL,
    "rw_int256": DataType.INT256, "int256": DataType.INT256,
    "struct": DataType.STRUCT,
    "list": DataType.LIST,
}


@dataclass(frozen=True)
class Field:
    """A named, typed column (reference: src/common/src/catalog/field-like)."""

    name: str
    data_type: DataType

    def __repr__(self) -> str:
        return f"{self.name}:{self.data_type.name.lower()}"


@dataclass(frozen=True)
class Schema:
    """Ordered list of fields describing a chunk/table/executor output."""

    fields: Tuple[Field, ...] = field(default_factory=tuple)

    def __init__(self, fields):
        object.__setattr__(self, "fields", tuple(fields))

    @staticmethod
    def of(**cols: DataType) -> "Schema":
        return Schema([Field(n, t) for n, t in cols.items()])

    def __len__(self) -> int:
        return len(self.fields)

    def __getitem__(self, i: int) -> Field:
        return self.fields[i]

    def __iter__(self):
        return iter(self.fields)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def names(self):
        return [f.name for f in self.fields]

    def types(self):
        return [f.data_type for f in self.fields]

    def select(self, indices) -> "Schema":
        return Schema([self.fields[i] for i in indices])

    def concat(self, other: "Schema") -> "Schema":
        return Schema(list(self.fields) + list(other.fields))

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(map(repr, self.fields)) + ")"
