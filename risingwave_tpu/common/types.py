"""Logical SQL types and their physical device representation.

Reference parity: ``DataType`` in src/common/src/types/mod.rs:99-160 (17 SQL
types). TPU-first design: every type picks a *physical* representation that is
either a JAX dtype (device-resident, participates in kernels) or a host-side
object column (varchar/jsonb — strings never ship to the device; they are
dictionary-encoded or carried on host alongside the device columns).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    """Logical SQL data types (reference: src/common/src/types/mod.rs:99)."""

    BOOLEAN = "boolean"
    INT16 = "smallint"
    INT32 = "int"
    INT64 = "bigint"
    FLOAT32 = "real"
    FLOAT64 = "double precision"
    DECIMAL = "numeric"          # physical: float64 (documented precision loss) v0
    DATE = "date"                # days since epoch, int32
    TIME = "time"                # microseconds since midnight, int64
    TIMESTAMP = "timestamp"      # microseconds since unix epoch, int64
    TIMESTAMPTZ = "timestamptz"  # microseconds since unix epoch (UTC), int64
    INTERVAL = "interval"        # microseconds, int64 (months/days folded) v0
    VARCHAR = "varchar"          # host column (numpy object)
    BYTEA = "bytea"              # host column
    JSONB = "jsonb"              # host column
    SERIAL = "serial"            # int64 row id
    # STRUCT / LIST handled as composite Schema-level features later rounds.

    # ------------------------------------------------------------------
    @property
    def is_device(self) -> bool:
        """Whether columns of this type live on device (JAX array)."""
        return self not in _HOST_TYPES

    @property
    def dtype(self) -> Optional[jnp.dtype]:
        """Physical JAX dtype for device types; None for host types."""
        return _PHYSICAL.get(self)

    @property
    def np_dtype(self):
        d = _PHYSICAL.get(self)
        return np.dtype(object) if d is None else np.dtype(d)

    @property
    def is_numeric(self) -> bool:
        return self in (
            DataType.INT16, DataType.INT32, DataType.INT64,
            DataType.FLOAT32, DataType.FLOAT64, DataType.DECIMAL,
        )

    @property
    def is_integral(self) -> bool:
        return self in (DataType.INT16, DataType.INT32, DataType.INT64,
                        DataType.SERIAL)

    def zero_value(self):
        """Padding value used in fixed-capacity device buffers."""
        if self.is_device:
            return np.zeros((), dtype=self.np_dtype)[()]
        return None

    @staticmethod
    def from_sql(name: str) -> "DataType":
        return _SQL_NAMES[name.strip().lower()]


_HOST_TYPES = frozenset({DataType.VARCHAR, DataType.BYTEA, DataType.JSONB})

_PHYSICAL = {
    DataType.BOOLEAN: jnp.bool_,
    DataType.INT16: jnp.int16,
    DataType.INT32: jnp.int32,
    DataType.INT64: jnp.int64,
    DataType.FLOAT32: jnp.float32,
    DataType.FLOAT64: jnp.float64,
    DataType.DECIMAL: jnp.float64,
    DataType.DATE: jnp.int32,
    DataType.TIME: jnp.int64,
    DataType.TIMESTAMP: jnp.int64,
    DataType.TIMESTAMPTZ: jnp.int64,
    DataType.INTERVAL: jnp.int64,
    DataType.SERIAL: jnp.int64,
}

_SQL_NAMES = {
    "boolean": DataType.BOOLEAN, "bool": DataType.BOOLEAN,
    "smallint": DataType.INT16, "int2": DataType.INT16,
    "int": DataType.INT32, "integer": DataType.INT32, "int4": DataType.INT32,
    "bigint": DataType.INT64, "int8": DataType.INT64,
    "real": DataType.FLOAT32, "float4": DataType.FLOAT32,
    "double precision": DataType.FLOAT64, "double": DataType.FLOAT64,
    "float8": DataType.FLOAT64, "float": DataType.FLOAT64,
    "numeric": DataType.DECIMAL, "decimal": DataType.DECIMAL,
    "date": DataType.DATE,
    "time": DataType.TIME,
    "timestamp": DataType.TIMESTAMP,
    "timestamptz": DataType.TIMESTAMPTZ,
    "timestamp with time zone": DataType.TIMESTAMPTZ,
    "interval": DataType.INTERVAL,
    "varchar": DataType.VARCHAR, "text": DataType.VARCHAR,
    "string": DataType.VARCHAR, "character varying": DataType.VARCHAR,
    "bytea": DataType.BYTEA,
    "jsonb": DataType.JSONB,
    "serial": DataType.SERIAL,
}


@dataclass(frozen=True)
class Field:
    """A named, typed column (reference: src/common/src/catalog/field-like)."""

    name: str
    data_type: DataType

    def __repr__(self) -> str:
        return f"{self.name}:{self.data_type.name.lower()}"


@dataclass(frozen=True)
class Schema:
    """Ordered list of fields describing a chunk/table/executor output."""

    fields: Tuple[Field, ...] = field(default_factory=tuple)

    def __init__(self, fields):
        object.__setattr__(self, "fields", tuple(fields))

    @staticmethod
    def of(**cols: DataType) -> "Schema":
        return Schema([Field(n, t) for n, t in cols.items()])

    def __len__(self) -> int:
        return len(self.fields)

    def __getitem__(self, i: int) -> Field:
        return self.fields[i]

    def __iter__(self):
        return iter(self.fields)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def names(self):
        return [f.name for f in self.fields]

    def types(self):
        return [f.data_type for f in self.fields]

    def select(self, indices) -> "Schema":
        return Schema([self.fields[i] for i in indices])

    def concat(self, other: "Schema") -> "Schema":
        return Schema(list(self.fields) + list(other.fields))

    def __repr__(self) -> str:
        return "Schema(" + ", ".join(map(repr, self.fields)) + ")"
