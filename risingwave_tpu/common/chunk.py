"""Columnar data batches: ``DataChunk`` and ``StreamChunk``.

Reference parity: src/common/src/array/data_chunk.rs:65 and
src/common/src/array/stream_chunk.rs:87.

TPU-first design decisions (deliberately NOT a port of the Rust arrays):

- A chunk is a set of fixed-capacity columns. Columns are HOST-resident
  numpy arrays by default; device residency begins exactly at stateful
  kernels, which call ``to_device()`` once per chunk (upload is cheap and
  async) and transfer back only at barrier flush via one batched
  ``jax.device_get``. Stateless operators (project/filter/dispatch) never
  touch the device — per-op device dispatch would be latency-bound, not
  compute-bound. varchar/bytea/jsonb columns are always host (numpy object
  arrays; strings never ship to the device).
- Row validity is a single boolean *visibility* array (doubles as both the
  reference's visibility bitmap and the padding mask). Capacity is padded to
  a power-of-two bucket so XLA sees a small, stable set of static shapes —
  this is how we live with dynamic row counts under jit (SURVEY.md section 7
  "hard part 2").
- Per-column null validity is an optional boolean array per column (None
  means "no nulls").
- ``StreamChunk`` adds an int8 ``ops`` vector with the 4 reference ops
  (Insert/Delete/UpdateDelete/UpdateInsert); ``signs()`` maps them to +1/-1
  which is what aggregation kernels actually consume.

Kernels take raw arrays (``chunk.device_columns()``), not chunk objects —
chunks are host-side bookkeeping, arrays are the jit boundary.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import types as types_mod
from risingwave_tpu.common.types import DataType, Field, Schema


def next_pow2(n: int, floor: int = 8) -> int:
    """Pad row counts to power-of-two buckets to bound jit recompilation."""
    c = floor
    while c < n:
        c <<= 1
    return c

def presize_cap(n: int, floor: int = 1 << 16, ceil: int = 1 << 20) -> int:
    """Kernel table capacity for a KNOWN cardinality: pow2 with 2x load
    headroom, clamped. Every growth doubling costs a device rehash plus
    a fresh XLA compile of the per-shape programs — a builder that
    knows its scale should skip the whole ladder."""
    return min(next_pow2(max(2 * n, floor)), ceil)


def presize_flush_cap(n: int, floor: int = 1 << 14,
                      ceil: int = 1 << 17) -> int:
    """Flush gather-buffer rows for a KNOWN dirty-group bound (same
    compile-ladder argument as presize_cap; the gather cost scales with
    the buffer, hence the lower ceiling)."""
    return min(next_pow2(max(n, floor)), ceil)



class Op(enum.IntEnum):
    """Row operation in a stream chunk (stream_chunk.rs:29-ish semantics)."""

    INSERT = 1
    DELETE = 2
    UPDATE_DELETE = 3
    UPDATE_INSERT = 4

    @property
    def is_insert(self) -> bool:
        return self in (Op.INSERT, Op.UPDATE_INSERT)

    @property
    def sign(self) -> int:
        return 1 if self.is_insert else -1


def get_xp(*arrays):
    """numpy for host arrays, jax.numpy once anything is a jax array/tracer.

    The chunk/expression layer is backend-polymorphic: chunks stay numpy
    (host) through stateless operators; the same code traces under jit when
    a stateful kernel pulls arrays to the device (to_device()).
    """
    for a in arrays:
        if isinstance(a, (jax.Array, jax.core.Tracer)):
            return jnp
    return np


# Vectorized op→sign: ops in {1,2,3,4}; insert-ish ops are odd (1) or 4.
def ops_to_signs(ops) -> "jnp.ndarray":
    """+1 for INSERT/UPDATE_INSERT, -1 for DELETE/UPDATE_DELETE (int32)."""
    xp = get_xp(ops)
    is_ins = (ops == Op.INSERT) | (ops == Op.UPDATE_INSERT)
    return xp.where(is_ins, xp.int32(1), xp.int32(-1))


@dataclass
class Column:
    """One column: device JAX array or host numpy object array + null mask."""

    data_type: DataType
    values: Union[jnp.ndarray, np.ndarray]
    validity: Optional[Union[jnp.ndarray, np.ndarray]] = None  # True = non-null

    @property
    def is_device(self) -> bool:
        return self.data_type.is_device

    def __len__(self) -> int:
        return int(self.values.shape[0])

    def take_host(self, idx: np.ndarray) -> "Column":
        vals = np.asarray(self.values)[idx]
        val = None if self.validity is None else np.asarray(self.validity)[idx]
        return Column(self.data_type, vals if not self.is_device
                      else jnp.asarray(vals), None if val is None
                      else (val if not self.is_device else jnp.asarray(val)))


def _make_column(dt: DataType, values, capacity: int,
                 validity=None) -> Column:
    """Build a column from python/numpy values, padded to `capacity`.

    Vectorized: numpy-array inputs take the zero-copy fast path; python-list
    inputs do one object-array pass for null detection (test construction
    only — the ingest hot path feeds ``DataChunk.from_arrays`` with ready
    numpy arrays, never lists).
    """
    n = len(values)
    if n > capacity:
        raise ValueError(f"{n} values exceed column capacity {capacity}")
    if dt.is_device:
        arr = np.zeros(capacity, dtype=dt.np_dtype)
        null_mask = None
        if n:
            if isinstance(values, np.ndarray) and values.dtype != object:
                if dt == DataType.DECIMAL:
                    # logical-value ingest of decimals: scale, vectorized
                    # (raw scaled-int arrays enter via from_arrays, not here)
                    if np.issubdtype(values.dtype, np.integer):
                        arr[:n] = values.astype(np.int64) * \
                            types_mod.DECIMAL_SCALE
                    else:
                        arr[:n] = np.rint(values * types_mod.DECIMAL_SCALE)
                else:
                    arr[:n] = values.astype(dt.np_dtype)
            else:
                obj = np.asarray(values, dtype=object)
                null_mask = obj == None  # noqa: E711  (elementwise)
                if null_mask.any():
                    obj = obj.copy()
                    obj[null_mask] = 0
                else:
                    null_mask = None
                if dt == DataType.DECIMAL:
                    obj = np.asarray(
                        [types_mod.decimal_to_scaled(v) for v in obj],
                        dtype=object)
                arr[:n] = obj.astype(dt.np_dtype)
        out_validity = None
        if validity is not None or null_mask is not None:
            val = np.ones(capacity, dtype=bool)
            if validity is not None:
                val[:n] = np.asarray(validity, dtype=bool)
            if null_mask is not None:
                val[:n] &= ~null_mask
            out_validity = val
        return Column(dt, arr, out_validity)
    else:
        arr = np.empty(capacity, dtype=object)
        # fromiter keeps tuple/list elements scalar (STRUCT/LIST columns)
        arr[:n] = np.fromiter(values, dtype=object, count=n)
        out_validity = None
        if validity is not None:
            val = np.ones(capacity, dtype=bool)
            val[:n] = np.asarray(validity, dtype=bool)
            out_validity = val
        return Column(dt, arr, out_validity)


class DataChunk:
    """A batch of columns + visibility mask (data_chunk.rs:65 analog)."""

    def __init__(self, schema: Schema, columns: Sequence[Column],
                 visibility: jnp.ndarray):
        assert len(schema) == len(columns), (len(schema), len(columns))
        self.schema = schema
        self.columns: Tuple[Column, ...] = tuple(columns)
        self.visibility = visibility  # jnp bool [capacity]
        cap = int(visibility.shape[0])
        for c in self.columns:
            assert int(c.values.shape[0]) == cap, "column capacity mismatch"
        self._capacity = cap
        # set by stream.coalesce.compact/merge_chunks on chunks whose
        # visible rows are a KNOWN dense prefix: the visible-row count
        # without a host sum. Exchange credit charges this instead of
        # padded capacity (a compacted chunk costs its true rows, not
        # 4x them); None means "not established".
        self.dense_rows: Optional[int] = None

    # -- constructors --------------------------------------------------
    @staticmethod
    def from_pydict(schema: Schema, data: Dict[str, list],
                    capacity: Optional[int] = None) -> "DataChunk":
        ncols = [data[f.name] for f in schema]
        n = len(ncols[0]) if ncols else 0
        cap = capacity or next_pow2(max(n, 1))
        cols = [_make_column(f.data_type, vals, cap)
                for f, vals in zip(schema, ncols)]
        vis = np.zeros(cap, dtype=bool)
        vis[:n] = True
        return DataChunk(schema, cols, vis)

    @staticmethod
    def from_arrays(schema: Schema, arrays: Sequence, num_rows: int,
                    capacity: Optional[int] = None) -> "DataChunk":
        """From ready-made (device or host) arrays, all already `capacity`-long."""
        cols = [Column(f.data_type, a) for f, a in zip(schema, arrays)]
        cap = int(arrays[0].shape[0]) if arrays else (capacity or 8)
        if capacity is not None and arrays and capacity != cap:
            raise ValueError(
                f"capacity={capacity} disagrees with array length {cap}")
        if num_rows > cap:
            raise ValueError(f"num_rows={num_rows} exceeds capacity {cap}")
        vis = np.zeros(cap, dtype=bool)
        vis[:num_rows] = True
        return DataChunk(schema, cols, vis)

    @classmethod
    def empty(cls, schema: Schema, capacity: int = 8) -> "DataChunk":
        return cls.from_pydict(schema, {f.name: [] for f in schema},
                               capacity=capacity)

    # -- properties ----------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._capacity

    def cardinality(self) -> int:
        """Number of visible rows (host sync unless dense_rows known)."""
        if self.dense_rows is not None:
            return self.dense_rows
        return int(np.sum(np.asarray(self.visibility)))

    def column(self, name: str) -> Column:
        return self.columns[self.schema.index_of(name)]

    def column_values(self, name: str):
        return self.columns[self.schema.index_of(name)].values

    def device_columns(self) -> List[jnp.ndarray]:
        return [c.values for c in self.columns if c.is_device]

    # -- device boundary -----------------------------------------------
    def _device_parts(self):
        cols = [
            Column(c.data_type, jnp.asarray(c.values),
                   None if c.validity is None else jnp.asarray(c.validity))
            if c.is_device else c
            for c in self.columns
        ]
        return cols, jnp.asarray(np.asarray(self.visibility))

    def to_device(self) -> "DataChunk":
        """Upload device-typed columns + visibility to HBM (async, cheap).

        This is THE device boundary: stateless operators never call it;
        stateful kernels call it once per chunk and never transfer back
        until barrier flush (batched jax.device_get there).
        """
        cols, vis = self._device_parts()
        return DataChunk(self.schema, cols, vis)

    # -- transforms ----------------------------------------------------
    def project(self, indices: Sequence[int]) -> "DataChunk":
        return DataChunk(self.schema.select(indices),
                         [self.columns[i] for i in indices], self.visibility)

    def with_visibility(self, vis: jnp.ndarray) -> "DataChunk":
        return DataChunk(self.schema, self.columns, vis)

    def mask(self, predicate: jnp.ndarray) -> "DataChunk":
        return self.with_visibility(self.visibility & predicate)

    def with_columns(self, schema: Schema,
                     columns: Sequence[Column]) -> "DataChunk":
        return DataChunk(schema, columns, self.visibility)

    # -- host materialization (tests, result sets, sinks) --------------
    def to_pylist(self, compact: bool = True) -> List[tuple]:
        vis = np.asarray(self.visibility)
        host_cols = []
        for c in self.columns:
            vals = np.asarray(c.values)
            val = None if c.validity is None else np.asarray(c.validity)
            host_cols.append((vals, val, c.data_type))
        rows = []
        for i in range(self._capacity):
            if compact and not vis[i]:
                continue
            row = []
            for vals, val, dt in host_cols:
                if val is not None and not val[i]:
                    row.append(None)
                else:
                    v = vals[i]
                    if dt.is_device:
                        v = v.item() if hasattr(v, "item") else v
                        if dt == DataType.BOOLEAN:
                            v = bool(v)
                        elif dt == DataType.DECIMAL:
                            v = types_mod.scaled_to_decimal(v)
                    row.append(v)
            rows.append(tuple(row))
        return rows

    def __repr__(self) -> str:
        return (f"DataChunk(cap={self._capacity}, "
                f"rows={self.cardinality()}, schema={self.schema})")


class StreamChunk(DataChunk):
    """DataChunk + per-row Op vector (stream_chunk.rs:87 analog)."""

    def __init__(self, schema: Schema, columns: Sequence[Column],
                 visibility: jnp.ndarray, ops: jnp.ndarray):
        super().__init__(schema, columns, visibility)
        assert int(ops.shape[0]) == self._capacity
        self.ops = ops  # jnp int8 [capacity]

    @staticmethod
    def from_pydict(schema: Schema, data: Dict[str, list],
                    ops: Optional[Sequence[int]] = None,
                    capacity: Optional[int] = None) -> "StreamChunk":
        base = DataChunk.from_pydict(schema, data, capacity=capacity)
        n = len(next(iter(data.values()))) if data else 0
        o = np.full(base.capacity, int(Op.INSERT), dtype=np.int8)
        if ops is not None:
            o[:n] = np.asarray([int(x) for x in ops], dtype=np.int8)
        return StreamChunk(schema, base.columns, base.visibility, o)

    @staticmethod
    def from_data_chunk(chunk: DataChunk,
                        ops: Optional[jnp.ndarray] = None) -> "StreamChunk":
        o = ops if ops is not None else np.full(
            chunk.capacity, int(Op.INSERT), dtype=np.int8)
        return StreamChunk(chunk.schema, chunk.columns, chunk.visibility, o)

    def signs(self) -> jnp.ndarray:
        """+1/-1 per row (masked rows included; gate with visibility)."""
        return ops_to_signs(self.ops)

    def to_device(self) -> "StreamChunk":
        cols, vis = self._device_parts()
        return StreamChunk(self.schema, cols, vis, jnp.asarray(self.ops))

    def project(self, indices: Sequence[int]) -> "StreamChunk":
        return StreamChunk(self.schema.select(indices),
                           [self.columns[i] for i in indices],
                           self.visibility, self.ops)

    def with_visibility(self, vis: jnp.ndarray) -> "StreamChunk":
        return StreamChunk(self.schema, self.columns, vis, self.ops)

    def with_columns(self, schema: Schema,
                     columns: Sequence[Column]) -> "StreamChunk":
        return StreamChunk(schema, columns, self.visibility, self.ops)

    def to_physical_records(self) -> Tuple[np.ndarray, List[tuple], np.ndarray]:
        """Vectorized extraction of visible rows as *physical* tuples.

        Returns (visible_idx, rows, ops[visible]) where rows hold raw
        physical values (DECIMAL as scaled int, timestamps as µs ints,
        NULL as None) — the representation state tables store. No per-row
        Python beyond C-speed zip; this is the barrier-flush hot path.
        """
        vis = np.asarray(self.visibility)
        idx = np.flatnonzero(vis)
        cols: List[list] = []
        for c in self.columns:
            vals = np.asarray(c.values)[idx]
            if c.validity is not None:
                nulls = ~np.asarray(c.validity)[idx]
                if nulls.any():
                    out = vals.astype(object)
                    out[nulls] = None
                    cols.append(out.tolist())
                    continue
            cols.append(vals.tolist())
        rows = list(zip(*cols)) if cols else []
        return idx, rows, np.asarray(self.ops)[idx]

    def to_records(self, compact: bool = True) -> List[tuple]:
        """[(Op, row-tuple)] for visible rows."""
        vis = np.asarray(self.visibility)
        ops = np.asarray(self.ops)
        rows = super().to_pylist(compact=False)
        out = []
        for i, row in enumerate(rows):
            if compact and not vis[i]:
                continue
            out.append((Op(int(ops[i])), row))
        return out

    def __repr__(self) -> str:
        return (f"StreamChunk(cap={self._capacity}, "
                f"rows={self.cardinality()}, schema={self.schema})")
