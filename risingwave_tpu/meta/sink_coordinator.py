"""Meta-side sink coordinator: epoch-aligned N-writer commits.

Reference parity: src/meta/src/manager/sink_coordination/ — the
coordinator that collects N sink writers' pre-commit metadata for a
checkpoint epoch and performs the single serialized commit. Here the
commit decision is LISTING-DRIVEN (connectors/sink.py): the
coordinator commits every staged-but-unmanifested epoch ≤ the
checkpoint floor, so pre-commit handles are pure telemetry — a lost
drain can delay nothing and lose nothing, and zero-row writers (which
stage no segment) need no special case.

One SinkCoordinator per barrier-engine owner (the in-process Frontend,
the cluster coordinator) — NOT process-global: commit authority is
"this engine's checkpoint floor", and two engines in one process (the
oracle arm beside the arm under test) must not commit each other's
sinks with each other's floors. The owner attaches the coordinator to
its CheckpointUploader (``uploader.sinks``), which calls:

  ``stage_upto(epoch)``  after the epoch's SST uploads, BEFORE the
                         durable commit — staging rides the async
                         upload tail (never barrier_wait), and the
                         floor can only advance past fully-staged
                         epochs (invariant 2 of connectors/sink.py);
  ``commit_upto(floor)`` after the durable commit — manifests land
                         strictly behind the floor (invariant 1).

In-process pipelines run writers in DEFERRED mode: the executor hands
its epoch payload (raw records) to ``submit`` at barrier passage — a
cheap list append — and serialization + staging happen in the
uploader's stage hook off the barrier path. Distributed workers run
INLINE: each writer stages synchronously at barrier passage in its own
process (before its barrier is collected, so collection ⟹ staged ⟹
the coordinator floor covers only durable staging), and the
coordinator process registers the sink for the commit/recovery half
only.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from risingwave_tpu.utils.metrics import STREAMING as _METRICS


def note_staged(sink: str, mode: str, rows: int, nbytes: int) -> None:
    """Metric taps shared by both staging paths (deferred coordinator
    staging and inline worker staging)."""
    if rows:
        _METRICS.sink_rows_total.inc(rows, sink=sink, mode=mode)
    if nbytes:
        _METRICS.sink_staged_bytes.inc(nbytes, sink=sink)


class _Sink:
    __slots__ = ("name", "encoder", "n_writers", "deferred",
                 "pending", "precommits", "committed")

    def __init__(self, name, encoder, n_writers, deferred):
        self.name = name
        self.encoder = encoder              # Append/UpsertSegmentSink
        self.n_writers = int(n_writers)
        self.deferred = bool(deferred)
        # deferred payloads: (epoch, writer, records) in submit order
        self.pending: List[tuple] = []
        # epoch → {writer: handle} — telemetry only, never authority
        self.precommits: Dict[int, Dict[int, dict]] = {}
        self.committed = 0

    @property
    def target(self):
        return self.encoder.target


class SinkCoordinator:
    """Collects pre-commits, stages deferred payloads, and owns the
    manifest commit + recovery truncation for every registered sink
    of ONE barrier engine."""

    def __init__(self) -> None:
        self._sinks: Dict[str, _Sink] = {}

    # -- registration -----------------------------------------------------
    def register(self, name: str, encoder, n_writers: int = 1,
                 deferred: bool = True,
                 floor: Optional[int] = None) -> None:
        """Register (or re-register after recovery — pending payloads
        of the dead generation drop). With a floor, run the recovery
        sweep immediately: promote ≤ floor, truncate the rest."""
        self._sinks[name] = _Sink(name, encoder, n_writers, deferred)
        if floor is not None:
            self.recover(floor, only=name)

    def unregister(self, name: str) -> None:
        self._sinks.pop(name, None)

    def names(self) -> List[str]:
        return sorted(self._sinks)

    def sink(self, name: str) -> Optional[_Sink]:
        return self._sinks.get(name)

    # -- writer side (deferred mode) --------------------------------------
    def submit(self, name: str, epoch: int, writer: int,
               records: list) -> None:
        """Buffer one writer's epoch payload at barrier passage (raw
        records; encoding happens in the stage hook, off the barrier
        path)."""
        s = self._sinks[name]
        assert s.deferred, "inline writers stage directly"
        s.pending.append((epoch, writer, records))

    def note_precommit(self, name: str, epoch: int,
                       handle: dict) -> None:
        s = self._sinks.get(name)
        if s is not None:
            s.precommits.setdefault(epoch, {})[
                handle.get("writer", 0)] = handle

    # -- the uploader hooks -----------------------------------------------
    def _take_pending(self, epoch: int):
        work = []
        for s in self._sinks.values():
            if not s.deferred or not s.pending:
                continue
            take = [p for p in s.pending if p[0] <= epoch]
            if take:
                s.pending = [p for p in s.pending if p[0] > epoch]
                work.append((s, take))
        return work

    def _stage_one(self, s: _Sink, epoch: int, writer: int,
                   records: list) -> dict:
        handle = s.encoder.stage(epoch, writer, records)
        note_staged(s.name, s.encoder.mode, handle["rows"],
                    handle["bytes"])
        return handle

    def stage_upto_sync(self, epoch: int) -> None:
        """Inline fallback (memory stores, the coordinator epoch
        shim): stage every pending payload ≤ epoch before the store's
        durable sync."""
        for s, take in self._take_pending(epoch):
            for e, w, recs in take:
                self.note_precommit(s.name, e,
                                    self._stage_one(s, e, w, recs))

    async def stage_upto(self, epoch: int) -> None:
        """Split-path hook: stage concurrently via worker threads —
        serialization and PUTs land in the ledger's async upload
        tail, never in barrier_wait."""
        work = [(s, e, w, recs)
                for s, take in self._take_pending(epoch)
                for e, w, recs in take]
        if not work:
            return
        handles = await asyncio.gather(
            *(asyncio.to_thread(self._stage_one, s, e, w, recs)
              for s, e, w, recs in work))
        for (s, e, _w, _r), h in zip(work, handles):
            self.note_precommit(s.name, e, h)

    def commit_upto(self, floor: int) -> Dict[str, List[int]]:
        """Manifest-commit every sink's staged epochs ≤ floor (the
        checkpoint floor just made durable). Raises on manifest-PUT
        failure — the barrier round fails and supervised recovery
        re-derives the commit from the staged listing."""
        out = {}
        for s in self._sinks.values():
            done = s.target.commit_upto(floor)
            if done:
                out[s.name] = done
                s.committed = max(s.committed, done[-1])
                _METRICS.sink_committed_epoch.set(
                    s.committed, sink=s.name)
                for e in done:
                    s.precommits.pop(e, None)
        return out

    # -- recovery ---------------------------------------------------------
    def recover(self, floor: int,
                only: Optional[str] = None) -> Dict[str, tuple]:
        """Post-crash sweep for every registered sink: drop dead
        in-memory payloads, promote staged epochs ≤ floor, truncate
        the rest (connectors/sink.py recovery rule)."""
        out = {}
        for s in self._sinks.values():
            if only is not None and s.name != only:
                continue
            s.pending = []
            s.precommits = {}
            promoted, truncated = s.target.recover(floor)
            s.committed = s.target.committed_epoch()
            _METRICS.sink_committed_epoch.set(s.committed, sink=s.name)
            out[s.name] = (promoted, truncated)
        return out

    # -- telemetry --------------------------------------------------------
    def snapshot(self) -> List[dict]:
        """Per-sink serving view (ctl sinks / rw_sinks): committed
        epoch, staged-but-uncommitted bytes, and writer lag at the
        newest uncommitted epoch."""
        out = []
        for name in sorted(self._sinks):
            s = self._sinks[name]
            out.append(sink_stats(s.target, s.n_writers,
                                  name=name, mode=s.encoder.mode))
        return out


def sink_stats(target, n_writers: int, name: str = "",
               mode: str = "") -> dict:
    """Listing-driven stats for one EpochSegmentTarget — usable from
    any process that can list the sink's store (the rw_sinks system
    table rebuilds targets from catalog options with this)."""
    staged = target.uncommitted_epochs()
    staged_bytes = sum(target.store.size(k)
                      for segs in staged.values() for _w, k in segs)
    lag = 0
    if staged:
        newest = max(staged)
        lag = max(0, int(n_writers) - len(staged[newest]))
    return {"name": name, "mode": mode or target.mode,
            "committed_epoch": target.committed_epoch(),
            "staged_epochs": len(staged),
            "staged_bytes": staged_bytes,
            "writer_lag": lag}
