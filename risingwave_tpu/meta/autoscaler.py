"""Elastic control loop: a meta-side autoscaler over the reschedule path.

ROADMAP item 3's missing piece: PR 14 built the input signals — the
``rw_bottlenecks`` walker (act only on ``sustained=1``; one-barrier
anecdotes are noise), the per-(actor, executor) utilization tricolor
and the per-MV freshness-lag series — and the domain-cohort reschedule
path already replays fusion, rewrite rules and tier caps, so a rescale
preserves every optimization. This module closes the loop: consume the
signals each serving heartbeat, decide, and drive
``Cluster.rescale_fragment`` / ``rescale_source_fragment``.

Robustness is the headline, not a rider (the PR-8 stance: an
autoscaler that can wedge a domain under fault is worse than no
autoscaler; concurrent-state discipline per arxiv 1904.03800):

- **Hysteresis.** A decision needs a *sustained* bottleneck row
  (contiguous slow-barrier streak from the walker), cross-checked
  against the live tricolor (the target fragment's actors must
  actually be busy-dominated) and the per-MV freshness-lag trend (a
  lag already recovering on its own is not scaled). Healthy domains
  produce zero decisions — the bench's q7 neighbor proof.
- **Per-MV cooldown.** After any completed action (applied OR rolled
  back) the MV is untouchable for ``cooldown_s`` — scaling decisions
  must observe their own consequences before acting again.
- **Storm gate.** Every action passes ``admit()`` (the PR-8 pattern:
  consecutive *failed* actions back off exponentially with seeded
  jitter, bounded by ``max_attempts`` → one loud refusal that disables
  the loop until an operator re-enables it). A clean round after a
  successful action closes the window; rollbacks keep it open.
- **Verify + rollback.** A rescale is not done when the RPCs return:
  the loop drives ``verify_barriers`` post-rescale rounds and rolls
  back to the prior parallelism when the rescale failed, timed out, or
  the verification rounds fail — recorded in ``rw_autoscaler`` AND
  ``rw_recovery`` (the cluster's own guarded-rescale rollback records
  there too; the two ledgers join on wall time and detail).

Every decision lands in the process-global ``AUTOSCALE_LOG`` (the
``rw_autoscaler`` system table payload) and bumps
``autoscaler_decision_total{mv,direction}`` /
``autoscaler_rollback_total{mv}``.
"""

from __future__ import annotations

import asyncio
import random
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from risingwave_tpu.utils.metrics import CLUSTER as _METRICS

# outcomes recorded in the decision ledger
OUTCOME_APPLIED = "applied"
OUTCOME_ROLLED_BACK = "rolled_back"
OUTCOME_ROLLBACK_FAILED = "rollback_failed"
OUTCOME_STORM = "storm_disabled"


def parse_autoscale(spec: str) -> bool:
    """'on'|'off' → bool (SET stream_autoscale validator)."""
    s = str(spec).strip().lower()
    if s in ("on", "true", "1"):
        return True
    if s in ("off", "false", "0"):
        return False
    from risingwave_tpu.frontend.planner import PlanError
    raise PlanError(f"stream_autoscale must be on|off, got {spec!r}")


class AutoscaleStormError(RuntimeError):
    """Consecutive failed scaling actions exhausted the bounded budget
    — the loop disables itself loudly instead of thrashing a domain
    that cannot hold a rescale."""


@dataclass
class AutoscaleEvent:
    """One decision, as recorded in the rw_autoscaler system table."""

    seq: int
    mv: str
    fragment: int                 # fragment index within the job
    operator: str                 # walker-named operator identity
    direction: str                # "up" | "down"
    from_parallelism: int
    to_parallelism: int
    outcome: str                  # applied|rolled_back|rollback_failed|…
    reason: str                   # the signal that triggered it
    epoch: int                    # committed floor at decision time
    duration_s: float             # decide → verified (or rolled back)
    detail: str = ""

    def row(self) -> tuple:
        return (self.seq, self.mv, self.fragment, self.operator,
                self.direction, self.from_parallelism,
                self.to_parallelism, self.outcome, self.reason,
                self.epoch, self.duration_s, self.detail)


# process-global decision ledger (RECOVERY_LOG shape): the autoscaler
# appends, the rw_autoscaler system table reads — bounded
AUTOSCALE_LOG: Deque[AutoscaleEvent] = deque(maxlen=1 << 12)
_SEQ = 0


def autoscaler_rows() -> List[tuple]:
    """rw_autoscaler payload: one row per recorded decision."""
    return [e.row() for e in AUTOSCALE_LOG]


def clear_autoscale_log() -> None:
    """Test isolation: the log is process-global."""
    global _SEQ
    AUTOSCALE_LOG.clear()
    _SEQ = 0


def _record(mv: str, fragment: int, operator: str, direction: str,
            from_p: int, to_p: int, outcome: str, reason: str,
            epoch: int, duration_s: float, detail: str = ""
            ) -> AutoscaleEvent:
    global _SEQ
    _SEQ += 1
    ev = AutoscaleEvent(_SEQ, mv, fragment, operator, direction,
                        from_p, to_p, outcome, reason, epoch,
                        round(duration_s, 4), detail[:200])
    AUTOSCALE_LOG.append(ev)
    _METRICS.autoscaler_decision.inc(mv=mv, direction=direction)
    if outcome in (OUTCOME_ROLLED_BACK, OUTCOME_ROLLBACK_FAILED):
        _METRICS.autoscaler_rollback.inc(mv=mv)
    return ev


@dataclass
class AutoscalerConfig:
    """Policy knobs (mechanism lives on the Cluster)."""

    max_parallelism: Optional[int] = None   # default: cluster.n
    min_parallelism: int = 1
    # hysteresis: seconds an MV is untouchable after a completed action
    cooldown_s: float = 15.0
    # post-rescale health verification rounds
    verify_barriers: int = 3
    # hard bound on one rescale's wall time (stop + handoff + redeploy)
    rescale_timeout_s: float = 120.0
    # tricolor cross-check: the target fragment's actors must average
    # at least this busy share for a scale-UP to proceed
    up_busy_mean: float = 0.30
    # scale-down: a fragment scaled above its baseline whose actors
    # stay under this busy share while its domain reports no sustained
    # bottleneck for `down_quiet_rounds` consecutive ticks shrinks by 1
    down_busy_max: float = 0.12
    down_quiet_rounds: int = 40
    # freshness cross-check: scale up only while the MV's wall lag is
    # not already recovering (last sample ≥ trend_ratio × window
    # median) or the MV publishes no freshness samples at all
    trend_ratio: float = 0.8
    # multi-step jump (ISSUE 19): a LOAD STEP (≥~4x input rate) shows
    # up as a near-saturated busy mean AND a steeply rising wall-lag
    # trend — jump +2 parallelism per decision (still ONE guarded
    # rescale, still capped) instead of walking +1 per cooldown
    # window while the backlog outruns each rung
    jump_busy_mean: float = 0.85
    jump_lag_slope: float = 2.0
    # storm gate (PR-8 admit() shape)
    max_attempts: int = 4
    backoff_s: float = 0.5
    backoff_cap_s: float = 16.0
    seed: int = 0


class _AdmitGate:
    """The PR-8 ``admit()`` pattern for scaling actions: consecutive
    FAILED actions back off exponentially with seeded jitter and a
    bounded budget; a successful, verified action closes the window.

    ``defer=True`` (the Autoscaler's mode) moves the backoff out of
    ``admit()``: the tick runs under the serving barrier lock, where a
    multi-second inline sleep would stall barrier stepping and every
    queued SELECT/ALTER — the caller spreads the same ``next_delay()``
    schedule as a not-before deadline between heartbeats instead."""

    def __init__(self, max_attempts: int, backoff_s: float,
                 backoff_cap_s: float, seed: int, sleep=asyncio.sleep,
                 defer: bool = False):
        self.max_attempts = max(1, max_attempts)
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.sleep = sleep
        self.defer = defer
        self.attempts = 0
        self._rng = random.Random(seed)

    def next_delay(self) -> float:
        """Seeded-jitter exponential backoff after ``attempts``
        consecutive failures (0 failures → no delay). THE one copy of
        the schedule — admit()'s inline sleep and the deferred
        deadline both draw from it."""
        if self.attempts < 1:
            return 0.0
        delay = min(self.backoff_s * (2 ** (self.attempts - 1)),
                    self.backoff_cap_s)
        return delay * (0.5 + self._rng.random())

    async def admit(self) -> int:
        if self.attempts >= self.max_attempts:
            raise AutoscaleStormError(
                f"autoscaler storm: {self.attempts} consecutive "
                f"failed scaling actions — disabling the loop; "
                f"investigate before re-enabling stream_autoscale")
        delay = 0.0 if self.defer else self.next_delay()
        self.attempts += 1
        if delay:
            await self.sleep(delay)
        return self.attempts

    def note_success(self) -> None:
        self.attempts = 0


class Autoscaler:
    """The control loop: signals → decision → guarded rescale →
    verify/rollback. Owned by a DistFrontend; ``tick()`` runs inside
    the serving heartbeat (under the barrier lock, so a manual ALTER
    queues behind an in-flight action instead of interleaving)."""

    def __init__(self, cluster, config: Optional[AutoscalerConfig]
                 = None, monotonic: Callable[[], float] = time.monotonic):
        self.cluster = cluster
        self.cfg = config or AutoscalerConfig()
        self.monotonic = monotonic
        self.gate = _AdmitGate(self.cfg.max_attempts,
                               self.cfg.backoff_s,
                               self.cfg.backoff_cap_s, self.cfg.seed,
                               defer=True)
        self.enabled = True
        # deferred storm-gate backoff: failed actions arm a not-before
        # deadline and tick() no-ops until it passes — the delay runs
        # BETWEEN heartbeats instead of inside the barrier lock
        self._not_before = 0.0
        # per-MV cooldown stamps (hysteresis half 2)
        self._cooldown_until: Dict[str, float] = {}
        # (mv, fragment) → parallelism when this loop first saw it —
        # scale-down never shrinks below the operator's own baseline
        self._baseline: Dict[Tuple[str, int], int] = {}
        # (mv, fragment) → consecutive quiet ticks (scale-down input)
        self._quiet: Dict[Tuple[str, int], int] = {}
        # recent per-MV wall-lag samples for the trend cross-check
        self._lag: Dict[str, Deque[float]] = {}
        # last completed action's outcome ("" = none yet): a clean
        # serving round closes the storm window only after a SUCCESS —
        # a rollback keeps the backoff armed (note_healthy contract)
        self._last_outcome = ""
        # wall durations of completed actions (the serving stall each
        # rescale cost — the bench lane's p99-during-rescale source)
        self.action_durations_s: List[float] = []

    # -- serving-loop hooks --------------------------------------------
    def note_healthy(self) -> None:
        """A barrier round committed cleanly. Closes the storm window
        only when the last action SUCCEEDED (or none ran): consecutive
        rollbacks must keep backing off even though the cluster steps
        cleanly between them — post-rollback health is the rollback
        working, not the rescale."""
        if self._last_outcome in ("", OUTCOME_APPLIED):
            self.gate.note_success()

    def reset_storm(self) -> None:
        """Operator re-enable (an explicit ``SET stream_autoscale=on``
        after a storm): clear the disabled latch AND the exhausted
        budget — a still-maxed gate would re-raise the storm on the
        next decision without attempting a single rescale."""
        self.enabled = True
        self.gate.note_success()
        self._last_outcome = ""
        self._not_before = 0.0

    # -- signal plumbing -----------------------------------------------
    async def _refresh_signals(self) -> None:
        """Pull worker-side signal snapshots (utilization tricolor +
        bottleneck walks + freshness parts) into the coordinator's
        process-global views. The walker runs per barrier inside each
        worker (the coordinator hosts no monitored actors); this merge
        is what rw_bottlenecks / rw_actor_utilization serve on the
        distributed session too."""
        # one round-trip's latency for both sweeps: the verbs hit
        # disjoint worker-side state, so they overlap safely. Light
        # drain: the decision reads utilization/bottlenecks/costs —
        # never the per-vnode topology, whose worker-side snapshot
        # walks the whole per-key map
        await asyncio.gather(self.cluster.drain_signals(light=True),
                             self.cluster.drain_freshness())
        from risingwave_tpu.stream.freshness import FRESHNESS
        for (mv, _dom, n, _e, _lag, wall_lag, _p50, _p99,
             _wp99) in FRESHNESS.rows():
            if not n or wall_lag is None:
                continue
            self._lag.setdefault(mv, deque(maxlen=32)).append(wall_lag)

    def _lag_still_rising(self, mv: str) -> bool:
        """Freshness cross-check: True unless the MV's wall lag is
        already clearly recovering (last sample under ``trend_ratio``
        of the window median). MVs with no samples pass — absence of
        the signal must not veto the walker's direct evidence."""
        window = self._lag.get(mv)
        if not window or len(window) < 4:
            return True
        ordered = sorted(window)
        median = ordered[len(ordered) // 2]
        return window[-1] >= self.cfg.trend_ratio * median

    def _step_size(self, busy_mean: float, mv: str) -> int:
        """+1 normally; +2 when the signals say LOAD STEP rather than
        drift: the fragment is saturated (busy mean ≥ jump_busy_mean)
        and the MV's wall lag is growing steeply (last sample ≥
        jump_lag_slope × window median). Under a 4x input step the +1
        ladder accumulates more backlog per cooldown window than each
        rung retires — the jump halves the rungs to reach the needed
        parallelism."""
        if busy_mean < self.cfg.jump_busy_mean:
            return 1
        window = self._lag.get(mv)
        if not window or len(window) < 4:
            return 1
        ordered = sorted(window)
        median = ordered[len(ordered) // 2]
        if median <= 0:
            return 1
        return 2 if window[-1] >= self.cfg.jump_lag_slope * median \
            else 1

    def _fragment_of_actor(self, job, actor_id: int) -> Optional[int]:
        for fi, placed in enumerate(job.placements):
            if any(aid == actor_id for aid, _slot in placed):
                return fi
        return None

    def _fragment_busy_mean(self, job_name: str, job,
                            fi: int) -> float:
        """Mean busy share across the target fragment's actors (the
        tricolor cross-check: scaling helps a fragment that is busy
        everywhere, not one with a single skewed straggler)."""
        from risingwave_tpu.stream.monitor import UTILIZATION
        best: Dict[int, float] = {}
        for (a, f, _node, _ex, _e, _i, busy, _bp,
             _idle) in UTILIZATION.rows():
            if f == job_name:
                best[a] = max(best.get(a, 0.0), busy)
        vals = [best.get(aid, 0.0)
                for aid, _slot in job.placements[fi]]
        return sum(vals) / len(vals) if vals else 0.0

    def _target_slots(self, job, fi: int, n: int) -> List[int]:
        """Derive the target slot set from the fragment's CURRENT
        placement: grow by appending unused slots round-robin, shrink
        by dropping the tail (the most recently added actors). Keeping
        the surviving actors where they are bounds the stop-the-world
        handoff to the rebalanced share — a formula-derived set could
        relocate the fragment's entire state cross-worker."""
        cur = [s for _a, s in job.placements[fi]]
        if n <= len(cur):
            return cur[:n]
        out = list(cur)
        used = set(out)
        c = (out[-1] + 1) if out else fi
        while len(out) < n:
            for k in range(self.cluster.n):
                cand = (c + k) % self.cluster.n
                if cand not in used:
                    out.append(cand)
                    used.add(cand)
                    c = cand + 1
                    break
            else:
                # parallelism past the worker count: slots repeat
                out.append(c % self.cluster.n)
                c += 1
        return out

    # -- decision ------------------------------------------------------
    def _decide(self) -> Optional[dict]:
        """At most ONE action per tick, scale-ups first (a saturated
        fragment outranks trimming an idle one)."""
        from risingwave_tpu.stream.bottleneck import BOTTLENECKS
        now = self.monotonic()
        sustained_domains = set()
        for (domain, op, fragment, actor, _node, busy, _bp, _streak,
             sustained, _epoch, diag) in BOTTLENECKS.rows():
            if not sustained or op is None:
                continue
            sustained_domains.add(domain)
            job = self.cluster.jobs.get(fragment)
            if job is None:
                continue
            if now < self._cooldown_until.get(fragment, 0.0):
                continue
            fi = self._fragment_of_actor(job, actor)
            if fi is None:
                continue                     # stale row (redeployed)
            frag = job.graph.fragments[fi]
            source_kind = self.cluster._source_rescalable(frag)
            if not source_kind and not self.cluster._rescalable(frag):
                continue                     # nothing to drive here
            cur = len(job.placements[fi])
            cap = self.cfg.max_parallelism or self.cluster.n
            if cur >= cap:
                continue
            busy_mean = self._fragment_busy_mean(fragment, job, fi)
            if busy_mean < self.cfg.up_busy_mean:
                continue                     # tricolor cross-check
            if not self._lag_still_rising(fragment):
                continue                     # freshness cross-check
            self._baseline.setdefault((fragment, fi), cur)
            step = self._step_size(busy_mean, fragment)
            to_p = min(cur + step, cap)      # bounded, ONE rescale
            reason = (f"sustained bottleneck: {diag}" if diag
                      else "sustained bottleneck")
            if to_p - cur > 1:
                reason += (f" (load step: busy {busy_mean:.0%}, "
                           f"lag slope — jump +{to_p - cur})")
            return {"mv": fragment, "fi": fi, "operator": op,
                    "direction": "up", "from_p": cur, "to_p": to_p,
                    "source": source_kind, "reason": reason}
        # scale-down sweep: fragments this loop scaled up whose demand
        # evaporated (quiet domain + idle actors for a long window)
        for (mv, fi), base in list(self._baseline.items()):
            job = self.cluster.jobs.get(mv)
            if job is None or fi >= len(job.placements):
                self._baseline.pop((mv, fi), None)
                continue
            cur = len(job.placements[fi])
            if cur <= max(base, self.cfg.min_parallelism):
                self._quiet.pop((mv, fi), None)
                continue
            dom = self.cluster.domain_of_job(mv)
            busy = self._fragment_busy_mean(mv, job, fi)
            if dom in sustained_domains or busy > self.cfg.down_busy_max:
                self._quiet[(mv, fi)] = 0
                continue
            q = self._quiet.get((mv, fi), 0) + 1
            self._quiet[(mv, fi)] = q
            if q < self.cfg.down_quiet_rounds:
                continue
            if self.monotonic() < self._cooldown_until.get(mv, 0.0):
                continue
            frag = job.graph.fragments[fi]
            return {"mv": mv, "fi": fi,
                    "operator": "", "direction": "down",
                    "from_p": cur, "to_p": cur - 1,
                    "source": self.cluster._source_rescalable(frag),
                    "reason": f"quiet {q} rounds, busy {busy:.0%}"}
        return None

    # -- the guarded action --------------------------------------------
    async def _rescale(self, job_name: str, fi: int, to_slots,
                       source: bool) -> None:
        if source:
            await self.cluster.rescale_source_fragment(
                job_name, fi, list(to_slots))
        else:
            await self.cluster.rescale_fragment(
                job_name, fi, list(to_slots))

    async def _act(self, d: dict) -> AutoscaleEvent:
        """Guarded-rescale protocol: admit → rescale (bounded) →
        verify N barriers → on ANY failure, roll back to the prior
        parallelism and record it in rw_autoscaler + rw_recovery."""
        from risingwave_tpu.meta.supervisor import (
            ACTION_ROLLBACK, CAUSE_RESCALE_FAILED,
        )
        await self.gate.admit()
        mv, fi = d["mv"], d["fi"]
        job = self.cluster.jobs[mv]
        prior_slots = [s for _a, s in job.placements[fi]]
        floor = self.cluster.store.committed_epoch()
        t0 = self.monotonic()
        outcome, detail = OUTCOME_APPLIED, ""
        try:
            await asyncio.wait_for(
                self._rescale(mv, fi,
                              self._target_slots(job, fi, d["to_p"]),
                              d["source"]),
                self.cfg.rescale_timeout_s)
            # post-rescale health verification: the rescale is done
            # when the redeployed domain holds N clean rounds, not
            # when the RPCs return
            for _ in range(self.cfg.verify_barriers):
                await self.cluster.step(1)
        except BaseException as exc:  # noqa: BLE001 — rollback path
            detail = repr(exc)[:160]
            from risingwave_tpu.cluster.scheduler import RescaleError
            already_rolled = (isinstance(exc, RescaleError)
                              and exc.rolled_back)
            # a RescaleError with rolled_back=False means the
            # CLUSTER's own unwind failed: the cohort is stopped and
            # possibly half-deployed, so a compensating rescale here
            # would no-op against the already-reverted placements and
            # MASK a wedged-idle cluster — record and re-raise so the
            # serving loop's supervised recovery redeploys (and runs
            # the pending state-placement repair)
            cluster_unrolled = (isinstance(exc, RescaleError)
                                and not exc.rolled_back)
            rolled = already_rolled
            if not already_rolled and not cluster_unrolled:
                try:
                    await asyncio.wait_for(
                        self._rescale(mv, fi, prior_slots, d["source"]),
                        self.cfg.rescale_timeout_s)
                    rolled = True
                except BaseException as rexc:  # noqa: BLE001
                    detail += f"; rollback failed: {rexc!r}"[:100]
                # the compensating rescale is an autoscaler decision,
                # not a cluster-internal unwind — record it in
                # rw_recovery so both ledgers tell the story
                self.cluster.supervisor.record(
                    CAUSE_RESCALE_FAILED, ACTION_ROLLBACK,
                    tuple(sorted(set(prior_slots))), floor,
                    self.monotonic() - t0, rolled, 1,
                    detail=f"autoscaler {mv}/f{fi}: {detail}")
            outcome = (OUTCOME_ROLLED_BACK if rolled
                       else OUTCOME_ROLLBACK_FAILED)
            if not rolled or isinstance(exc, asyncio.CancelledError):
                # broken beyond the compensating action (supervised
                # recovery owns the underlying fault), or the serving
                # task itself was cancelled mid-action — swallowing
                # the CancelledError here would make the heartbeat
                # uncancellable. Record, then re-raise.
                self._finish(d, outcome, floor, t0, detail)
                raise
        return self._finish(d, outcome, floor, t0, detail)

    def _finish(self, d: dict, outcome: str, floor: int, t0: float,
                detail: str) -> AutoscaleEvent:
        dur = self.monotonic() - t0
        self.action_durations_s.append(dur)
        self._cooldown_until[d["mv"]] = \
            self.monotonic() + self.cfg.cooldown_s
        self._last_outcome = outcome
        if outcome == OUTCOME_APPLIED:
            self.gate.note_success()
            self._quiet.pop((d["mv"], d["fi"]), None)
        else:
            # deferred storm-gate backoff (the gate's own schedule):
            # the next action waits out the window between heartbeats,
            # not under the barrier lock
            self._not_before = (self.monotonic()
                                + self.gate.next_delay())
        return _record(d["mv"], d["fi"], d["operator"], d["direction"],
                       d["from_p"], d["to_p"], outcome, d["reason"],
                       floor, dur, detail)

    async def tick(self) -> Optional[AutoscaleEvent]:
        """One control-loop round (each serving heartbeat): refresh
        signals, decide, and run at most one guarded action. Raises
        only when a failed action could not be rolled back — the
        serving loop's supervised-recovery ladder owns that."""
        if not self.enabled:
            return None
        if self.monotonic() < self._not_before:
            return None          # deferred backoff window still open
        await self._refresh_signals()
        d = self._decide()
        if d is None:
            return None
        try:
            return await self._act(d)
        except AutoscaleStormError as e:
            self.enabled = False
            self._last_outcome = OUTCOME_STORM
            return _record(d["mv"], d["fi"], d["operator"],
                           d["direction"], d["from_p"], d["to_p"],
                           OUTCOME_STORM, d["reason"],
                           self.cluster.store.committed_epoch(), 0.0,
                           str(e))
