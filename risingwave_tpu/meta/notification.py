"""Notification service: versioned meta-change broadcast.

Reference parity: src/meta/src/manager/notification.rs — observers
(frontends, compute nodes, compactors) subscribe and receive catalog /
cluster deltas with a monotone notification version; a new observer
first gets a SNAPSHOT at the current version so it never observes a
gap. TPU re-design: in-process pub/sub with per-observer asyncio
queues — the cross-process transport (the coordinator's JSON control
channel) forwards the same payloads; versioning and snapshot-then-
delta semantics live here either way.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass(frozen=True)
class Notification:
    kind: str                 # e.g. "mv_created", "worker_expired"
    payload: dict
    version: int = 0          # stamped by the service at publish


class Observer:
    """One subscription: an asyncio queue of notifications."""

    def __init__(self, observer_id: int, snapshot: List[Notification]):
        self.observer_id = observer_id
        self.queue: "asyncio.Queue[Notification]" = asyncio.Queue()
        # snapshot-then-delta: everything up to the subscribe version
        # arrives as one batch before any live notification
        self.snapshot = snapshot

    async def recv(self) -> Notification:
        return await self.queue.get()

    def try_recv(self) -> Optional[Notification]:
        try:
            return self.queue.get_nowait()
        except asyncio.QueueEmpty:
            return None


class NotificationService:
    """Versioned broadcast hub (notification.rs NotificationManager)."""

    def __init__(self, snapshot_fn: Optional[Callable[[], List[dict]]]
                 = None, history_cap: int = 1024):
        self.version = 0
        self._observers: Dict[int, Observer] = {}
        self._next_observer = 1
        # bounded history so late subscribers can be given the recent
        # deltas; a real snapshot (catalog dump) wins when provided
        self._history: List[Notification] = []
        self._history_cap = history_cap
        self._snapshot_fn = snapshot_fn

    def subscribe(self) -> Observer:
        if self._snapshot_fn is not None:
            snap = [Notification("snapshot", p, self.version)
                    for p in self._snapshot_fn()]
        else:
            snap = list(self._history)
        obs = Observer(self._next_observer, snap)
        self._next_observer += 1
        self._observers[obs.observer_id] = obs
        return obs

    def unsubscribe(self, observer_id: int) -> None:
        self._observers.pop(observer_id, None)

    def publish(self, n: Notification) -> int:
        """Stamp, record, fan out. Returns the stamped version."""
        self.version += 1
        stamped = Notification(n.kind, n.payload, self.version)
        self._history.append(stamped)
        if len(self._history) > self._history_cap:
            del self._history[:len(self._history) - self._history_cap]
        for obs in list(self._observers.values()):
            obs.queue.put_nowait(stamped)
        return self.version
