"""CompactionManager: meta-side compaction control plane.

Reference parity: src/meta/src/hummock/manager/compaction.rs + the
compaction pickers (picker/*.rs) — the meta service watches each
namespace's level topology, picks tasks with multi-level pickers
(L0→L1 overlap, size-ratio, tombstone-reclaim), freezes each task's
inputs behind a reservation (``HummockLite.reserve_task``), dispatches
the merge to a compactor executor OFF the serving path, and lands the
result as a compare-and-commit version delta
(``apply_version_delta``). Serving commits proceed concurrently — new
L0 runs simply aren't in a frozen input set.

Task recovery is lease-based, like streaming workers: an executor that
dies mid-task (SIGKILL, storage fault, torn channel) or outlives its
lease gets its task ABORTED (reservation released, any uploaded
outputs deleted — their ids stay burned) and the trigger re-picks on a
later tick. Compactor faults never touch the serving recovery ladder:
they are recorded (``CAUSE_COMPACTOR_DEAD`` → ``ACTION_REQUEUE``)
without charging the storm gate — zero serving-domain recoveries is
the chaos invariant.

Executors are pluggable per namespace (``CompactorHooks``): the
single-process session wires ``InProcessCompactor`` (a background
thread); the cluster wires the ``role="compactor"`` subprocess over
its control channel. Hooks may be sync or async — ``tick()`` awaits
what needs awaiting.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

from risingwave_tpu.utils.metrics import STORAGE as _METRICS

# -- picker thresholds --------------------------------------------------
L0_TRIGGER = 4            # L0 run count (hummock.L0_COMPACT_THRESHOLD)
SIZE_RATIO = 4            # L0 within 1/ratio of L1 bytes → early merge
TOMBSTONE_DENSITY = 0.3   # tombstones/entries in a run → reclaim rewrite


def _up(hex_key: str) -> bytes:
    """User-key prefix of a hex SST boundary (strips the 8-byte
    inverted-epoch suffix, which would mis-order comparisons)."""
    return bytes.fromhex(hex_key)[:-8]


def _overlapping(l1: List[dict], lo: bytes, hi: bytes) -> List[dict]:
    return [i for i in l1
            if not (_up(i["largest"]) < lo or _up(i["smallest"]) > hi)]


# -- pickers (pure: snapshot dict in, proto-task dict out) --------------
def pick_l0(snap: dict, threshold: int = L0_TRIGGER) -> Optional[dict]:
    """L0→L1 overlap picker: too many time-ordered L0 runs (read
    amplification — every run is a merge source on every read) →
    absorb ALL of L0 plus the overlapping L1 runs. Bottom merge: the
    destination is the terminal level, so ≤-safe tombstones drop."""
    reserved = set(snap.get("reserved") or ())
    l0 = snap.get("l0") or []
    if len(l0) < threshold or any(i["id"] in reserved for i in l0):
        return None
    lo = min(_up(i["smallest"]) for i in l0)
    hi = max(_up(i["largest"]) for i in l0)
    l1 = _overlapping(snap.get("l1") or [], lo, hi)
    if any(i["id"] in reserved for i in l1):
        return None
    return {"picker": "l0", "inputs_l0": list(l0), "inputs_l1": l1,
            "bottom": True}


def pick_size_ratio(snap: dict, ratio: int = SIZE_RATIO
                    ) -> Optional[dict]:
    """Size-ratio Ln→Ln+1 picker: the young level's bytes have grown
    to within 1/ratio of the level below — merge early, before the
    count trigger, so one giant flush cannot sit on the read path
    until three more land."""
    reserved = set(snap.get("reserved") or ())
    l0 = snap.get("l0") or []
    l1_all = snap.get("l1") or []
    if len(l0) < 2 or any(i["id"] in reserved for i in l0):
        return None
    l0_bytes = sum(i.get("size", 0) for i in l0)
    l1_bytes = sum(i.get("size", 0) for i in l1_all)
    if l1_bytes <= 0 or l0_bytes * ratio < l1_bytes:
        return None
    lo = min(_up(i["smallest"]) for i in l0)
    hi = max(_up(i["largest"]) for i in l0)
    l1 = _overlapping(l1_all, lo, hi)
    if any(i["id"] in reserved for i in l1):
        return None
    return {"picker": "size_ratio", "inputs_l0": list(l0),
            "inputs_l1": l1, "bottom": True}


def pick_tombstone(snap: dict, density: float = TOMBSTONE_DENSITY
                   ) -> Optional[dict]:
    """Tombstone-reclaim picker: rewrite a single bottom-level run
    whose delete markers exceed the density threshold — space reclaim
    with no L0 involvement. Safe as a lone-run bottom merge: L1 runs
    are key-disjoint and every L0 run is strictly newer, so a dropped
    ≤-safe tombstone can shadow nothing it should not."""
    reserved = set(snap.get("reserved") or ())
    for info in snap.get("l1") or []:
        if info["id"] in reserved:
            continue
        n = info.get("count", 0)
        if n > 0 and info.get("tombstones", 0) / n >= density:
            return {"picker": "tombstone", "inputs_l0": [],
                    "inputs_l1": [info], "bottom": True}
    return None


def pick_task(snap: dict) -> Optional[dict]:
    """Priority order: read-amp first (L0 count), then size ratio,
    then space reclaim."""
    return (pick_l0(snap) or pick_size_ratio(snap)
            or pick_tombstone(snap))


# -- task ledger (rw_compaction payload) --------------------------------
@dataclass
class CompactionTask:
    """One compaction task's lifecycle row. Mutated in place as the
    manager drives it: pending → running → applied | aborted |
    requeued | failed."""

    task_id: int
    namespace: str
    picker: str
    input_ids: List[int]
    bottom: bool = True
    state: str = "pending"
    attempts: int = 1
    safe_epoch: int = 0
    read_version: int = 0
    output_base: int = 0
    output_cap: int = 0
    outputs: List[int] = field(default_factory=list)
    bytes_read: int = 0
    bytes_written: int = 0
    duration_s: float = 0.0
    detail: str = ""

    def row(self) -> tuple:
        return (self.task_id, self.namespace, self.picker, self.state,
                ",".join(str(i) for i in self.input_ids),
                ",".join(str(i) for i in self.outputs),
                self.bytes_read, self.bytes_written, self.attempts,
                round(self.duration_s, 6), self.detail)


COMPACTION_LOG: Deque[CompactionTask] = deque(maxlen=1 << 12)
_SEQ = 0


def compaction_rows() -> List[tuple]:
    """rw_compaction payload: one row per task, current state."""
    return [t.row() for t in COMPACTION_LOG]


def clear_compaction_log() -> None:
    """Test isolation: the log is process-global."""
    global _SEQ
    COMPACTION_LOG.clear()
    _SEQ = 0


@dataclass
class CompactorHooks:
    """Per-namespace plumbing the manager drives. ``snapshot``/
    ``reserve``/``apply``/``abort`` run on the owning store (local
    calls or worker RPCs); ``execute`` dispatches the merge and
    returns a handle with done()/result() — a concurrent Future
    (thread arm) or an asyncio Task (subprocess arm)."""

    snapshot: Callable[[], object]
    reserve: Callable[[List[int], int], object]
    apply: Callable[[List[int], List[dict]], object]
    abort: Callable[[List[int], List[int]], object]
    execute: Callable[[dict], object]


async def _maybe(x):
    return await x if inspect.isawaitable(x) else x


class CompactionManager:
    """Watch level topology, pick + lease tasks, apply version deltas.

    One task in flight per namespace: compaction is a background
    hygiene loop, not a throughput race — and the single-flight rule
    makes conflict analysis trivial (a reservation can only collide
    with serving-side inline compaction, which `apply` detects as a
    compare-and-commit conflict). Requeue is re-pick: an aborted or
    expired task releases its reservation and the unchanged trigger
    fires again on a later tick with a fresh id grant."""

    def __init__(self, lease_s: float = 30.0, max_attempts: int = 5,
                 monotonic: Callable[[], float] = time.monotonic,
                 on_fault: Optional[Callable] = None):
        self.lease_s = lease_s
        self.max_attempts = max_attempts
        self.monotonic = monotonic
        # on_fault(namespace, kind, exc_or_None): the cluster wires
        # this to supervisor.record(CAUSE_COMPACTOR_DEAD, requeue) +
        # compactor respawn — NEVER through the serving storm gate
        self.on_fault = on_fault
        self.namespaces: Dict[str, CompactorHooks] = {}
        self._inflight: Dict[str, dict] = {}
        self._fails: Dict[str, int] = {}    # consecutive, per namespace
        self.applied_total = 0
        self.requeued_total = 0

    def add_namespace(self, name: str, hooks: CompactorHooks) -> None:
        self.namespaces[name] = hooks

    def remove_namespace(self, name: str) -> None:
        self.namespaces.pop(name, None)
        entry = self._inflight.pop(name, None)
        if entry is not None:
            entry["handle"].cancel()

    def inflight(self) -> Dict[str, CompactionTask]:
        return {ns: e["task"] for ns, e in self._inflight.items()}

    async def tick(self) -> dict:
        """One control round: settle finished/expired tasks, then
        dispatch new ones. Cheap when idle — a snapshot per namespace
        and no dispatch unless a picker fires."""
        applied = requeued = dispatched = 0
        for ns in list(self.namespaces):
            if ns in self._inflight:
                a, r = await self._settle(ns)
                applied += a
                requeued += r
            if ns not in self._inflight:
                dispatched += await self._maybe_dispatch(ns)
        _METRICS.compaction_pending_tasks.set(float(len(self._inflight)))
        return {"applied": applied, "requeued": requeued,
                "dispatched": dispatched,
                "inflight": len(self._inflight)}

    async def drain(self, timeout_s: float = 30.0) -> int:
        """Settle every in-flight task WITHOUT dispatching new ones —
        the graceful-shutdown path (session close, arm flip back to
        inline). Waits out running executors up to ``timeout_s``; a
        straggler is lease-expired and aborted. Returns tasks applied."""
        deadline = self.monotonic() + timeout_s
        applied = 0
        for ns in list(self._inflight):
            entry = self._inflight.get(ns)
            if entry is None:
                continue
            handle = entry["handle"]
            while not handle.done() and self.monotonic() < deadline:
                await asyncio.sleep(0.01)
            if not handle.done():
                entry["deadline"] = float("-inf")
            a, _ = await self._settle(ns)
            applied += a
        _METRICS.compaction_pending_tasks.set(float(len(self._inflight)))
        return applied

    # -- lifecycle ------------------------------------------------------
    async def _settle(self, ns: str):
        entry = self._inflight[ns]
        task: CompactionTask = entry["task"]
        handle = entry["handle"]
        hooks: CompactorHooks = entry["hooks"]
        if not handle.done():
            if self.monotonic() < entry["deadline"]:
                return 0, 0
            # lease expired: the executor is wedged or gone — abort
            # the reservation (outputs, if any, die with it) and let
            # the trigger re-pick
            handle.cancel()
            await self._abort(ns, task, hooks, "lease_expired", None)
            return 0, 1
        try:
            result = handle.result()
        except asyncio.CancelledError:
            await self._abort(ns, task, hooks, "cancelled", None)
            return 0, 1
        except BaseException as e:  # noqa: BLE001 — executor died
            await self._abort(ns, task, hooks, "executor_fault", e)
            return 0, 1
        outputs = result.get("outputs") or []
        try:
            await _maybe(hooks.apply(task.input_ids, outputs))
        except BaseException as e:  # noqa: BLE001 — CAS conflict or
            # a dead worker; either way the reservation must release
            await self._abort(ns, task, hooks, "apply_conflict", e,
                              uploaded=[i["id"] for i in outputs])
            return 0, 1
        task.state = "applied"
        task.outputs = [i["id"] for i in outputs]
        task.bytes_read = int(result.get("bytes_read", 0))
        task.bytes_written = int(result.get("bytes_written", 0))
        task.duration_s = self.monotonic() - entry["started"]
        self._inflight.pop(ns, None)
        self._fails[ns] = 0
        self.applied_total += 1
        return 1, 0

    async def _abort(self, ns: str, task: CompactionTask,
                     hooks: CompactorHooks, kind: str,
                     exc: Optional[BaseException],
                     uploaded: Optional[List[int]] = None) -> None:
        # delete the whole reserved id range: we cannot know which
        # outputs a dead executor managed to upload (ids stay burned)
        out_ids = uploaded if uploaded is not None else list(
            range(task.output_base,
                  task.output_base + task.output_cap))
        try:
            await _maybe(hooks.abort(task.input_ids, out_ids))
        except BaseException as e:  # noqa: BLE001 — the namespace
            # owner may itself be mid-recovery; vacuum_orphans cleans
            # what this abort could not
            task.detail = f"abort failed: {e!r}"
        fails = self._fails.get(ns, 0) + 1
        self._fails[ns] = fails
        task.state = ("failed" if fails >= self.max_attempts
                      else "requeued")
        task.duration_s = self.monotonic() - self._inflight[ns]["started"]
        if not task.detail:
            task.detail = kind if exc is None else f"{kind}: {exc!r}"
        self._inflight.pop(ns, None)
        self.requeued_total += 1
        if self.on_fault is not None:
            self.on_fault(ns, kind, exc)

    async def _maybe_dispatch(self, ns: str) -> int:
        global _SEQ
        hooks = self.namespaces[ns]
        try:
            snap = await _maybe(hooks.snapshot())
        except BaseException:  # noqa: BLE001 — owner unreachable
            # (mid-recovery worker): try again next tick
            return 0
        proto = pick_task(snap)
        if proto is None:
            return 0
        inputs = proto["inputs_l0"] + proto["inputs_l1"]
        input_ids = [i["id"] for i in inputs]
        # generous output grant: a merge never fans one input out to
        # more than ~2x runs (it only compresses), +8 slack
        id_block = 2 * len(inputs) + 8
        try:
            grant = await _maybe(hooks.reserve(input_ids, id_block))
        except BaseException:  # noqa: BLE001 — raced an inline
            # compact or a concurrent reservation: skip this tick
            return 0
        grant = grant.get("grant", grant)  # RPC replies nest it
        _SEQ += 1
        task = CompactionTask(
            task_id=_SEQ, namespace=ns, picker=proto["picker"],
            input_ids=input_ids, bottom=proto["bottom"],
            attempts=self._fails.get(ns, 0) + 1,
            safe_epoch=int(grant["safe_epoch"]),
            read_version=int(grant["read_version"]),
            output_base=int(grant["output_base"]),
            output_cap=int(grant["output_cap"]))
        task_dict = {
            "task_id": task.task_id,
            "inputs_l0": proto["inputs_l0"],
            "inputs_l1": proto["inputs_l1"],
            "bottom": proto["bottom"],
            "safe_epoch": task.safe_epoch,
            "output_base": task.output_base,
            "output_cap": task.output_cap,
        }
        handle = hooks.execute(task_dict)
        if inspect.isawaitable(handle):
            handle = asyncio.ensure_future(handle)
        task.state = "running"
        COMPACTION_LOG.append(task)
        self._inflight[ns] = {
            "task": task, "handle": handle, "hooks": hooks,
            "started": self.monotonic(),
            "deadline": self.monotonic() + self.lease_s,
        }
        return 1


def parse_compaction(spec: str) -> str:
    """SET storage_compaction validator: 'inline' | 'dedicated'
    (PlanError so a typo fails the SET, not a later commit)."""
    s = str(spec).strip().lower()
    if s not in ("inline", "dedicated"):
        from risingwave_tpu.frontend.planner import PlanError
        raise PlanError(
            f"storage_compaction must be 'inline' or 'dedicated', "
            f"got {spec!r}")
    return s
