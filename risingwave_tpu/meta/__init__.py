"""Meta: the control plane — barrier loop, catalog, DDL (grows per layer 10).

Reference parity: src/meta/ (GlobalBarrierManager src/meta/src/barrier/
mod.rs:128; stream manager, catalog, recovery come in later rounds).
Barrier domains + the cross-domain checkpoint plane live in
meta/domains.py (ISSUE 13).
"""

from risingwave_tpu.meta.barrier import BarrierLoop, BarrierStats
from risingwave_tpu.meta.domains import BarrierPlane, EpochAllocator

__all__ = ["BarrierLoop", "BarrierStats", "BarrierPlane",
           "EpochAllocator"]
