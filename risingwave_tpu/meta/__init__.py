"""Meta: the control plane — barrier loop, catalog, DDL (grows per layer 10).

Reference parity: src/meta/ (GlobalBarrierManager src/meta/src/barrier/
mod.rs:128; stream manager, catalog, recovery come in later rounds).
"""

from risingwave_tpu.meta.barrier import BarrierLoop, BarrierStats

__all__ = ["BarrierLoop", "BarrierStats"]
