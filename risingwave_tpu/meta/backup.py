"""Meta/storage backup & restore: consistent cluster snapshots.

Reference parity: src/meta/src/backup_restore/ — a backup captures the
meta snapshot (here: the DDL log) plus the hummock version and every
SST it references, into a self-contained prefix of an object store;
restore materializes a FRESH cluster root from a backup and a new
session recovers from it (DDL replay + state recovery, the normal boot
path). Backups are consistent by construction: the hummock version is
an immutable snapshot (SSTs are never rewritten in place — compaction
writes new objects and commits a new version), so copying CURRENT's
closure needs no quiesce.

Layout under the backup store:
    backup/<id>/MANIFEST.json   {"id", "files": [...], "version_id"}
    backup/<id>/<original path> (verbatim object copies)
"""

from __future__ import annotations

import json
from typing import List, Optional

BACKUP_PREFIX = "backup"


def _closure(obj) -> List[str]:
    """Every object a consistent snapshot needs: the CURRENT hummock
    version file, every SST path it references, and the meta DDL log."""
    files: List[str] = []
    if obj.exists("meta/ddl.json"):
        files.append("meta/ddl.json")
    if not obj.exists("meta/CURRENT"):
        return files
    files.append("meta/CURRENT")
    vid = int(obj.read("meta/CURRENT").decode())
    vpath = f"meta/v{vid}.json"
    files.append(vpath)
    v = json.loads(obj.read(vpath).decode())
    for level in ("l0", "l1"):
        for sst in v.get(level, []):
            files.append(f"data/{sst['id']}.sst")
    return files


def create_backup(obj, backup_obj=None,
                  backup_id: Optional[str] = None) -> str:
    """Copy the current consistent snapshot into the backup store
    (defaults to the same object store under ``backup/<id>/``).
    Returns the backup id."""
    backup_obj = backup_obj if backup_obj is not None else obj
    if backup_id is None:
        existing = list_backups(backup_obj)
        n = 1 + max((int(b) for b in existing if b.isdigit()),
                    default=0)
        backup_id = str(n)
    files = _closure(obj)
    base = f"{BACKUP_PREFIX}/{backup_id}"
    for path in files:
        backup_obj.upload(f"{base}/{path}", obj.read(path))
    version_id = None
    if obj.exists("meta/CURRENT"):
        version_id = int(obj.read("meta/CURRENT").decode())
    backup_obj.upload(f"{base}/MANIFEST.json", json.dumps({
        "id": backup_id, "files": files,
        "version_id": version_id}).encode())
    return backup_id


def list_backups(backup_obj) -> List[str]:
    out = set()
    for path in backup_obj.list(BACKUP_PREFIX + "/"):
        rest = path[len(BACKUP_PREFIX) + 1:]
        out.add(rest.split("/", 1)[0])
    # numeric ids sort numerically ('10' after '2'); names after
    return sorted(out, key=lambda b: (not b.isdigit(),
                                      int(b) if b.isdigit() else 0, b))


def delete_backup(backup_obj, backup_id: str) -> int:
    base = f"{BACKUP_PREFIX}/{backup_id}/"
    paths = list(backup_obj.list(base))
    for p in paths:
        backup_obj.delete(p)
    return len(paths)


def restore_backup(backup_obj, backup_id: str, target_obj) -> dict:
    """Materialize a backup into a FRESH cluster root. Refuses a
    non-empty target (restoring over live state silently merges two
    histories — the reference's restore makes the same demand)."""
    if target_obj.list(""):
        raise ValueError(
            "restore target must be empty — refusing to mix a backup "
            "into live cluster state")
    base = f"{BACKUP_PREFIX}/{backup_id}"
    manifest = json.loads(
        backup_obj.read(f"{base}/MANIFEST.json").decode())
    for path in manifest["files"]:
        target_obj.upload(path, backup_obj.read(f"{base}/{path}"))
    return manifest
