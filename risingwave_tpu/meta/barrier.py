"""The barrier/checkpoint loop: the system heartbeat.

Reference parity: src/meta/src/barrier/mod.rs:128,558,652 —
GlobalBarrierManager ticks every `barrier_interval_ms`, pairs the tick with
a scheduled command, issues the next epoch, injects the barrier at sources,
keeps at most `in_flight_barrier_nums` barriers un-collected, and on
collection commits the epoch to the state store (HummockManager::commit_epoch
analog). `checkpoint_frequency` makes only every k-th barrier durable
(BarrierKind::{Barrier,Checkpoint}).

TPU notes: barrier collection is the device sync point — an epoch completes
only after every actor flushed device state for it. The loop never blocks
data flow: injection is pipelined up to the in-flight window.
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.state.store import StateStore
from risingwave_tpu.stream.actor import LocalBarrierManager
from risingwave_tpu.stream.message import Barrier, BarrierKind, Mutation
from risingwave_tpu.utils.metrics import STREAMING


@dataclass
class BarrierStats:
    """Collected per-epoch latencies (meta barrier_latency metric analog)."""

    completed_epochs: List[int] = field(default_factory=list)
    latencies_s: List[float] = field(default_factory=list)

    def p99_latency_s(self) -> float:
        if not self.latencies_s:
            return 0.0
        xs = sorted(self.latencies_s)
        return xs[min(len(xs) - 1, int(len(xs) * 0.99))]

    def mean_latency_s(self) -> float:
        return (sum(self.latencies_s) / len(self.latencies_s)
                if self.latencies_s else 0.0)


class VirtualClock:
    """Deterministic time source (the madsim stance, SURVEY §4:
    replace time, keep the program): `sleep` advances virtual time and
    yields once so actors run — a whole barrier schedule executes
    deterministically at full speed. ``install()`` also rebinds the
    EPOCH clock (common/epoch.py), so epoch values — and thus SST keys
    and committed_epoch — are identical across runs, not wall-clock
    residue."""

    def __init__(self, start_s: float = 1_700_000_000.0) -> None:
        self.t = 0.0
        self.start_s = start_s

    def monotonic(self) -> float:
        return self.t

    def time(self) -> float:
        return self.start_s + self.t

    async def sleep(self, delay: float) -> None:
        # yield FIRST: a sleep cancelled by the barrier loop's
        # first-completed race must not have consumed its interval
        await asyncio.sleep(0)
        self.t += delay

    @contextlib.contextmanager
    def install(self):
        """Bind the global epoch clock to virtual time for the block."""
        from risingwave_tpu.common.epoch import set_clock
        prev = set_clock(self.time)
        try:
            yield self
        finally:
            set_clock(prev)


class BarrierLoop:
    """GlobalBarrierManager-lite driving one LocalBarrierManager.

    Two driving modes:
    - `run()`: background task ticking `interval_ms` on the (injectable)
      clock + sleeper — production shape on the wall clock, the
      deterministic simulation under a VirtualClock.
    - `inject_and_collect()` / `checkpoint()`: explicit stepping for tests
      and benchmarks (deterministic; no timers).
    """

    def __init__(self, local: LocalBarrierManager, store: StateStore,
                 interval_ms: int = 250, checkpoint_frequency: int = 1,
                 in_flight_barrier_nums: int = 10,
                 monotonic: Callable[[], float] = time.monotonic,
                 sleep=asyncio.sleep):
        self.local = local
        self.store = store
        self.interval_ms = interval_ms
        self.checkpoint_frequency = max(1, checkpoint_frequency)
        self.in_flight_barrier_nums = max(1, in_flight_barrier_nums)
        self.monotonic = monotonic
        self.sleep = sleep
        self.stats = BarrierStats()
        self._epoch: Optional[Epoch] = None
        self._barriers_since_checkpoint = 0
        self._inject_times: Dict[int, float] = {}
        self._in_flight: List[int] = []       # injected, not yet collected
        self._committed_epoch = 0
        self._pending_mutations: List[Mutation] = []
        self._stopped = False

    # -- command scheduling (BarrierScheduler analog) -------------------
    def schedule_mutation(self, mutation: Mutation) -> None:
        self._pending_mutations.append(mutation)

    @property
    def committed_epoch(self) -> int:
        return self._committed_epoch

    @property
    def in_flight_count(self) -> int:
        """Injected-but-uncollected barriers (drivers pipelining against
        the window should read this, not the private list)."""
        return len(self._in_flight)

    # -- one step -------------------------------------------------------
    def _next_kind(self, force_checkpoint: bool) -> BarrierKind:
        if self._epoch is None:
            return BarrierKind.INITIAL
        self._barriers_since_checkpoint += 1
        if force_checkpoint or (self._barriers_since_checkpoint
                                >= self.checkpoint_frequency):
            return BarrierKind.CHECKPOINT
        return BarrierKind.BARRIER

    async def inject(self, mutation: Optional[Mutation] = None,
                     force_checkpoint: bool = False) -> Barrier:
        """Issue the next epoch and send its barrier to source actors."""
        kind = self._next_kind(force_checkpoint)
        if self._epoch is None:
            curr = Epoch.now()
            # recovery: the initial barrier's prev is the committed epoch,
            # so state-table reads see the checkpointed data (recovery.rs)
            recovered = Epoch(self.store.committed_epoch())
            if curr.value <= recovered.value:
                curr = Epoch(recovered.value + 1)
            pair = EpochPair(curr=curr, prev=recovered)
        else:
            curr = self._epoch.next()
            pair = EpochPair(curr=curr, prev=self._epoch)
        self._epoch = curr
        if mutation is None and self._pending_mutations:
            mutation = self._pending_mutations.pop(0)
        barrier = Barrier(pair, kind, mutation)
        self._inject_times[curr.value] = self.monotonic()
        self._in_flight.append(curr.value)
        if kind.is_checkpoint:
            self._barriers_since_checkpoint = 0
        await self.local.send_barrier(barrier)
        return barrier

    def advance_epoch_to(self, value: int) -> None:
        """Reserve every epoch ≤ `value` (out-of-band bulk ingest, e.g.
        reschedule state handoff): the next barrier's curr will exceed
        it, so no in-flight flush can collide with the reserved epoch."""
        assert not self._in_flight, "advance with barriers in flight"
        if self._epoch is None or self._epoch.value < value:
            self._epoch = Epoch(value)

    async def collect_next(self) -> Barrier:
        """Await the oldest in-flight epoch; commit it to the store."""
        assert self._in_flight, "nothing in flight"
        epoch = self._in_flight.pop(0)
        barrier = await self.local.await_epoch_complete(epoch)
        # the epoch whose data this barrier flushed is the one that ENDED:
        # barrier.epoch.prev (meta commits prev_epoch — barrier/mod.rs:652).
        # The INITIAL barrier has prev=INVALID: nothing to commit yet.
        prev = barrier.epoch.prev.value
        if prev > 0:
            self.store.seal_epoch(prev, barrier.is_checkpoint)
            if barrier.is_checkpoint:
                self.store.sync(prev)
                self._committed_epoch = prev
        t0 = self._inject_times.pop(epoch, None)
        if t0 is not None:
            lat = self.monotonic() - t0
            self.stats.latencies_s.append(lat)
            STREAMING.barrier_latency.observe(lat)
        if barrier.is_checkpoint:
            STREAMING.checkpoint_count.inc()
            # host-memory accounting/eviction sweep piggybacks on the
            # checkpoint (memory_manager.rs watermark-loop analog)
            from risingwave_tpu.utils.memory import GLOBAL as _MEM
            _MEM.tick()
        self.stats.completed_epochs.append(epoch)
        return barrier

    async def inject_and_collect(
            self, mutation: Optional[Mutation] = None,
            force_checkpoint: bool = False) -> Barrier:
        await self.inject(mutation, force_checkpoint)
        # drain everything in flight, oldest first
        barrier = None
        while self._in_flight:
            barrier = await self.collect_next()
        assert barrier is not None
        return barrier

    async def checkpoint(self) -> Barrier:
        """Force a durable checkpoint barrier and wait for it."""
        return await self.inject_and_collect(force_checkpoint=True)

    # -- background loop -------------------------------------------------
    async def run(self, stop_after: Optional[int] = None) -> None:
        """Tick-inject-collect until `stop()` (or `stop_after` barriers).

        Injection and collection are pipelined: a new barrier is injected
        on schedule as long as the in-flight window has room.
        """
        n = 0
        collector = None
        interval = self.interval_ms / 1000
        next_tick = self.monotonic()      # first barrier fires immediately
        try:
            while not self._stopped and (stop_after is None
                                         or n < stop_after):
                if self.monotonic() >= next_tick:
                    # the tick schedule survives fast collections: barriers
                    # are injected at interval rate, not collection rate
                    if len(self._in_flight) < self.in_flight_barrier_nums:
                        await self.inject()
                        n += 1
                    next_tick = max(next_tick + interval, self.monotonic())
                if collector is None and self._in_flight:
                    collector = asyncio.ensure_future(self.collect_next())
                delay = max(0.0, next_tick - self.monotonic())
                sleeper = asyncio.ensure_future(self.sleep(delay))
                waits = {sleeper} | ({collector} if collector else set())
                done, _ = await asyncio.wait(
                    waits, return_when=asyncio.FIRST_COMPLETED)
                if collector in done:
                    collector.result()
                    collector = None
                if sleeper not in done:
                    sleeper.cancel()
            # drain: a running collector holds an epoch already popped from
            # _in_flight — await it too, or the last epoch never commits
            while collector is not None or self._in_flight:
                if collector is not None:
                    await collector
                    collector = None
                else:
                    await self.collect_next()
        finally:
            if collector is not None:
                collector.cancel()

    def stop(self) -> None:
        self._stopped = True
