"""The barrier/checkpoint loop: the system heartbeat.

Reference parity: src/meta/src/barrier/mod.rs:128,558,652 —
GlobalBarrierManager ticks every `barrier_interval_ms`, pairs the tick with
a scheduled command, issues the next epoch, injects the barrier at sources,
keeps at most `in_flight_barrier_nums` barriers un-collected, and on
collection commits the epoch to the state store (HummockManager::commit_epoch
analog). `checkpoint_frequency` makes only every k-th barrier durable
(BarrierKind::{Barrier,Checkpoint}).

TPU notes: barrier collection is the device sync point — an epoch completes
only after every actor flushed device state for it. The loop never blocks
data flow: injection is pipelined up to the in-flight window.
"""

from __future__ import annotations

import asyncio
import contextlib
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.state.store import StateStore
from risingwave_tpu.storage.uploader import CheckpointUploader
from risingwave_tpu.stream.actor import LocalBarrierManager
from risingwave_tpu.stream.message import Barrier, BarrierKind, Mutation
from risingwave_tpu.utils import ledger as _ledger
from risingwave_tpu.utils import spans as _spans
from risingwave_tpu.utils.failpoint import fail_point
from risingwave_tpu.utils.metrics import STREAMING, exact_quantile
from risingwave_tpu.utils.trace import GLOBAL_AWAITS


class BarrierWedgedError(RuntimeError):
    """Barrier collection exceeded the configured collect timeout —
    the wedged-barrier failure class: some participant holds the epoch
    open (a stuck executor, a starved exchange edge) without dying.
    The recovery supervisor classifies this as unrecoverable in place
    and escalates to full recovery."""


@dataclass
class BarrierStats:
    """Collected per-epoch latencies (meta barrier_latency metric
    analog). A multi-domain plane shares ONE stats object so the
    aggregate list keeps its historical meaning (bench warm-trims
    assign it in place); per-domain p99 lives on the PROFILER
    (``EpochProfiler.p99_by_domain`` — ``drop_first`` trims it in
    step with the aggregate), never here, so the two views cannot
    desync."""

    completed_epochs: List[int] = field(default_factory=list)
    latencies_s: List[float] = field(default_factory=list)

    def observe(self, latency_s: float, domain: str = "") -> None:
        self.latencies_s.append(latency_s)

    def p99_latency_s(self) -> float:
        return exact_quantile(self.latencies_s, 0.99)

    def mean_latency_s(self) -> float:
        return (sum(self.latencies_s) / len(self.latencies_s)
                if self.latencies_s else 0.0)


@dataclass
class EpochProfile:
    """One barrier's breakdown + per-actor attribution snapshot."""

    epoch: int
    kind: str                         # "barrier" | "checkpoint"
    inject_to_collect_s: float
    collect_to_commit_s: float
    in_flight: int                    # window depth at collection
    actor_rows: Dict[int, float]      # rows moved this epoch, per actor
    slowest_actor: Optional[int] = None
    slowest_actor_lag_s: float = 0.0  # first-collect → last-collect
    await_dump: str = ""              # attached only on slow barriers
    # async checkpoint tail: seal→durable-commit time (patched in by
    # the uploader when the commit lands — OVERLAPPED with younger
    # barriers, so it is deliberately NOT part of total_s) and the
    # uploading-window depth right after this epoch was submitted
    upload_s: float = 0.0
    queue_depth: int = 0
    # alignment domain that ran this barrier ("" = the global domain —
    # single-loop deployments and the stream_epoch_pipeline=off arm)
    domain: str = ""

    @property
    def total_s(self) -> float:
        return self.inject_to_collect_s + self.collect_to_commit_s

    def format(self) -> str:
        lines = [
            f"epoch {self.epoch:#x} "
            f"({self.kind}"
            f"{', domain ' + self.domain if self.domain else ''}): "
            f"inject→collect {self.inject_to_collect_s * 1e3:.2f}ms, "
            f"collect→commit {self.collect_to_commit_s * 1e3:.2f}ms, "
            f"in-flight {self.in_flight}"]
        if self.upload_s > 0.0 or self.queue_depth:
            lines.append(
                f"  async upload: {self.upload_s * 1e3:.2f}ms "
                f"(queue depth {self.queue_depth})")
        if self.slowest_actor is not None:
            lines.append(
                f"  slowest actor: {self.slowest_actor} "
                f"(+{self.slowest_actor_lag_s * 1e3:.2f}ms after "
                f"first collect)")
        if self.actor_rows:
            rows = ", ".join(f"{a}={int(n)}" for a, n in
                             sorted(self.actor_rows.items()))
            lines.append(f"  rows/actor: {rows}")
        if self.await_dump:
            lines.append("  await states at collect:")
            lines += [f"    {ln}" for ln in
                      self.await_dump.splitlines()]
        return "\n".join(lines)


class EpochProfiler:
    """Barrier-aligned metric snapshots (the attribution layer).

    At every collection the profiler diffs the per-actor row counters
    (MonitoredExecutor series), splits the barrier into inject→collect
    and collect→commit, and — when the barrier exceeds the slow
    threshold — attaches the AwaitRegistry dump plus the slowest-actor
    attribution, so a p99 outlier names its culprit instead of being
    one opaque number.
    """

    def __init__(self, slow_threshold_s: float = 1.0,
                 capacity: int = 1 << 16):
        self.slow_threshold_s = slow_threshold_s
        # bounded: profiles carry dicts and await dumps, and a 250ms
        # heartbeat would append ~345k/day unbounded. 64k epochs keep
        # rw_barrier_latency 1:1 with BarrierStats for any bench or
        # test run (they trim warmup from the front of both) while a
        # long-lived server just loses the oldest profiles.
        self.profiles: Deque[EpochProfile] = deque(maxlen=capacity)
        # baseline at profiler birth: the registry is process-global,
        # so an earlier pipeline's totals must not bleed into this
        # loop's first epoch delta
        self._last_rows: Dict[tuple, float] = {}
        self._actor_row_deltas()

    def _actor_row_deltas(self) -> Dict[int, float]:
        """Per-actor rows moved this epoch: the MAX over the actor's
        monitored executor nodes — every wrapped node counts the same
        rows flowing through, so summing would inflate by the chain
        depth; the busiest node is the actor's true data volume."""
        totals: Dict[tuple, float] = {}
        for labels, v in STREAMING.executor_rows.series():
            a = labels.get("actor")
            if a is not None:
                totals[(a, labels.get("node", ""))] = v
        per_actor: Dict[int, float] = {}
        for (a, node), v in totals.items():
            d = v - self._last_rows.get((a, node), 0.0)
            if d > 0:
                try:
                    aid = int(a)
                except ValueError:
                    continue
                per_actor[aid] = max(per_actor.get(aid, 0.0), d)
        self._last_rows = totals
        return per_actor

    def record(self, epoch: int, kind: str, inject_to_collect_s: float,
               collect_to_commit_s: float, in_flight: int,
               collect_times: Dict[int, float],
               domain: str = "") -> EpochProfile:
        prof = EpochProfile(epoch, kind, inject_to_collect_s,
                            collect_to_commit_s, in_flight,
                            self._actor_row_deltas(), domain=domain)
        if collect_times:
            slowest = max(collect_times, key=collect_times.get)
            prof.slowest_actor = slowest
            prof.slowest_actor_lag_s = (collect_times[slowest]
                                        - min(collect_times.values()))
        if prof.total_s >= self.slow_threshold_s:
            prof.await_dump = GLOBAL_AWAITS.dump()
        self.profiles.append(prof)
        STREAMING.barrier_inject_to_collect.observe(inject_to_collect_s)
        STREAMING.barrier_collect_to_commit.observe(collect_to_commit_s)
        return prof

    def drop_first(self, n: int) -> None:
        """Discard the oldest n profiles (bench warmup epochs: the
        trace-compile outliers must not masquerade as the steady-state
        p99 the same result line reports)."""
        for _ in range(min(n, len(self.profiles))):
            self.profiles.popleft()

    def rows(self) -> List[tuple]:
        """(epoch, kind, i2c, c2c, total, in_flight, slowest_actor,
        slowest_lag, upload_s, queue_depth, domain) per profiled
        barrier — the rw_barrier_latency system-table payload (new
        columns appended so existing positional consumers keep their
        indices)."""
        return [(p.epoch, p.kind, p.inject_to_collect_s,
                 p.collect_to_commit_s, p.total_s, p.in_flight,
                 p.slowest_actor, p.slowest_actor_lag_s,
                 p.upload_s, p.queue_depth, p.domain)
                for p in self.profiles]

    def p99_by_domain(self) -> Dict[str, float]:
        """Per-domain p99 barrier total over the retained profiles —
        the multi-MV bench lane's per-domain breakdown source (the
        warmup trim via ``drop_first`` applies to this view too)."""
        by: Dict[str, List[float]] = {}
        for p in self.profiles:
            by.setdefault(p.domain, []).append(p.total_s)
        return {d: exact_quantile(v, 0.99) for d, v in by.items()}

    def report(self, last_n: int = 10) -> str:
        return "\n".join(p.format()
                         for p in list(self.profiles)[-last_n:])

    def p99_breakdown(self) -> Dict[str, float]:
        """Per-phase p99 over the profiled barriers. An EMPTY deque —
        a fresh loop, or a bench whose warmup trim consumed every
        profile (drop_first(n) with n ≥ len) — yields all-zero phases,
        never an exception: bench snapshot assembly runs after exactly
        that trim and must not die on a short run."""
        profs = list(self.profiles)
        if not profs:
            return {"inject_to_collect_s": 0.0,
                    "collect_to_commit_s": 0.0, "upload_s": 0.0}
        return {
            "inject_to_collect_s": exact_quantile(
                [p.inject_to_collect_s for p in profs], 0.99),
            "collect_to_commit_s": exact_quantile(
                [p.collect_to_commit_s for p in profs], 0.99),
            # the overlapped async tail — NOT part of barrier latency;
            # reported so the overlap is visible, not invisible
            "upload_s": exact_quantile(
                [p.upload_s for p in profs], 0.99),
        }


class VirtualClock:
    """Deterministic time source (the madsim stance, SURVEY §4:
    replace time, keep the program): `sleep` advances virtual time and
    yields once so actors run — a whole barrier schedule executes
    deterministically at full speed. ``install()`` also rebinds the
    EPOCH clock (common/epoch.py), so epoch values — and thus SST keys
    and committed_epoch — are identical across runs, not wall-clock
    residue."""

    def __init__(self, start_s: float = 1_700_000_000.0) -> None:
        self.t = 0.0
        self.start_s = start_s

    def monotonic(self) -> float:
        return self.t

    def time(self) -> float:
        return self.start_s + self.t

    async def sleep(self, delay: float) -> None:
        # yield FIRST: a sleep cancelled by the barrier loop's
        # first-completed race must not have consumed its interval
        await asyncio.sleep(0)
        self.t += delay

    @contextlib.contextmanager
    def install(self):
        """Bind the global epoch clock to virtual time for the block."""
        from risingwave_tpu.common.epoch import set_clock
        prev = set_clock(self.time)
        try:
            yield self
        finally:
            set_clock(prev)


class BarrierLoop:
    """GlobalBarrierManager-lite driving one LocalBarrierManager.

    Two driving modes:
    - `run()`: background task ticking `interval_ms` on the (injectable)
      clock + sleeper — production shape on the wall clock, the
      deterministic simulation under a VirtualClock.
    - `inject_and_collect()` / `checkpoint()`: explicit stepping for tests
      and benchmarks (deterministic; no timers).
    """

    def __init__(self, local: LocalBarrierManager, store: StateStore,
                 interval_ms: int = 250, checkpoint_frequency: int = 1,
                 in_flight_barrier_nums: int = 10,
                 monotonic: Callable[[], float] = time.monotonic,
                 sleep=asyncio.sleep,
                 slow_barrier_threshold_s: float = 1.0,
                 max_uploading: int = 4,
                 collect_timeout_s: Optional[float] = None,
                 distributed: bool = False,
                 domain: str = "",
                 plane=None,
                 stats: Optional[BarrierStats] = None,
                 profiler: Optional[EpochProfiler] = None):
        self.local = local
        self.store = store
        self.interval_ms = interval_ms
        self.checkpoint_frequency = max(1, checkpoint_frequency)
        self.in_flight_barrier_nums = max(1, in_flight_barrier_nums)
        self.monotonic = monotonic
        self.sleep = sleep
        # barrier-domain membership (ISSUE 13): under a BarrierPlane
        # this loop drives ONE alignment domain — epochs mint from the
        # plane's shared allocator (globally unique, always above the
        # committed floor), barriers flow only through the domain's
        # senders/actors, the store's seal fence advances at the
        # cross-domain low watermark, and checkpoint submission is the
        # plane's (cross-domain aligned) job. With plane=None the loop
        # is exactly the historical global-lockstep engine — the
        # stream_epoch_pipeline=off oracle arm.
        self.domain = domain
        self._plane = plane
        # distributed coordinator: actor work runs in worker processes,
        # so a sealed phase record covers only coordinator-side time
        # until drain_ledger merges the workers' accumulators —
        # conservation is deferred until then (utils/ledger.py)
        self.distributed = distributed
        # None: wait forever (the historical behavior — tests that
        # step explicitly own their own timeouts). Set: a barrier that
        # fails to collect within the bound raises BarrierWedgedError
        # instead of wedging the whole control loop silently.
        self.collect_timeout_s = collect_timeout_s
        # a plane shares ONE stats/profiler across its domain loops so
        # the aggregate surfaces (bench warm-trim, rw_barrier_latency)
        # keep working; standalone loops own theirs as before
        self.stats = stats if stats is not None else BarrierStats()
        self.profiler = profiler if profiler is not None \
            else EpochProfiler(slow_barrier_threshold_s)
        self._epoch: Optional[Epoch] = None
        self._barriers_since_checkpoint = 0
        self._inject_times: Dict[int, float] = {}
        self._in_flight: List[int] = []       # injected, not yet collected
        self._committed_epoch = store.committed_epoch()
        self._pending_mutations: List[Mutation] = []
        self._stopped = False
        # async checkpoint pipeline: collect_next only seals + submits;
        # epochs commit in order when their uploads land. The sealed-
        # but-uncommitted window (`uploading_count`) is bounded by
        # max_uploading — submit back-pressures, collection stalls,
        # the in-flight window fills, injection stops: total staging is
        # bounded by in_flight_barrier_nums + max_uploading epochs.
        if plane is not None:
            # ONE checkpoint pipeline per store: domains share the
            # plane's uploader (the imm drain is cumulative — two
            # uploaders on one store would race each other's builds),
            # and submission happens only at cross-domain aligned
            # checkpoints (the plane's decoupled cadence).
            self.uploader = plane.uploader
        else:
            self.uploader = CheckpointUploader(
                store, max_uploading=max_uploading, monotonic=monotonic,
                on_commit=self._on_epoch_committed)
        self._upload_profiles: Dict[int, EpochProfile] = {}
        # previous epoch's collect stamp (wall monotonic): the phase
        # ledger starts each epoch's conservation interval here, so
        # pipelined in-flight barriers PARTITION wall time instead of
        # overlapping — time queued behind an older epoch belongs to
        # that epoch's books, not to this one's as `unattributed`.
        # (rw_barrier_latency keeps the overlapping inject→collect
        # semantics: queueing IS part of user-visible latency.)
        self._last_seal_stamp: Optional[float] = None

    # -- command scheduling (BarrierScheduler analog) -------------------
    def schedule_mutation(self, mutation: Mutation) -> None:
        self._pending_mutations.append(mutation)

    @property
    def committed_epoch(self) -> int:
        if self._plane is not None:
            return self.store.committed_epoch()
        return self._committed_epoch

    def frontier_epoch(self) -> int:
        """The newest epoch this loop issued (0 before the first
        barrier) — reschedule/state-handoff paths read this instead of
        poking the private cursor."""
        return self._epoch.value if self._epoch is not None else 0

    @property
    def in_flight_count(self) -> int:
        """Injected-but-uncollected barriers (drivers pipelining against
        the window should read this, not the private list)."""
        return len(self._in_flight)

    @property
    def uploading_count(self) -> int:
        """Sealed-but-uncommitted checkpoint epochs (the async upload
        window alongside in_flight)."""
        return self.uploader.depth

    def _on_epoch_committed(self, epoch: int, upload_s: float) -> None:
        """Uploader commit callback — epochs arrive strictly in order,
        so committed_epoch never skips past an unfinished older one."""
        self._committed_epoch = epoch
        prof = self._upload_profiles.pop(epoch, None)
        if prof is not None:
            prof.upload_s = upload_s
            if _spans.enabled():
                # the async checkpoint tail (seal→durable commit),
                # overlapped with younger barriers — traced under the
                # barrier that SEALED it so the overlap is visible
                _spans.EPOCH_TRACER.record(
                    "checkpoint.upload", "upload", epoch=prof.epoch,
                    start_s=time.time() - upload_s, dur_s=upload_s,
                    committed_epoch=epoch)

    # -- one step -------------------------------------------------------
    def _next_kind(self, force_checkpoint: bool) -> BarrierKind:
        if self._epoch is None:
            return BarrierKind.INITIAL
        if self._plane is not None:
            # decoupled cadence: the plane alone decides when a durable
            # checkpoint happens (a cross-domain aligned event); plain
            # domain barriers never auto-promote on a local counter
            return (BarrierKind.CHECKPOINT if force_checkpoint
                    else BarrierKind.BARRIER)
        self._barriers_since_checkpoint += 1
        if force_checkpoint or (self._barriers_since_checkpoint
                                >= self.checkpoint_frequency):
            return BarrierKind.CHECKPOINT
        return BarrierKind.BARRIER

    async def inject(self, mutation: Optional[Mutation] = None,
                     force_checkpoint: bool = False) -> Barrier:
        """Issue the next epoch and send its barrier to source actors."""
        kind = self._next_kind(force_checkpoint)
        if self._plane is not None:
            # shared allocator: globally-unique, monotone epochs above
            # the committed floor — concurrent domains can never mint
            # colliding epoch values or write under the seal fence
            curr = self._plane.allocator.allocate(self.domain)
            prev = self._epoch if self._epoch is not None \
                else Epoch(self.store.committed_epoch())
            pair = EpochPair(curr=curr, prev=prev)
        elif self._epoch is None:
            curr = Epoch.now()
            # recovery: the initial barrier's prev is the committed epoch,
            # so state-table reads see the checkpointed data (recovery.rs)
            recovered = Epoch(self.store.committed_epoch())
            if curr.value <= recovered.value:
                curr = Epoch(recovered.value + 1)
            pair = EpochPair(curr=curr, prev=recovered)
        else:
            curr = self._epoch.next()
            pair = EpochPair(curr=curr, prev=self._epoch)
        self._epoch = curr
        if mutation is None and self._pending_mutations:
            mutation = self._pending_mutations.pop(0)
        barrier = Barrier(pair, kind, mutation)
        # epoch-causal trace root: every span of this barrier round
        # (actor processing, exchange edges, dispatches, commit) parents
        # here. Dispatch spans recorded between barriers attribute to
        # the newest injected epoch (utils/spans.py docstring).
        _spans.set_current_epoch(curr.value)
        if _spans.enabled():
            root = _spans.EPOCH_TRACER.record(
                "barrier.inject", "barrier", epoch=curr.value,
                kind=kind.value)
            _spans.EPOCH_TRACER.set_root(curr.value, root)
        self._inject_times[curr.value] = self.monotonic()
        self._in_flight.append(curr.value)
        STREAMING.barrier_in_flight.set(len(self._in_flight))
        if kind.is_checkpoint:
            self._barriers_since_checkpoint = 0
        if self._plane is not None:
            sender_ids, expected = self._plane.scope(self.domain)
            await self.local.send_barrier(barrier,
                                          sender_ids=sender_ids,
                                          expected=expected)
        else:
            await self.local.send_barrier(barrier)
        return barrier

    def advance_epoch_to(self, value: int) -> None:
        """Reserve every epoch ≤ `value` (out-of-band bulk ingest, e.g.
        reschedule state handoff): the next barrier's curr will exceed
        it, so no in-flight flush can collide with the reserved epoch."""
        assert not self._in_flight, "advance with barriers in flight"
        if self._plane is not None:
            self._plane.allocator.reserve_to(value)
        if self._epoch is None or self._epoch.value < value:
            self._epoch = Epoch(value)

    async def _await_complete_or_upload_failure(self, epoch: int
                                                ) -> Barrier:
        """Race epoch completion against a terminal uploader failure,
        so a dead checkpoint pipeline fails the barrier promptly — and
        as the ORIGINAL error (e.g. the object store's OSError), not a
        later symptom."""
        self.uploader.bind_loop()
        waiter = asyncio.ensure_future(
            self.local.await_epoch_complete(epoch))
        failer = asyncio.ensure_future(self.uploader.failed.wait())
        timer = (asyncio.ensure_future(
            self.sleep(self.collect_timeout_s))
            if self.collect_timeout_s is not None else None)
        waits = {waiter, failer} | ({timer} if timer else set())
        try:
            done, _ = await asyncio.wait(
                waits, return_when=asyncio.FIRST_COMPLETED)
        except asyncio.CancelledError:
            waiter.cancel()
            raise
        finally:
            failer.cancel()
            if timer is not None:
                timer.cancel()
        if waiter in done:
            return waiter.result()
        waiter.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await waiter
        if timer is not None and timer in done:
            # wedged-barrier detection: the epoch is still collectible
            # by a retry (await_epoch_complete is cancellation-safe),
            # but the supervisor treats the wedge as terminal in place
            raise BarrierWedgedError(
                f"barrier collect for epoch {epoch:#x} exceeded "
                f"{self.collect_timeout_s}s — wedged barrier")
        self.uploader.raise_if_failed()
        raise RuntimeError("uploader failure event without a failure")

    async def collect_next(self) -> Barrier:
        """Await the oldest in-flight epoch; seal it and hand the flush
        to the checkpoint uploader. SST build and object-store upload
        run OFF this path — the commit lands asynchronously, in epoch
        order, once the uploads are durable (uploader.rs:567 analog)."""
        assert self._in_flight, "nothing in flight"
        # a failed upload fails the barrier here, after its retries
        self.uploader.raise_if_failed()
        epoch = self._in_flight.pop(0)
        barrier = await self._await_complete_or_upload_failure(epoch)
        t_collect = self.monotonic()
        # ledger-test seam: a sleep spec here lands inside the commit
        # half of the measured interval as wall time NO phase can
        # claim — the conservation residual must surface it as
        # `unattributed`
        fail_point("barrier.collect")
        STREAMING.barrier_in_flight.set(len(self._in_flight))
        # the epoch whose data this barrier flushed is the one that ENDED:
        # barrier.epoch.prev (meta commits prev_epoch — barrier/mod.rs:652).
        # The INITIAL barrier has prev=INVALID: nothing to commit yet.
        prev = barrier.epoch.prev.value
        if prev > 0:
            if self._plane is not None:
                # domain epochs interleave globally: the store's seal
                # fence may only advance at the cross-domain low
                # watermark (an eager per-domain seal would fence out
                # a sibling domain's still-open epoch)
                self._plane.allocator.note_ended(
                    prev, barrier.is_checkpoint)
            else:
                self.store.seal_epoch(prev, barrier.is_checkpoint)
        t0 = self._inject_times.pop(epoch, None)
        prof = None
        seal_rec = None
        seal_interval = None
        if t0 is not None:
            lat = self.monotonic() - t0
            self.stats.observe(lat, self.domain)
            STREAMING.barrier_latency.observe(lat)
            collect_times = self.local.take_collect_times(epoch)
            prof = self.profiler.record(
                epoch,
                "checkpoint" if barrier.is_checkpoint else "barrier",
                inject_to_collect_s=t_collect - t0,
                collect_to_commit_s=self.monotonic() - t_collect,
                in_flight=len(self._in_flight),
                collect_times=collect_times,
                domain=self.domain)
            if _spans.enabled():
                now = time.time()
                _spans.EPOCH_TRACER.record(
                    "barrier.collect", "barrier", epoch=epoch,
                    start_s=now - prof.total_s,
                    dur_s=prof.inject_to_collect_s,
                    in_flight=prof.in_flight,
                    **({"domain": self.domain} if self.domain else {}))
                _spans.EPOCH_TRACER.record(
                    "barrier.commit", "commit", epoch=epoch,
                    start_s=now - prof.collect_to_commit_s,
                    dur_s=prof.collect_to_commit_s, kind=prof.kind,
                    **({"domain": self.domain} if self.domain else {}))
                if prof.total_s >= self.profiler.slow_threshold_s:
                    # slow-barrier watchdog: the flight ring rolls in
                    # EPOCH_WINDOW barriers — promote the outlier's
                    # full trace into the retained store NOW, with its
                    # one-line straggler attribution
                    diag = _spans.EPOCH_TRACER.diagnose(
                        epoch, prof.total_s)
                    _spans.EPOCH_TRACER.promote(epoch, diag,
                                                prof.total_s)
                    print(f"slow barrier: {diag}", file=sys.stderr)
            if _ledger.enabled():
                # seal the epoch's phase books against the measured
                # interval (residual → unattributed, metrics history
                # row, Perfetto phase lanes). Virtual-clock loops
                # DISCARD instead: the simulated interval and the
                # wall-clock phases live on different clocks, so a
                # conservation check there would be noise
                if self.monotonic is time.monotonic:
                    # the conservation interval ends when the LAST
                    # actor collected (its wall stamp), not when this
                    # coroutine got scheduled — the wake gap is event-
                    # loop time during which actors already run the
                    # NEXT epoch's pulls, which the ledger rightly
                    # attributes to the next epoch. It STARTS at the
                    # previous epoch's collect stamp when that is
                    # later than this inject (pipelined injection:
                    # queueing behind an older epoch is that epoch's
                    # wall time, already on its books).
                    t_true = max(collect_times.values(),
                                 default=t_collect)
                    start = t0 if self._last_seal_stamp is None \
                        else max(t0, self._last_seal_stamp)
                    interval = max(0.0, t_true - start) \
                        + prof.collect_to_commit_s
                    # the next epoch's books open where this one's
                    # close — AFTER the commit half, which this
                    # interval already claims (a stall there must not
                    # land on two epochs' books)
                    self._last_seal_stamp = \
                        t_true + prof.collect_to_commit_s
                    seal_interval = interval
                    seal_rec = _ledger.LEDGER.seal(
                        epoch, interval, prof.kind,
                        # remote pseudo-actors ⇒ actor work ran in
                        # other processes: conservation defers to the
                        # drain_ledger merge (auto-detected so bare
                        # coordinator loops in tests behave too)
                        distributed=self.distributed
                        or self.local.has_remote_participants(),
                        # mutation barriers (deploy/stop/reschedule)
                        # do topology work no phase claims — exempt
                        warmup=barrier.mutation is not None,
                        domain=self.domain)
                else:
                    _ledger.LEDGER.discard(epoch)
            # bottleneck walk (ISSUE 14): one candidate per domain per
            # barrier off the just-published utilization tricolor,
            # cross-checked against the sealed phase record. Wall-clock
            # loops only — virtual-clock ratios would be meaningless.
            from risingwave_tpu.stream import monitor as _monitor
            if _monitor.TRICOLOR and barrier.mutation is None \
                    and self.monotonic is time.monotonic:
                # mutation barriers (deploy/stop/reschedule) do
                # topology work, not epoch work — walking them would
                # reset every streak right before a teardown report
                from risingwave_tpu.stream.bottleneck import BOTTLENECKS
                fragments = None
                if self._plane is not None:
                    jobs = self._plane.jobs_of_domain(self.domain)
                    fragments = set(jobs) if jobs else None
                BOTTLENECKS.observe(
                    epoch=epoch, domain=self.domain,
                    interval_s=(seal_interval
                                if seal_interval is not None
                                else prof.total_s),
                    phase_seconds=(seal_rec.seconds
                                   if seal_rec is not None else None),
                    fragments=fragments)
        if prev > 0 and barrier.is_checkpoint:
            if self._plane is not None:
                # checkpoint durability is a CROSS-DOMAIN aligned
                # event: this loop only reports its sealed prev; the
                # plane submits ONE floor epoch to the shared uploader
                # once every domain of the round has collected
                self._plane.note_checkpoint_sealed(self.domain, prev,
                                                   prof)
            else:
                if prof is not None:
                    # registered BEFORE submit: the inline fallback
                    # commits inside submit and patches upload_s right
                    # away
                    self._upload_profiles[prev] = prof
                if not await self.uploader.submit(prev):
                    # no flush needed (recovery-initial epoch): drop
                    # the registration or it pins the profile forever
                    self._upload_profiles.pop(prev, None)
                if prof is not None:
                    prof.queue_depth = self.uploader.depth
        if barrier.is_checkpoint:
            STREAMING.checkpoint_count.inc()
            # host-memory accounting/eviction sweep piggybacks on the
            # checkpoint (memory_manager.rs watermark-loop analog)
            from risingwave_tpu.utils.memory import GLOBAL as _MEM
            _MEM.tick()
            # topology two-book recount (armed by the tier-1 gate
            # fixture only — a no-op in production) and the per-MV
            # state-bytes gauge refresh both ride the checkpoint:
            # state only moves at checkpoints
            from risingwave_tpu.state.topology import TOPOLOGY
            from risingwave_tpu.stream.costs import COSTS
            TOPOLOGY.checkpoint_verify()
            COSTS.publish_state_bytes()
        self.stats.completed_epochs.append(epoch)
        return barrier

    async def inject_and_collect(
            self, mutation: Optional[Mutation] = None,
            force_checkpoint: bool = False,
            drain_uploader: bool = True) -> Barrier:
        await self.inject(mutation, force_checkpoint)
        # drain everything in flight, oldest first
        barrier = None
        while self._in_flight:
            barrier = await self.collect_next()
        assert barrier is not None
        # explicit stepping keeps its synchronous contract: the barrier
        # this returns is DURABLY committed (tests/DDL read
        # committed_epoch right after). Background heartbeats pass
        # drain_uploader=False — a periodic driver that drained every
        # beat would re-serialize the pipeline it exists to overlap —
        # and pipelined drivers use inject()/collect_next() directly,
        # draining only at the end.
        if drain_uploader:
            await self.uploader.drain()
        return barrier

    async def checkpoint(self) -> Barrier:
        """Force a durable checkpoint barrier and wait for it — the
        uploader is drained, so every collected epoch has committed."""
        return await self.inject_and_collect(force_checkpoint=True)

    # -- background loop -------------------------------------------------
    async def run(self, stop_after: Optional[int] = None) -> None:
        """Tick-inject-collect until `stop()` (or `stop_after` barriers).

        Injection and collection are pipelined: a new barrier is injected
        on schedule as long as the in-flight window has room.
        """
        n = 0
        collector = None
        interval = self.interval_ms / 1000
        next_tick = self.monotonic()      # first barrier fires immediately
        try:
            while not self._stopped and (stop_after is None
                                         or n < stop_after):
                if self.monotonic() >= next_tick:
                    # the tick schedule survives fast collections: barriers
                    # are injected at interval rate, not collection rate
                    if len(self._in_flight) < self.in_flight_barrier_nums:
                        await self.inject()
                        n += 1
                    next_tick = max(next_tick + interval, self.monotonic())
                if collector is None and self._in_flight:
                    collector = asyncio.ensure_future(self.collect_next())
                delay = max(0.0, next_tick - self.monotonic())
                sleeper = asyncio.ensure_future(self.sleep(delay))
                waits = {sleeper} | ({collector} if collector else set())
                done, _ = await asyncio.wait(
                    waits, return_when=asyncio.FIRST_COMPLETED)
                if collector in done:
                    collector.result()
                    collector = None
                if sleeper not in done:
                    sleeper.cancel()
            # drain: a running collector holds an epoch already popped from
            # _in_flight — await it too, or the last epoch never commits
            while collector is not None or self._in_flight:
                if collector is not None:
                    await collector
                    collector = None
                else:
                    await self.collect_next()
            # and the async tail: uploads still in flight at stop()
            # must land (in order) before run() returns, or the last
            # collected epochs never commit
            await self.uploader.drain()
        finally:
            if collector is not None:
                collector.cancel()

    def stop(self) -> None:
        self._stopped = True
