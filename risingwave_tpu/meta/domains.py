"""Barrier domains: per-fragment alignment + cross-domain checkpoints.

The pipelined-epoch redesign (ISSUE 13; ROADMAP item 2a — the
Hazelcast-Jet stance of arxiv 2103.10169 that p99 is a pipeline-
occupancy problem): the deployed actor graph is partitioned into
independent **alignment domains** by dataflow reachability — jobs that
share actors, chain edges, MV dependencies or a source stay joined;
everything else gets its own domain. Each domain runs its own
``BarrierLoop`` (own epoch cursor, own in-flight window), so a slow
fragment's barrier holds only its own domain instead of every actor in
the deployment, while **checkpoint barriers stay a cross-domain aligned
event on their own cadence** — durability no longer forces the global
lockstep that plain barriers just escaped.

Three mechanisms keep the shared store honest under concurrent epochs:

- **Shared epoch allocation.** All domains mint epochs from ONE
  monotone ``EpochAllocator``, so epoch values are globally unique,
  globally ordered, and always above the committed floor. A domain's
  barrier pair is consecutive *within its domain*; across domains the
  values interleave.
- **Low-watermark sealing.** The store's seal fence (`seal_epoch`) is
  a single watermark: writes at or below it are rejected and imms
  drain cumulatively. A per-domain eager seal would fence out a
  sibling domain's still-open epoch, so the allocator advances the
  fence only to the **cross-domain low watermark** — the largest epoch
  below every outstanding (allocated-but-unfinished) epoch.
- **Aligned checkpoint submission.** ONE checkpoint uploader serves
  the store. At a checkpoint round every domain injects a CHECKPOINT
  barrier; once all domains collected, everything at or below
  ``min(outstanding) - 1`` is sealed, and the plane submits that floor
  as one epoch to the async uploader. Recovery therefore aligns every
  domain to the same committed floor — each rebuilt domain's initial
  barrier recovers ``prev = committed``.

The ``stream_epoch_pipeline=off`` arm bypasses this module entirely
(one plain ``BarrierLoop``), reproducing the historical global
lockstep bit-identically as the oracle.
"""

from __future__ import annotations

import asyncio
import bisect
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from risingwave_tpu.common.epoch import Epoch
from risingwave_tpu.meta.barrier import (
    BarrierLoop, BarrierStats, EpochProfile, EpochProfiler,
)
from risingwave_tpu.storage.uploader import CheckpointUploader
from risingwave_tpu.stream.message import Barrier, Mutation, StopMutation


def parse_epoch_pipeline(spec: str) -> bool:
    """'on'|'off' → bool (SET stream_epoch_pipeline validator)."""
    s = str(spec).strip().lower()
    if s in ("on", "true", "1"):
        return True
    if s in ("off", "false", "0"):
        return False
    from risingwave_tpu.frontend.planner import PlanError
    raise PlanError(
        f"stream_epoch_pipeline must be on|off, got {spec!r}")


class EpochAllocator:
    """Shared monotone epoch source + low-watermark seal gate.

    ``allocate`` hands out globally-unique epoch values (physical time
    when it advances, +1 sequence otherwise — the epoch.rs shape) and
    tracks them as *outstanding* until the domain that owns them
    reports the epoch ended (its successor barrier collected, so no
    more writes can land there). The store's seal fence advances to
    ``min(outstanding) - 1`` — the largest epoch no open writer can
    still touch."""

    def __init__(self, store):
        self.store = store
        committed = int(store.committed_epoch())
        self._last = committed
        self._sealed = max(committed,
                           int(getattr(store, "_sealed_epoch", 0) or 0))
        self._outstanding: List[int] = []      # sorted, allocated+open
        self._domain_of: Dict[int, str] = {}
        # merge re-anchoring: absorbed domains' frontier epochs end
        # together with the target frontier that superseded them
        # (their last writes flush during the first merged round)
        self._end_with: Dict[int, List[int]] = {}

    # -- allocation ----------------------------------------------------
    def allocate(self, domain: str = "") -> Epoch:
        e = Epoch.now()
        v = max(e.value, self._last + 1)
        self._last = v
        bisect.insort(self._outstanding, v)
        self._domain_of[v] = domain
        return Epoch(v)

    def reserve_to(self, value: int) -> None:
        """Burn every epoch ≤ value (out-of-band bulk ingest)."""
        if value > self._last:
            self._last = value

    def domain_of(self, value: int) -> Optional[str]:
        return self._domain_of.get(value)

    # -- lifecycle -----------------------------------------------------
    def note_ended(self, value: int, is_checkpoint: bool = False) -> None:
        """The epoch's writes are complete (its successor barrier
        collected). Unknown values — recovered/committed prevs that
        were never allocated here — are ignored."""
        for alias in self._end_with.pop(value, ()):
            self._pop(alias)
        if self._pop(value):
            self._advance_seal(is_checkpoint)

    def _pop(self, value: int) -> bool:
        i = bisect.bisect_left(self._outstanding, value)
        if i < len(self._outstanding) and self._outstanding[i] == value:
            self._outstanding.pop(i)
            self._domain_of.pop(value, None)
            return True
        return False

    def alias_end(self, value: int, with_value: int) -> None:
        """End ``value`` together with ``with_value`` (domain merge:
        the absorbed frontier's last writes flush during the first
        merged barrier round, which ends ``with_value``)."""
        if value == with_value:
            return
        self._end_with.setdefault(with_value, []).append(value)

    def write_floor(self) -> int:
        """Largest epoch no open writer can still touch."""
        return (self._outstanding[0] - 1) if self._outstanding \
            else self._last

    def _advance_seal(self, is_checkpoint: bool) -> None:
        floor = self.write_floor()
        if floor > self._sealed:
            self._sealed = floor
            self.store.seal_epoch(floor, is_checkpoint)

    def outstanding(self) -> List[int]:
        return list(self._outstanding)


class _Domain:
    """One alignment domain: its loop + member bookkeeping."""

    __slots__ = ("name", "loop", "senders", "expected", "actors",
                 "jobs", "rounds_since_checkpoint")

    def __init__(self, name: str, loop: BarrierLoop):
        self.name = name
        self.loop = loop
        self.senders: Set[int] = set()     # barrier-sender actor ids
        self.expected: Set[int] = set()    # collection-expected ids
        self.actors: Set[int] = set()      # every actor id (routing)
        self.jobs: Set[str] = set()
        # pipelined-driver cadence counter (the facade inject()/drive
        # paths promote every k-th injection to a checkpoint barrier;
        # aligned rounds use the plane-global counter instead)
        self.rounds_since_checkpoint = 0


class BarrierPlane:
    """Per-domain barrier engine with cross-domain checkpoint cadence.

    Exposes the ``BarrierLoop`` driving surface (``inject_and_collect``
    / ``inject`` / ``collect_next`` / ``stats`` / ``profiler`` /
    ``uploader`` / ``committed_epoch``) so sessions, benches and tests
    that held a loop hold a plane unchanged. Plain rounds run every
    domain CONCURRENTLY — a slow domain's collect no longer serializes
    its neighbors' rounds — and every ``checkpoint_frequency``-th round
    (or any forced/mutation round) is an aligned checkpoint."""

    def __init__(self, local, store,
                 checkpoint_frequency: int = 1,
                 in_flight_barrier_nums: int = 10,
                 slow_barrier_threshold_s: float = 1.0,
                 max_uploading: int = 4,
                 collect_timeout_s: Optional[float] = None,
                 distributed: bool = False,
                 monotonic: Callable[[], float] = time.monotonic):
        self.local = local
        self.store = store
        self.monotonic = monotonic
        # a plane in the process means domain merges can monotonely
        # re-anchor live chains — state tables must accept prev > curr
        # (sticky: the strict guard returns only in plane-free procs)
        from risingwave_tpu.state.state_table import (
            allow_monotone_reanchor,
        )
        allow_monotone_reanchor(True)
        self.allocator = EpochAllocator(store)
        self.stats = BarrierStats()
        self.profiler = EpochProfiler(slow_barrier_threshold_s)
        self.slow_barrier_threshold_s = slow_barrier_threshold_s
        self.uploader = CheckpointUploader(
            store, max_uploading=max_uploading, monotonic=monotonic,
            on_commit=self._on_epoch_committed)
        self.checkpoint_frequency = max(1, checkpoint_frequency)
        self.in_flight_barrier_nums = max(1, in_flight_barrier_nums)
        self.collect_timeout_s = collect_timeout_s
        self.distributed = distributed
        self._domains: Dict[str, _Domain] = {}
        self._job_domain: Dict[str, str] = {}
        self._job_keys: Dict[str, Set[str]] = {}
        self._job_members: Dict[str, Tuple[Set[int], Set[int],
                                           Set[int]]] = {}
        self._key_owner: Dict[str, str] = {}
        self._rounds_since_checkpoint = 0
        # domain → (sealed prev, profile) of checkpoint barriers whose
        # durability submission is still pending; consumed by
        # _maybe_submit once the write floor covers them
        self._pending_ckpt: Dict[str, Tuple[int,
                                            Optional[EpochProfile]]] = {}
        self._upload_profiles: Dict[int, List[EpochProfile]] = {}
        self._submitted = int(store.committed_epoch())
        # distributed hook: awaited with the aligned floor BEFORE the
        # coordinator watermark advances (the Cluster fans seal_sync
        # out to every worker here, so the floor is durable everywhere
        # before recovery could ever trust it)
        self.aligned_hook = None

    # -- BarrierLoop-compatible surface --------------------------------
    @property
    def committed_epoch(self) -> int:
        return self.store.committed_epoch()

    @property
    def in_flight_count(self) -> int:
        return max((d.loop.in_flight_count
                    for d in self._domains.values()), default=0)

    @property
    def uploading_count(self) -> int:
        return self.uploader.depth

    def frontier_epoch(self) -> int:
        return max([self.allocator._last]
                   + [d.loop.frontier_epoch()
                      for d in self._domains.values()])

    def advance_epoch_to(self, value: int) -> None:
        """Reserve every epoch ≤ value in the shared allocator. Unlike
        the single-loop version this must NOT touch domain cursors: a
        live domain's frontier epoch still has flushes pending, and
        overwriting the cursor would orphan it in the outstanding set
        — the write floor (and with it every later commit) would
        freeze below the leaked epoch forever."""
        for d in self._domains.values():
            assert not d.loop.in_flight_count, \
                "advance with barriers in flight"
        self.allocator.reserve_to(value)

    def advance_domain_to(self, domain: str, value: int) -> None:
        """Pin one domain's cursor past out-of-band committed epochs
        (reschedule state handoff: the redeployed domain's first
        barrier must READ at/above the handoff ingest epochs, which
        land above the coordinator's committed floor). A redeployed
        job may have joined a LIVE shared domain (sibling jobs on the
        same source): the live frontier still has the siblings'
        pending flushes, so it ends together with the advanced epoch
        (the next barrier's prev) rather than being orphaned in the
        outstanding set."""
        loop = self._domains[domain].loop
        assert not loop.in_flight_count, \
            "advance with barriers in flight"
        f = loop.frontier_epoch()
        if 0 < f < value:
            self.allocator.alias_end(f, value)
        self.allocator.reserve_to(value)
        loop.advance_epoch_to(value)

    @property
    def last_allocated(self) -> int:
        return self.allocator._last

    # -- domain membership ---------------------------------------------
    def scope(self, domain: str) -> Tuple[Optional[Sequence[int]],
                                          Optional[Sequence[int]]]:
        """(sender_ids, expected) for one domain's barriers — what its
        loop passes to ``LocalBarrierManager.send_barrier``."""
        d = self._domains.get(domain)
        if d is None:
            return (), ()
        return sorted(d.senders), sorted(d.expected)

    def domains(self) -> List[str]:
        return list(self._domains)

    def domain_of_job(self, job: str) -> Optional[str]:
        return self._job_domain.get(job)

    def jobs_of_domain(self, domain: str) -> List[str]:
        """Jobs aligned in one domain (the reschedule path stops and
        redeploys a domain's whole cohort together)."""
        d = self._domains.get(domain)
        return sorted(d.jobs) if d is not None else []

    def domain_actors(self, domain: str) -> Set[int]:
        d = self._domains.get(domain)
        return set(d.actors) if d is not None else set()

    def set_domain_channel(self, domain: str,
                           sender_ids: Sequence[int]) -> None:
        """Distributed wiring (cluster/scheduler.py): a domain's
        barriers flow through per-domain worker channels — one pseudo
        actor per (domain, slot) — rather than per-job source senders.
        Replaces the domain's sender/expected sets wholesale."""
        d = self._domains[domain]
        d.senders = set(sender_ids)
        d.expected = set(sender_ids)
        d.actors |= set(sender_ids)

    def _new_loop(self, name: str) -> BarrierLoop:
        return BarrierLoop(
            self.local, self.store,
            in_flight_barrier_nums=self.in_flight_barrier_nums,
            slow_barrier_threshold_s=self.slow_barrier_threshold_s,
            collect_timeout_s=self.collect_timeout_s,
            distributed=self.distributed,
            monotonic=self.monotonic,
            domain=name, plane=self,
            stats=self.stats, profiler=self.profiler)

    def _ensure_default(self) -> _Domain:
        """Zero-job sessions still heartbeat: a default domain with no
        members collects trivially (the legacy zero-actor shape)."""
        if not self._domains:
            self._domains[""] = _Domain("", self._new_loop(""))
        return next(iter(self._domains.values()))

    def assign_job(self, job: str, keys: Sequence[str],
                   sender_ids: Sequence[int],
                   expected_ids: Sequence[int],
                   actor_ids: Optional[Sequence[int]] = None) -> str:
        """Place one deployed job into its alignment domain.

        ``keys`` are the job's reachability anchors (its own name, its
        source names, its MV dependencies). Any existing domain owning
        one of the keys absorbs the job; keys spanning several domains
        merge them (dataflow turned out to be connected after all).
        Returns the domain id."""
        keys = set(keys) | {job}
        owners = {self._key_owner[k] for k in keys
                  if k in self._key_owner}
        owners = {o for o in owners if o in self._domains}
        if not owners:
            name = job
            # never collide with a live domain name (job names are
            # unique in the catalog, but a default "" domain exists)
            while name in self._domains:
                name += "+"
            d = self._domains[name] = _Domain(name, self._new_loop(name))
        elif len(owners) == 1:
            d = self._domains[next(iter(owners))]
        else:
            d = self._merge(sorted(owners))
        senders = set(sender_ids)
        expected = set(expected_ids)
        actors = set(actor_ids) if actor_ids is not None else set()
        d.senders |= senders
        d.expected |= expected
        d.actors |= senders | expected | actors
        d.jobs.add(job)
        self._job_domain[job] = d.name
        self._job_keys[job] = keys
        self._job_members[job] = (senders, expected,
                                  actors | senders | expected)
        for k in keys:
            self._key_owner[k] = d.name
        # a lone empty default domain is superseded by the first real
        # one (it never flowed data; dropping it keeps rounds tight)
        empty = self._domains.get("")
        if empty is not None and not empty.jobs \
                and len(self._domains) > 1:
            self._retire("")
        return d.name

    def _merge(self, names: List[str]) -> _Domain:
        """Collapse several live domains into one. The survivor is the
        domain with the LARGEST epoch frontier: after the merge its
        next barrier carries ``prev = max frontier``, which every
        absorbed chain's state tables accept (monotone re-anchor —
        state_table.commit's ``prev >= curr`` contract) while their
        final writes land at their old frontiers, still under the seal
        fence until the first merged round ends them."""
        doms = [self._domains[n] for n in names]
        for d in doms:
            assert not d.loop.in_flight_count, \
                f"domain merge with barriers in flight in {d.name!r}"
        target = max(doms, key=lambda d: d.loop.frontier_epoch())
        t_front = target.loop.frontier_epoch()
        for d in doms:
            if d is target:
                continue
            f = d.loop.frontier_epoch()
            # survivor selection guarantees the target carries the
            # max frontier, so an absorbed f > 0 implies t_front >= f
            assert f <= t_front, (f, t_front)
            if 0 < f < t_front:
                self.allocator.alias_end(f, t_front)
            target.senders |= d.senders
            target.expected |= d.expected
            target.actors |= d.actors
            target.jobs |= d.jobs
            for j in d.jobs:
                self._job_domain[j] = target.name
            del self._domains[d.name]
        for j, ks in self._job_keys.items():
            if self._job_domain.get(j) == target.name:
                for k in ks:
                    self._key_owner[k] = target.name
        return target

    def remove_job(self, job: str) -> None:
        """Drop one job's members; retire its domain when empty (the
        frontier epoch is released so the seal fence never waits on a
        dead domain)."""
        name = self._job_domain.pop(job, None)
        self._job_keys.pop(job, None)
        members = self._job_members.pop(job, None)
        if name is None or name not in self._domains:
            return
        d = self._domains[name]
        d.jobs.discard(job)
        if members is not None:
            senders, expected, actors = members
            d.senders -= senders
            d.expected -= expected
            d.actors -= actors
        if not d.jobs:
            self._retire(name)
        self._rebuild_key_owner()

    def _retire(self, name: str) -> None:
        d = self._domains.pop(name, None)
        if d is None:
            return
        assert not d.loop.in_flight_count, \
            f"retiring domain {name!r} with barriers in flight"
        f = d.loop.frontier_epoch()
        if f > 0:
            # the stop barrier collected ⇒ its actors flushed and
            # terminated: nothing can write at the frontier anymore
            self.allocator.note_ended(f)

    def _rebuild_key_owner(self) -> None:
        self._key_owner = {}
        for j, ks in self._job_keys.items():
            dom = self._job_domain.get(j)
            if dom is not None:
                for k in ks:
                    self._key_owner[k] = dom

    # -- checkpoint plumbing -------------------------------------------
    def note_checkpoint_sealed(self, domain: str, prev: int,
                               prof: Optional[EpochProfile]) -> None:
        """A domain collected its checkpoint barrier of the current
        aligned round (called from its loop's collect path)."""
        self._pending_ckpt[domain] = (prev, prof)

    def _on_epoch_committed(self, epoch: int, upload_s: float) -> None:
        profs = self._upload_profiles.pop(epoch, [])
        for prof in profs:
            prof.upload_s = upload_s
        from risingwave_tpu.utils import spans as _spans
        if _spans.enabled() and profs:
            _spans.EPOCH_TRACER.record(
                "checkpoint.upload", "upload", epoch=profs[0].epoch,
                start_s=time.time() - upload_s, dur_s=upload_s,
                committed_epoch=epoch)

    async def _maybe_submit(self) -> None:
        """Submit the durability floor to the shared uploader once a
        sealed checkpoint is covered by it. After an aligned round the
        floor covers every domain's prev; under pipelined per-domain
        checkpoint driving it covers them as sibling windows drain —
        either way ONE floor epoch rides the uploader, and everything
        at or below it is sealed by construction."""
        floor = self.allocator.write_floor()
        if floor <= max(self.store.committed_epoch(), self._submitted):
            return
        covered = [d for d, (prev, _p) in self._pending_ckpt.items()
                   if prev <= floor]
        if not covered:
            return
        profs = [p for p in (self._pending_ckpt.pop(d)[1]
                             for d in covered) if p is not None]
        self._submitted = floor
        if self.aligned_hook is not None:
            # distributed: the floor becomes durable on every worker
            # BEFORE the coordinator watermark can advance to it
            await self.aligned_hook(floor)
        self._upload_profiles[floor] = profs
        if not await self.uploader.submit(floor):
            self._upload_profiles.pop(floor, None)
        else:
            depth = self.uploader.depth
            for p in profs:
                p.queue_depth = depth

    # -- rounds --------------------------------------------------------
    def _route_mutation(self, mutation: Optional[Mutation]
                        ) -> Dict[str, Optional[Mutation]]:
        """Which domains carry the mutation. Stop barriers ride only
        the domains owning the stopped actors (a foreign domain must
        not wait on actors it never drives); pause/resume and everything
        else broadcast."""
        doms = list(self._domains.values())
        if isinstance(mutation, StopMutation):
            out = {}
            for d in doms:
                hit = bool(d.actors & mutation.actors) \
                    or bool(d.expected & mutation.actors)
                out[d.name] = mutation if hit else None
            if not any(out.values()) and doms:
                # unknown actors (e.g. pure pseudo-actor stop sets):
                # broadcast rather than silently dropping the command
                out = {d.name: mutation for d in doms}
            return out
        return {d.name: mutation for d in doms}

    async def _domain_round(self, d: _Domain,
                            mutation: Optional[Mutation],
                            force_checkpoint: bool) -> Barrier:
        await d.loop.inject(mutation, force_checkpoint)
        barrier = None
        while d.loop.in_flight_count:
            barrier = await d.loop.collect_next()
        assert barrier is not None
        return barrier

    async def _gather_rounds(self, routed: Dict[str,
                                                Optional[Mutation]],
                             force_checkpoint: bool) -> Barrier:
        tasks = [self._domain_round(self._domains[n], m,
                                    force_checkpoint)
                 for n, m in routed.items() if n in self._domains]
        results = await asyncio.gather(*tasks, return_exceptions=True)
        barrier = None
        failure = None
        for r in results:
            if isinstance(r, BaseException):
                failure = failure or r
            else:
                barrier = r
        if failure is not None:
            raise failure
        assert barrier is not None
        return barrier

    async def inject_and_collect(
            self, mutation: Optional[Mutation] = None,
            force_checkpoint: bool = False,
            drain_uploader: bool = True) -> Barrier:
        """One barrier round. Plain rounds run per-domain concurrently;
        forced/mutation rounds — and every ``checkpoint_frequency``-th
        plain round — align every domain on a checkpoint."""
        self._ensure_default()
        checkpoint = force_checkpoint or mutation is not None
        if not checkpoint:
            self._rounds_since_checkpoint += 1
            if self._rounds_since_checkpoint >= self.checkpoint_frequency:
                checkpoint = True
        if checkpoint:
            self._rounds_since_checkpoint = 0
            # drain stragglers a pipelining driver may have left in
            # domain windows: an aligned round starts clean
            for d in self._domains.values():
                while d.loop.in_flight_count:
                    await d.loop.collect_next()
            routed = self._route_mutation(mutation)
            barrier = await self._gather_rounds(routed,
                                                force_checkpoint=True)
            await self._maybe_submit()
        else:
            routed = {d.name: None for d in self._domains.values()}
            barrier = await self._gather_rounds(routed,
                                                force_checkpoint=False)
        if drain_uploader:
            await self.uploader.drain()
        return barrier

    async def checkpoint(self) -> Barrier:
        return await self.inject_and_collect(force_checkpoint=True)

    # -- pipelined driving (bench/tests) -------------------------------
    def _cadence_checkpoint(self, d: _Domain,
                            force_checkpoint: bool) -> bool:
        """Per-domain checkpoint cadence for pipelined injection:
        every ``checkpoint_frequency``-th barrier of a domain is a
        checkpoint even without global alignment — the floor-based
        submit makes unaligned checkpoint prevs durable as sibling
        windows drain, so pipelined drivers keep the same durability
        cadence the single-loop engine had (frequency 1 = every
        barrier, the historical default)."""
        if force_checkpoint:
            d.rounds_since_checkpoint = 0
            return True
        d.rounds_since_checkpoint += 1
        if d.rounds_since_checkpoint >= self.checkpoint_frequency:
            d.rounds_since_checkpoint = 0
            return True
        return False

    async def inject(self, mutation: Optional[Mutation] = None,
                     force_checkpoint: bool = False) -> Barrier:
        """Widen every domain's in-flight window by one barrier (the
        pipelined-driver facade: ``while in_flight < W: inject`` keeps
        every domain's window full). Checkpoint cadence applies
        per-domain."""
        self._ensure_default()
        barrier = None
        for d in self._domains.values():
            barrier = await d.loop.inject(
                mutation, self._cadence_checkpoint(d, force_checkpoint))
        assert barrier is not None
        return barrier

    async def collect_next(self) -> Barrier:
        """Collect the oldest in-flight barrier of EVERY domain that
        has one, concurrently — the pipelined driver's collect step."""
        pending = [d.loop.collect_next()
                   for d in self._domains.values()
                   if d.loop.in_flight_count]
        assert pending, "nothing in flight"
        results = await asyncio.gather(*pending,
                                       return_exceptions=True)
        barrier = None
        failure = None
        for r in results:
            if isinstance(r, BaseException):
                failure = failure or r
            else:
                barrier = r
        if failure is not None:
            raise failure
        assert barrier is not None
        # pipelined checkpoint driving (inject(force_checkpoint=True)
        # + collect_next) must still reach durability
        await self._maybe_submit()
        return barrier

    async def drive(self, done_fn: Callable[[], bool],
                    in_flight: int = 2,
                    max_epochs_per_domain: int = 500,
                    progress_fn: Optional[Callable[[], object]] = None
                    ) -> int:
        """Drive every domain INDEPENDENTLY until ``done_fn()``: each
        domain keeps its own window full and collects at its own pace —
        the intra-plane overlap a shared round-robin driver cannot
        express (a fast domain ticks at its own rate while a slow
        neighbor's epoch is still in flight). ``progress_fn`` (e.g.
        total source rows) resets the per-domain stall guard whenever
        it changes: an exhausted domain idling while a sibling still
        works is not a stall. Returns barriers driven."""
        self._ensure_default()
        total = [0]
        progress = [progress_fn() if progress_fn is not None else None]

        async def pump(d: _Domain) -> None:
            injected = 0
            while not done_fn():
                if progress_fn is not None:
                    p = progress_fn()
                    if p != progress[0]:
                        progress[0] = p
                        injected = 0
                if injected >= max_epochs_per_domain:
                    raise RuntimeError(
                        f"domain {d.name!r}: sources stalled after "
                        f"{injected} epochs without progress")
                t0 = time.perf_counter()
                while d.loop.in_flight_count < max(1, in_flight):
                    await d.loop.inject(
                        force_checkpoint=self._cadence_checkpoint(
                            d, False))
                    injected += 1
                await d.loop.collect_next()
                await self._maybe_submit()
                total[0] += 1
                if time.perf_counter() - t0 < 0.002:
                    # exhausted domain: its sources are drained and
                    # rounds collect trivially — idle instead of
                    # busy-spinning the shared event loop (which would
                    # both steal CPU from working siblings and flood
                    # the stats with junk sub-millisecond epochs)
                    await asyncio.sleep(0.01)
            while d.loop.in_flight_count:
                await d.loop.collect_next()
                await self._maybe_submit()
                total[0] += 1

        results = await asyncio.gather(
            *(pump(d) for d in list(self._domains.values())),
            return_exceptions=True)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return total[0]

    # -- introspection --------------------------------------------------
    def p99_by_domain(self) -> Dict[str, float]:
        return self.profiler.p99_by_domain()

    def describe(self) -> List[dict]:
        """One dict per domain (bench/result surfaces and tests)."""
        return [{
            "domain": d.name,
            "jobs": sorted(d.jobs),
            "actors": len(d.actors),
            "frontier_epoch": d.loop.frontier_epoch(),
            "in_flight": d.loop.in_flight_count,
        } for d in self._domains.values()]
