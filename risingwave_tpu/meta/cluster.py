"""ClusterManager: worker membership with heartbeat expiry.

Reference parity: src/meta/src/manager/cluster.rs — add_worker_node /
heartbeat (:312) and the expiry check loop (:360-400) that deletes
workers whose heartbeat lapses beyond ``max_heartbeat_interval`` and
notifies observers. TPU re-design notes: membership is a meta-side
map keyed by worker id; expiry drives the coordinator's failure
handling (a dead worker's pipelines re-deploy from committed state —
the recovery path the two-node tests already exercise). Time comes
from an injectable clock so expiry is deterministic under the
VirtualClock test harness.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from risingwave_tpu.meta.notification import (
    Notification, NotificationService,
)


@dataclass
class WorkerNode:
    worker_id: int
    host: str
    port: int
    started_at: float
    last_heartbeat: float
    # opaque worker-reported info (parallelism, resource summary)
    info: dict = field(default_factory=dict)


class ClusterManager:
    """Membership + heartbeat liveness (cluster.rs analog)."""

    def __init__(self, max_heartbeat_interval_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 notifications: Optional[NotificationService] = None):
        self.max_interval = max_heartbeat_interval_s
        self.clock = clock
        self.notifications = notifications
        self._workers: Dict[int, WorkerNode] = {}
        self._next_id = 1

    # -- membership -------------------------------------------------------
    def add_worker(self, host: str, port: int,
                   info: Optional[dict] = None) -> WorkerNode:
        now = self.clock()
        w = WorkerNode(self._next_id, host, port, now, now,
                       dict(info or {}))
        self._next_id += 1
        self._workers[w.worker_id] = w
        if self.notifications:
            self.notifications.publish(Notification(
                "worker_added", {"worker_id": w.worker_id,
                                 "host": host, "port": port}))
        return w

    def remove_worker(self, worker_id: int) -> bool:
        w = self._workers.pop(worker_id, None)
        if w is None:
            return False
        if self.notifications:
            self.notifications.publish(Notification(
                "worker_removed", {"worker_id": worker_id}))
        return True

    def heartbeat(self, worker_id: int,
                  info: Optional[dict] = None) -> bool:
        """Refresh a worker's lease; False if it was already expired
        (the worker must re-register — cluster.rs heartbeat returns
        WorkerNotFound the same way)."""
        w = self._workers.get(worker_id)
        if w is None:
            return False
        w.last_heartbeat = self.clock()
        if info:
            w.info.update(info)
        return True

    def workers(self) -> List[WorkerNode]:
        return list(self._workers.values())

    # -- expiry (cluster.rs:360 check loop body) --------------------------
    def expire_stale(self) -> List[WorkerNode]:
        """Evict workers whose heartbeat lapsed; returns the evicted.
        Callers run this on their own cadence (the coordinator ticks it
        per barrier round; tests tick a VirtualClock)."""
        now = self.clock()
        dead = [w for w in self._workers.values()
                if now - w.last_heartbeat > self.max_interval]
        for w in dead:
            del self._workers[w.worker_id]
            if self.notifications:
                self.notifications.publish(Notification(
                    "worker_expired", {"worker_id": w.worker_id}))
        return dead
