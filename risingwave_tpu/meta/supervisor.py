"""RecoverySupervisor: failure classification + graduated recovery.

Reference parity: src/meta/src/barrier/recovery.rs — recovery as a
first-class control loop (SURVEY #39, #52: epoch rollback + rebuild),
not a crash. The meta service detects a failed barrier round,
classifies it, and drives the cheapest response that restores the
invariants, with bounded retries so a persistent fault dies loudly
instead of looping a recovery storm.

The detection→classify→respond ladder (cheapest rung first):

1. ABSORB (below this module): transient faults never reach the
   supervisor — object-store ops retry with jittered backoff
   (``RetryingObjectStore``), idempotent worker-control RPCs
   reconnect a desynced channel and retry (``WorkerClient.
   call_idempotent``), the SST uploader retries PUTs. Metrics:
   ``object_store_retry_total`` / ``rpc_retry_total``; recovery_total
   does NOT move.
2. RESPAWN: dead worker subprocesses restart over their namespaces;
   LIVE workers reset in place (actors dropped, staged state
   discarded, jit caches kept warm) and rejoin through the existing
   ``recover_store`` handshake — process restarts only where a
   process actually died.
3. FULL: kill-and-redeploy every slot (the old total response), now
   reserved for faults that poison whole-cluster state: a wedged
   barrier (collect timeout), a storage fault past its retries, or an
   unclassifiable failure.

Every recovery is admitted through a storm gate: consecutive
recoveries back off exponentially (jitter from a seeded PRNG — the
madsim stance: chaos runs are reproducible) and a bounded attempt
budget turns a recovery loop into one loud ``RecoveryStormError``.
A completed recovery appends a ``RecoveryEvent`` to the process-global
``RECOVERY_LOG`` (the ``rw_recovery`` system table payload), bumps
``recovery_total{cause,action}`` / ``recovery_duration_seconds``, and
leaves a ``recovery.*`` span chain in the epoch trace recorder.
"""

from __future__ import annotations

import asyncio
import random
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, List, Optional, Sequence, Tuple

from risingwave_tpu.utils import spans as _spans
from risingwave_tpu.utils.metrics import CLUSTER as _METRICS

# -- failure causes (the classifier's output vocabulary) ----------------
CAUSE_DEAD_WORKER = "dead_worker"        # subprocess gone / lease expired
CAUSE_WORKER_DESYNC = "worker_desync"    # alive, but control channel torn
CAUSE_STORAGE_FAULT = "storage_fault"    # object-store error past retries
CAUSE_WEDGED_BARRIER = "wedged_barrier"  # collect exceeded its timeout
CAUSE_WORKER_FAULT = "worker_fault"      # worker-side executor/plan error
CAUSE_UNKNOWN = "unknown"

CAUSE_RESCALE_FAILED = "rescale_failed"  # guarded rescale unwound

# compactor-role faults (dedicated compaction, ISSUE 19): a dead or
# lease-expired compactor costs a TASK, never a serving domain —
# recorded via record() directly, NEVER admitted through the storm
# gate (the gate budgets serving recoveries; background hygiene must
# not spend it)
CAUSE_COMPACTOR_DEAD = "compactor_dead"

# -- graduated responses ------------------------------------------------
ACTION_RESPAWN = "respawn"   # restart dead slots, reset live ones in place
ACTION_FULL = "full"         # kill-and-redeploy every slot
ACTION_ROLLBACK = "rollback"  # rescale reverted to the prior topology
ACTION_REQUEUE = "requeue"   # compaction task aborted + re-picked

# causes a respawn (rung 2) can repair; everything else escalates to
# full recovery (rung 3)
_RESPAWNABLE = frozenset({CAUSE_DEAD_WORKER, CAUSE_WORKER_DESYNC})


class RecoveryStormError(RuntimeError):
    """The bounded recovery budget is exhausted — the fault persists
    across recoveries and the cluster must stop serving, loudly,
    rather than loop kill-and-redeploy forever."""


@dataclass
class RecoveryEvent:
    """One recovery, as recorded in the rw_recovery system table."""

    seq: int
    cause: str
    action: str
    workers: Tuple[int, ...]      # slots restarted/reset by the response
    epoch: int                    # committed floor recovered to
    duration_s: float             # detection → cluster serving again
    ok: bool
    attempt: int                  # consecutive-recovery counter (1-based)
    detail: str = ""

    def row(self) -> tuple:
        return (self.seq, self.cause, self.action,
                ",".join(str(w) for w in self.workers), self.epoch,
                self.duration_s, int(self.ok), self.attempt,
                self.detail)


# process-global event log (EPOCH_TRACER shape): the supervisor appends,
# the rw_recovery system table reads — bounded, oldest dropped
RECOVERY_LOG: Deque[RecoveryEvent] = deque(maxlen=1 << 12)
_SEQ = 0


def recovery_rows() -> List[tuple]:
    """rw_recovery payload: one row per recorded recovery event."""
    return [e.row() for e in RECOVERY_LOG]


def clear_recovery_log() -> None:
    """Test isolation: the log is process-global."""
    global _SEQ
    RECOVERY_LOG.clear()
    _SEQ = 0


def _exc_chain(exc: BaseException) -> List[BaseException]:
    """The exception plus its __cause__/__context__ ancestry (bounded):
    a barrier failure surfaces as RuntimeError('actor failure during
    epoch …') FROM the ConnectionError that actually names the fault."""
    out: List[BaseException] = []
    seen = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen and len(out) < 16:
        out.append(cur)
        seen.add(id(cur))
        cur = cur.__cause__ or cur.__context__
    return out


class RecoverySupervisor:
    """Classify failures, gate recoveries, and record the outcome.

    The supervisor owns POLICY (what kind of fault, which rung, how
    many attempts); the cluster owns MECHANISM (how to respawn or
    redeploy). ``note_healthy()`` after a clean barrier round resets
    the consecutive-attempt counter, so the budget bounds recovery
    *storms*, not total recoveries over a long-lived server."""

    def __init__(self, max_attempts: int = 5, backoff_s: float = 0.25,
                 backoff_cap_s: float = 8.0, seed: int = 0,
                 sleep=asyncio.sleep,
                 monotonic: Callable[[], float] = time.monotonic):
        self.max_attempts = max(1, max_attempts)
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.sleep = sleep
        self.monotonic = monotonic
        self.attempts = 0            # consecutive, reset on note_healthy
        self._rng = random.Random(seed)

    # -- detection → classification ------------------------------------
    def classify(self, exc: BaseException,
                 dead_workers: Sequence[int] = ()) -> str:
        """Name the failure class. ``dead_workers`` (slots whose
        subprocess is gone or whose heartbeat lease expired) dominates:
        a dead worker explains every downstream symptom."""
        if dead_workers:
            return CAUSE_DEAD_WORKER
        from risingwave_tpu.meta.barrier import BarrierWedgedError
        chain = _exc_chain(exc)
        for e in chain:
            if isinstance(e, BarrierWedgedError):
                return CAUSE_WEDGED_BARRIER
        for e in chain:
            # ConnectionError/TimeoutError subclass OSError — check the
            # channel faults before the storage bucket
            if isinstance(e, (ConnectionError, TimeoutError,
                              asyncio.TimeoutError)):
                return CAUSE_WORKER_DESYNC
        for e in chain:
            if isinstance(e, (OSError, IOError)):
                return CAUSE_STORAGE_FAULT
        for e in chain:
            # a worker-side failure crosses the control channel as
            # RuntimeError("worker error: <repr>") — sniff the repr for
            # the original class
            msg = str(e)
            if "worker error" in msg:
                if ("OSError" in msg or "IOError" in msg
                        or "FileNotFoundError" in msg):
                    return CAUSE_STORAGE_FAULT
                return CAUSE_WORKER_FAULT
        return CAUSE_UNKNOWN

    @staticmethod
    def action_for(cause: str) -> str:
        return ACTION_RESPAWN if cause in _RESPAWNABLE else ACTION_FULL

    # -- storm gate -----------------------------------------------------
    async def admit(self, cause: str) -> int:
        """Admit one recovery attempt: raises RecoveryStormError past
        the consecutive budget, otherwise sleeps the jittered
        exponential backoff (attempt 1 is immediate — the first
        recovery after a healthy period must not add latency) and
        returns the 1-based attempt number."""
        if self.attempts >= self.max_attempts:
            raise RecoveryStormError(
                f"recovery storm: {self.attempts} consecutive "
                f"recoveries without a healthy barrier round (latest "
                f"cause: {cause}) — refusing to loop; fix the fault")
        self.attempts += 1
        if self.attempts > 1:
            delay = min(self.backoff_s * (2 ** (self.attempts - 2)),
                        self.backoff_cap_s)
            # full jitter (0.5–1.5×): concurrent supervisors recovering
            # against one shared fault domain must not stampede; the
            # seeded PRNG keeps a chaos replay's timing reproducible
            await self.sleep(delay * (0.5 + self._rng.random()))
        return self.attempts

    def note_healthy(self) -> None:
        """A barrier round committed cleanly: the storm window closes."""
        self.attempts = 0

    # -- outcome --------------------------------------------------------
    def record(self, cause: str, action: str,
               workers: Sequence[int], epoch: int, duration_s: float,
               ok: bool, attempt: int, detail: str = ""
               ) -> RecoveryEvent:
        """Append the event to RECOVERY_LOG + metrics + trace spans."""
        global _SEQ
        _SEQ += 1
        ev = RecoveryEvent(_SEQ, cause, action, tuple(workers), epoch,
                           duration_s, ok, attempt, detail)
        RECOVERY_LOG.append(ev)
        _METRICS.recovery_total.inc(cause=cause, action=action)
        _METRICS.recovery_duration.observe(duration_s)
        return ev


def trace_recovery_root(cause: str, action: str, epoch: int,
                        attempt: int) -> Optional[int]:
    """Open the recovery.* span chain under the recovered-to epoch —
    the causal trace a post-mortem walks from rw_recovery into
    rw_epoch_trace. Returns the root span id (None when tracing is
    off); phases record children with parent=root."""
    if not _spans.enabled():
        return None
    return _spans.EPOCH_TRACER.record(
        "recovery.supervised", "recovery", epoch=epoch,
        cause=cause, action=action, attempt=attempt)


def trace_recovery_phase(name: str, epoch: int, parent: Optional[int],
                         start_s: float, dur_s: float, **args) -> None:
    """One recovery phase span (recovery.respawn / recovery.reset /
    recovery.handshake / recovery.redeploy), parented to the root."""
    if not _spans.enabled():
        return
    _spans.EPOCH_TRACER.record(
        f"recovery.{name}", "recovery", epoch=epoch, parent=parent,
        start_s=start_s, dur_s=dur_s, **args)
