"""Host-side utilities (reference: src/utils/* grab-bag crates)."""
