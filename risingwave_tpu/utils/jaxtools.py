"""JAX runtime knobs shared by bench/driver entry points.

The stateful kernels compile one XLA program per (table capacity, chunk
rows) shape pair; growth doublings therefore trigger a handful of
compiles per process lifetime. The persistent compilation cache makes
those a one-time cost per machine instead of per run — on a tunneled
TPU a single kernel compile is ~0.5-1s, so a cold bench run would
otherwise spend most of its wall clock in the compiler.

``fetch``: measured on the tunneled v5e, a plain blocking device→host
read (``np.asarray`` / ``int()`` on a jax array) costs 70ms-40s(!)
regardless of size, while ``copy_to_host_async()`` followed by the same
read costs ~0.1ms once the transfer has landed. EVERY device read in
this codebase must go through fetch()/fetch_async — a stray bare
``np.asarray`` on the hot path costs three orders of magnitude.
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def shard_map(f, **kw):
    """``jax.shard_map`` across jax versions: a top-level alias only in
    newer jax; the pinned 0.4.x exposes it under
    ``jax.experimental.shard_map`` with the replication check named
    ``check_rep`` instead of ``check_vma``."""
    import jax

    try:
        return jax.shard_map(f, **kw)
    except AttributeError:  # pragma: no cover - depends on installed jax
        from jax.experimental import shard_map as _esm

        kw["check_rep"] = kw.pop("check_vma", True)
        return _esm.shard_map(f, **kw)


def instrumented_jit(fn, label: str | None = None, **jit_kw):
    """``jax.jit`` with (re)trace visibility: the wrapper's Python body
    runs only while jax TRACES it — once per new input shape bucket —
    so each execution of the hook is exactly one compile event. It
    lands in ``stream_kernel_recompile_count{kernel=label}`` and as a
    compile span in the current epoch's trace (utils/spans.py), making
    warmup compiles and steady-state shape-churn recompiles visible
    instead of silent multi-second stalls. Steady state pays nothing:
    jit dispatches the cached executable without entering the body."""
    import functools

    import jax

    name = label or getattr(fn, "__name__", "kernel")

    @functools.wraps(fn)
    def traced(*a, **k):
        from risingwave_tpu.utils.spans import note_compile
        note_compile(name)
        return fn(*a, **k)

    return jax.jit(traced, **jit_kw)


def enable_compilation_cache(path: str | None = None) -> str:
    """Point JAX's persistent compilation cache at a repo-local dir."""
    import jax

    cache_dir = path or os.environ.get("RW_TPU_JAX_CACHE", _DEFAULT_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache EVERY program: the kernel zoo is many sub-100ms compiles
    # (probe/link/flush per shape bucket) whose first-run total is the
    # difference between a cold bench and a warm one
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


def start_fetch(*arrays) -> None:
    """Kick the device→host DMA without waiting (no-op on host arrays)."""
    for a in arrays:
        f = getattr(a, "copy_to_host_async", None)
        if f is not None:
            f()


def _not_ready(arrays) -> List:
    """Arrays still computing/in DMA (host numpy is always ready)."""
    out = []
    for a in arrays:
        ready = getattr(a, "is_ready", None)
        if ready is not None and not ready():
            out.append(a)
    return out


def fetch(*arrays, poll_s: float = 0.002) -> List[np.ndarray]:
    """Read device arrays via the async-DMA path (see module docstring).

    Starts all copies first so transfers overlap, polls readiness (a
    bare blocking read over the tunnel occasionally degrades to a
    multi-second wait quantum), then materializes. Host numpy arrays
    pass through untouched.

    The wait ladders: GIL-yield spins first (XLA host compute lands in
    µs — a fixed 2ms quantum was the q8 hot path's single biggest cost
    on CPU), then sub-ms naps, then the tunnel-friendly `poll_s`.
    """
    import time

    start_fetch(*arrays)
    pending = _not_ready(arrays)
    spins = 0
    while pending:
        if spins < 50:
            time.sleep(0)              # yield the GIL; compute threads run
        elif spins < 80:
            time.sleep(0.0002)
        else:
            time.sleep(poll_s)
        spins += 1
        pending = _not_ready(pending)
    return [np.asarray(a) for a in arrays]


def fetch1(array) -> np.ndarray:
    return fetch(array)[0]


async def fetch_async(*arrays, poll_s: float = 0.001) -> List[np.ndarray]:
    """fetch() that yields to the event loop during the wait, so
    barrier/actor coroutines keep flowing during the DMA. Same wait
    ladder as fetch(): zero-delay yields first (they still run other
    ready coroutines), timed naps only once the wait is clearly long."""
    import asyncio

    start_fetch(*arrays)
    pending = _not_ready(arrays)
    spins = 0
    while pending:
        await asyncio.sleep(0 if spins < 50 else poll_s)
        spins += 1
        pending = _not_ready(pending)
    return [np.asarray(a) for a in arrays]


class PendingCounters:
    """Sync-free occupancy accounting for device hash structures.

    Every insert step returns an exact device-side insert count; the
    DMA for it is kicked at dispatch (start_fetch) and folded into the
    running count when it lands. The load bound callers should use is
    ``count() + pending_rows()`` — exact once all counters drain, and a
    tight upper bound (count + rows of undrained batches) meanwhile.
    Shared by GroupedAggKernel and DeviceHashTable so the drain
    ordering/readiness subtleties live in exactly one place.
    """

    def __init__(self, initial: int = 0):
        self._count = initial
        self._pending: List[tuple] = []   # (device scalar, n_rows)
        self._rows = 0

    def push(self, ins, n_rows: int) -> None:
        start_fetch(ins)
        self._pending.append((ins, n_rows))
        self._rows += n_rows

    def count(self) -> int:
        return self._count

    def pending_rows(self) -> int:
        return self._rows

    def bound(self) -> int:
        return self._count + self._rows

    def drain_ready(self) -> None:
        """Fold in landed counters; never blocks. FIFO: counters land
        in dispatch order (single device stream)."""
        while self._pending and self._pending[0][0].is_ready():
            ins, n = self._pending.pop(0)
            self._count += int(ins)
            self._rows -= n

    def drain_all(self) -> int:
        """Fold in every counter (blocks; DMAs already in flight)."""
        if self._pending:
            counts = fetch(*[i for i, _n in self._pending])
            self._count += int(sum(int(c) for c in counts))
            self._pending = []
            self._rows = 0
        return self._count

    def reset(self, exact: int) -> None:
        """Adopt an externally-observed exact count (flush header,
        rebuild) that subsumes all in-flight counters."""
        self._count = exact
        self._pending = []
        self._rows = 0
