"""JAX runtime knobs shared by bench/driver entry points.

The stateful kernels compile one XLA program per (table capacity, chunk
rows) shape pair; growth doublings therefore trigger a handful of
compiles per process lifetime. The persistent compilation cache makes
those a one-time cost per machine instead of per run — on a tunneled
TPU a single kernel compile is ~0.5-1s, so a cold bench run would
otherwise spend most of its wall clock in the compiler.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def enable_compilation_cache(path: str | None = None) -> str:
    """Point JAX's persistent compilation cache at a repo-local dir."""
    import jax

    cache_dir = path or os.environ.get("RW_TPU_JAX_CACHE", _DEFAULT_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache EVERY program: the kernel zoo is many sub-100ms compiles
    # (probe/link/flush per shape bucket) whose first-run total is the
    # difference between a cold bench and a warm one
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir
