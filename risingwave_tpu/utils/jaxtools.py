"""JAX runtime knobs shared by bench/driver entry points.

The stateful kernels compile one XLA program per (table capacity, chunk
rows) shape pair; growth doublings therefore trigger a handful of
compiles per process lifetime. The persistent compilation cache makes
those a one-time cost per machine instead of per run — on a tunneled
TPU a single kernel compile is ~0.5-1s, so a cold bench run would
otherwise spend most of its wall clock in the compiler.

``fetch``: measured on the tunneled v5e, a plain blocking device→host
read (``np.asarray`` / ``int()`` on a jax array) costs 70ms-40s(!)
regardless of size, while ``copy_to_host_async()`` followed by the same
read costs ~0.1ms once the transfer has landed. EVERY device read in
this codebase must go through fetch()/fetch_async — a stray bare
``np.asarray`` on the hot path costs three orders of magnitude.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from risingwave_tpu.utils import ledger as _ledger

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def shard_map(f, **kw):
    """``jax.shard_map`` across jax versions: a top-level alias only in
    newer jax; the pinned 0.4.x exposes it under
    ``jax.experimental.shard_map`` with the replication check named
    ``check_rep`` instead of ``check_vma``."""
    import jax

    try:
        return jax.shard_map(f, **kw)
    except AttributeError:  # pragma: no cover - depends on installed jax
        from jax.experimental import shard_map as _esm

        kw["check_rep"] = kw.pop("check_vma", True)
        return _esm.shard_map(f, **kw)


# every InstrumentedJit by label (last construction wins): the
# compiled-program cost-analysis registry behind EXPLAIN's kernel-cost
# footer, ctl phases and the device_kernel_* gauges
KERNELS: Dict[str, "InstrumentedJit"] = {}

# True while cost_analysis() lowers a kernel: the traced body re-runs
# during that lowering, and its note_compile/mark_stale side effects
# (recompile counter, ledger warmup mark, shape recapture) must NOT
# fire — a report read is not a compile event (RecompileGuard would
# trip on an EXPLAIN otherwise)
_COST_LOWERING = False


class InstrumentedJit:
    """A jitted kernel plus the bookkeeping the observability layer
    needs: (re)trace counting (note_compile inside the traced body)
    and compiled-program cost analysis. Whenever a call (re)traces —
    the traced body marks the instance stale — the call's argument
    SHAPES are captured (jax.ShapeDtypeStruct leaves, no array
    pinning), so the analysis tracks the LATEST compiled shape bucket
    through capacity growth. ``cost_analysis()`` lowers against them
    on demand, which hits the in-process/persistent compilation cache
    instead of re-running XLA, and returns the HLO cost model's
    flops / bytes-accessed — the yardstick device_compute
    measurements are sanity-checked against."""

    __slots__ = ("label", "_jit", "_args", "_kw", "_cost", "_stale")

    # sentinel: analysis attempted and unavailable on this backend —
    # cached so an EXPLAIN never re-lowers per statement
    _UNAVAILABLE = object()

    def __init__(self, jitted, label: str):
        self.label = label
        self._jit = jitted
        self._args = None
        self._kw = None
        self._cost = None
        self._stale = True             # first call always captures
        KERNELS[label] = self

    def __call__(self, *args, **kw):
        out = self._jit(*args, **kw)
        if self._stale:
            # capture AFTER the call: a retrace flips the flag while
            # jax traces, so the shapes recorded always belong to a
            # program that actually compiled (donated args keep their
            # aval — .shape/.dtype stay readable past the buffer)
            import jax

            def _abstract(x):
                if not (hasattr(x, "shape") and hasattr(x, "dtype")):
                    return x
                # keep the sharding when the aval supports it: a
                # mesh kernel's cost lowering then matches the LIVE
                # executable's cache entry instead of compiling a
                # default-sharded twin on the reporting path
                try:
                    sh = getattr(x, "sharding", None)
                except Exception:      # noqa: BLE001 — donated buffer
                    sh = None
                if sh is not None:
                    try:
                        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                    sharding=sh)
                    except TypeError:   # older jax: no sharding param
                        pass
                return jax.ShapeDtypeStruct(x.shape, x.dtype)

            self._args = jax.tree.map(_abstract, args)
            self._kw = jax.tree.map(_abstract, kw)
            self._cost = None
            self._stale = False
        return out

    def mark_stale(self) -> None:
        """A (re)trace happened: recapture shapes at this call."""
        self._stale = True

    def cost_analysis(self) -> Optional[dict]:
        """{'flops': f, 'bytes_accessed': b} for the latest-captured
        shapes, or None (never called yet / backend without a cost
        model). Both outcomes cache — repeated reads never re-lower."""
        if self._cost is self._UNAVAILABLE:
            return None
        if self._cost is not None:
            return self._cost
        if self._args is None:
            return None
        global _COST_LOWERING
        _COST_LOWERING = True
        try:
            ca = self._jit.lower(*self._args,
                                 **self._kw).compile().cost_analysis()
        except Exception:              # noqa: BLE001 — backend-dependent
            self._cost = self._UNAVAILABLE
            return None
        finally:
            _COST_LOWERING = False
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            self._cost = self._UNAVAILABLE
            return None
        self._cost = {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed",
                                           ca.get("bytes_accessed",
                                                  0.0))),
        }
        return self._cost


def kernel_cost_rows() -> List[tuple]:
    """(label, flops, bytes_accessed) per registered kernel with an
    available cost analysis, sorted by label."""
    out = []
    for label in sorted(KERNELS):
        ca = KERNELS[label].cost_analysis()
        if ca is not None:
            out.append((label, ca["flops"], ca["bytes_accessed"]))
    return out


def publish_kernel_costs() -> int:
    """Refresh the device_kernel_flops/bytes_accessed gauges from the
    registry (lazy by design: cost analysis compiles on first read, so
    it runs at report points — ctl phases, bench snapshot — not on the
    hot path). Returns the number of kernels published."""
    from risingwave_tpu.utils.metrics import STREAMING
    rows = kernel_cost_rows()
    for label, flops, nbytes in rows:
        STREAMING.kernel_flops.set(flops, kernel=label)
        STREAMING.kernel_bytes_accessed.set(nbytes, kernel=label)
    return len(rows)


def instrumented_jit(fn, label: str | None = None, **jit_kw):
    """``jax.jit`` with (re)trace visibility: the wrapper's Python body
    runs only while jax TRACES it — once per new input shape bucket —
    so each execution of the hook is exactly one compile event. It
    lands in ``stream_kernel_recompile_count{kernel=label}`` and as a
    compile span in the current epoch's trace (utils/spans.py), making
    warmup compiles and steady-state shape-churn recompiles visible
    instead of silent multi-second stalls. Steady state pays nothing:
    jit dispatches the cached executable without entering the body.

    Returns an InstrumentedJit: call it like the jitted function; its
    ``cost_analysis()`` serves the compiled program's flops/bytes."""
    import functools

    import jax

    name = label or getattr(fn, "__name__", "kernel")
    inst_box: list = []

    @functools.wraps(fn)
    def traced(*a, **k):
        if not _COST_LOWERING:
            from risingwave_tpu.utils.spans import note_compile
            note_compile(name)
            if inst_box:
                # this call is (re)tracing: the wrapper recaptures the
                # call's shapes so cost_analysis follows growth
                inst_box[0].mark_stale()
        return fn(*a, **k)

    inst = InstrumentedJit(jax.jit(traced, **jit_kw), name)
    inst_box.append(inst)
    return inst


def enable_compilation_cache(path: str | None = None) -> str:
    """Point JAX's persistent compilation cache at a repo-local dir."""
    import jax

    cache_dir = path or os.environ.get("RW_TPU_JAX_CACHE", _DEFAULT_DIR)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache EVERY program: the kernel zoo is many sub-100ms compiles
    # (probe/link/flush per shape bucket) whose first-run total is the
    # difference between a cold bench and a warm one
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


def start_fetch(*arrays) -> None:
    """Kick the device→host DMA without waiting (no-op on host arrays)."""
    for a in arrays:
        f = getattr(a, "copy_to_host_async", None)
        if f is not None:
            f()


def _not_ready(arrays) -> List:
    """Arrays still computing/in DMA (host numpy is always ready)."""
    out = []
    for a in arrays:
        ready = getattr(a, "is_ready", None)
        if ready is not None and not ready():
            out.append(a)
    return out


def _ledger_d2h(arrays, out) -> None:
    """Count the device→host payload of a completed fetch (host numpy
    pass-throughs excluded — they never crossed the bus)."""
    nbytes = sum(o.nbytes for a, o in zip(arrays, out)
                 if hasattr(a, "copy_to_host_async"))
    if nbytes:
        _ledger.LEDGER.add_bytes("d2h", nbytes)


def _wait_ready(pending, poll_s: float) -> None:
    """The ONE copy of the ready-wait ladder: GIL-yield spins first
    (XLA host compute lands in µs — a fixed 2ms quantum was the q8
    hot path's single biggest cost on CPU), then sub-ms naps, then
    the tunnel-friendly `poll_s`."""
    import time

    spins = 0
    while pending:
        if spins < 50:
            time.sleep(0)              # yield the GIL; compute runs
        elif spins < 80:
            time.sleep(0.0002)
        else:
            time.sleep(poll_s)
        spins += 1
        pending = _not_ready(pending)


def fetch(*arrays, poll_s: float = 0.002) -> List[np.ndarray]:
    """Read device arrays via the async-DMA path (see module docstring).

    Starts all copies first so transfers overlap, polls readiness (a
    bare blocking read over the tunnel occasionally degrades to a
    multi-second wait quantum), then materializes. Host numpy arrays
    pass through untouched.

    Phase ledger: the ready-wait segment is the device's compute tail
    as the host observes it under async dispatch (device_compute); the
    materialization is the d2h transfer, with exact bytes.
    """
    start_fetch(*arrays)
    pending = _not_ready(arrays)
    if not _ledger.enabled():
        _wait_ready(pending, poll_s)
        return [np.asarray(a) for a in arrays]
    if pending:
        with _ledger.LEDGER.phase("device_compute"):
            _wait_ready(pending, poll_s)
    with _ledger.LEDGER.phase("d2h"):
        out = [np.asarray(a) for a in arrays]
    _ledger_d2h(arrays, out)
    return out


def fetch1(array) -> np.ndarray:
    return fetch(array)[0]


async def fetch_async(*arrays, poll_s: float = 0.001) -> List[np.ndarray]:
    """fetch() that yields to the event loop during the wait, so
    barrier/actor coroutines keep flowing during the DMA. Same wait
    ladder as fetch(): zero-delay yields first (they still run other
    ready coroutines), timed naps only once the wait is clearly long.

    Ledger note: the wait here is NOT attributed to device_compute —
    other coroutines run during the yields and their own phases own
    that wall time; only the materialization (d2h, with bytes) is."""
    import asyncio

    start_fetch(*arrays)
    pending = _not_ready(arrays)
    spins = 0
    while pending:
        await asyncio.sleep(0 if spins < 50 else poll_s)
        spins += 1
        pending = _not_ready(pending)
    if not _ledger.enabled():
        return [np.asarray(a) for a in arrays]
    with _ledger.LEDGER.phase("d2h"):
        out = [np.asarray(a) for a in arrays]
    _ledger_d2h(arrays, out)
    return out


def upload(host, sharding=None, kernel: Optional[str] = None):
    """``jax.device_put`` with h2d ledger accounting (phase time +
    exact payload bytes under ``stream_transfer_bytes_total``). EVERY
    hot-path host→device matrix upload should go through here — it is
    the h2d half of the epoch phase ledger's conservation argument."""
    import jax

    if not _ledger.enabled():
        return jax.device_put(host) if sharding is None \
            else jax.device_put(host, sharding)
    with _ledger.LEDGER.phase("h2d", kernel=kernel):
        out = jax.device_put(host) if sharding is None \
            else jax.device_put(host, sharding)
    _ledger.LEDGER.add_bytes("h2d", int(getattr(host, "nbytes", 0)),
                             kernel=kernel)
    return out


class PendingCounters:
    """Sync-free occupancy accounting for device hash structures.

    Every insert step returns an exact device-side insert count; the
    DMA for it is kicked at dispatch (start_fetch) and folded into the
    running count when it lands. The load bound callers should use is
    ``count() + pending_rows()`` — exact once all counters drain, and a
    tight upper bound (count + rows of undrained batches) meanwhile.
    Shared by GroupedAggKernel and DeviceHashTable so the drain
    ordering/readiness subtleties live in exactly one place.
    """

    def __init__(self, initial: int = 0):
        self._count = initial
        self._pending: List[tuple] = []   # (device scalar, n_rows)
        self._rows = 0

    def push(self, ins, n_rows: int) -> None:
        start_fetch(ins)
        self._pending.append((ins, n_rows))
        self._rows += n_rows

    def count(self) -> int:
        return self._count

    def pending_rows(self) -> int:
        return self._rows

    def bound(self) -> int:
        return self._count + self._rows

    def drain_ready(self) -> None:
        """Fold in landed counters; never blocks. FIFO: counters land
        in dispatch order (single device stream)."""
        while self._pending and self._pending[0][0].is_ready():
            ins, n = self._pending.pop(0)
            self._count += int(ins)
            self._rows -= n

    def drain_all(self) -> int:
        """Fold in every counter (blocks; DMAs already in flight)."""
        if self._pending:
            counts = fetch(*[i for i, _n in self._pending])
            self._count += int(sum(int(c) for c in counts))
            self._pending = []
            self._rows = 0
        return self._count

    def reset(self, exact: int) -> None:
        """Adopt an externally-observed exact count (flush header,
        rebuild) that subsumes all in-flight counters."""
        self._count = exact
        self._pending = []
        self._rows = 0
