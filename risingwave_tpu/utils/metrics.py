"""Metrics: counters/gauges/histograms + Prometheus text rendering.

Reference parity: src/common/src/metrics.rs + the per-subsystem
registries (StreamingMetrics src/stream/src/executor/monitor/
streaming_stats.rs, meta barrier_latency src/meta/src/rpc/metrics.rs:57)
— a dependency-free in-process registry with the same exposition
format, so the numbers can feed any Prometheus scraper later.
"""

from __future__ import annotations

import bisect
import time
from typing import Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _fmt_value(v: float) -> str:
    """Full-precision exposition: '%g' truncates to 6 significant
    digits, freezing large counters in a scraper's eyes."""
    if float(v).is_integer() and abs(v) < 2**63:
        return str(int(v))
    return repr(float(v))


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        k = _label_key(labels)
        self._values[k] = self._values.get(k, 0.0) + amount

    def get(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def render(self) -> List[str]:
        out = [f"# TYPE {self.name} counter"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels: str) -> None:
        self._values[_label_key(labels)] = value

    def get(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def remove(self, **labels: str) -> None:
        """Drop a labeled series (executor teardown — avoids leaking
        stale series in the process-global registry)."""
        self._values.pop(_label_key(labels), None)

    def render(self) -> List[str]:
        out = [f"# TYPE {self.name} gauge"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}")
        return out


DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    """Fixed-bucket histogram with exact-quantile support for tests
    (keeps raw observations up to a cap)."""

    def __init__(self, name: str, help_: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 keep_raw: int = 100_000):
        self.name = name
        self.help = help_
        self.buckets = list(buckets)
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sum: Dict[LabelKey, float] = {}
        self._total: Dict[LabelKey, int] = {}
        self._raw: Dict[LabelKey, List[float]] = {}
        self._keep_raw = keep_raw

    def observe(self, value: float, **labels: str) -> None:
        k = _label_key(labels)
        counts = self._counts.setdefault(k, [0] * (len(self.buckets) + 1))
        i = bisect.bisect_left(self.buckets, value)
        counts[i] += 1
        self._sum[k] = self._sum.get(k, 0.0) + value
        self._total[k] = self._total.get(k, 0) + 1
        raw = self._raw.setdefault(k, [])
        if len(raw) < self._keep_raw:
            raw.append(value)

    def quantile(self, q: float, **labels: str) -> float:
        raw = sorted(self._raw.get(_label_key(labels), []))
        if not raw:
            return 0.0
        return raw[min(len(raw) - 1, int(len(raw) * q))]

    def count(self, **labels: str) -> int:
        return self._total.get(_label_key(labels), 0)

    def render(self) -> List[str]:
        out = [f"# TYPE {self.name} histogram"]
        for k, counts in sorted(self._counts.items()):
            acc = 0
            for le, c in zip(self.buckets, counts):
                acc += c
                lk = k + (("le", f"{le:g}"),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} {acc}")
            acc += counts[-1]
            lk = k + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {acc}")
            out.append(f"{self.name}_sum{_fmt_labels(k)} "
                       f"{_fmt_value(self._sum.get(k, 0.0))}")
            out.append(f"{self.name}_count{_fmt_labels(k)} "
                       f"{self._total.get(k, 0)}")
        return out


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, buckets))

    def _get(self, name: str, mk):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = mk()
        return m

    def render(self) -> str:
        lines: List[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"


# the process-global registry (per-node registry analog)
GLOBAL = MetricsRegistry()


class StreamingMetrics:
    """The streaming-side metric family (streaming_stats.rs analog)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or GLOBAL
        self.source_rows = r.counter(
            "stream_source_output_rows_counts",
            "rows emitted by sources")
        self.executor_rows = r.counter(
            "stream_executor_row_count", "rows through executors")
        self.barrier_latency = r.histogram(
            "meta_barrier_duration_seconds",
            "inject→commit latency per barrier")
        self.agg_dirty_groups = r.gauge(
            "stream_agg_dirty_groups_count",
            "dirty groups at last flush")
        self.agg_table_capacity = r.gauge(
            "stream_agg_table_capacity", "device hash-table slots")
        self.join_rows_evicted = r.counter(
            "stream_join_rows_evicted",
            "join-state rows evicted to the cold (state-table) tier")
        self.agg_rows_cleaned = r.counter(
            "stream_agg_state_rows_cleaned",
            "state rows deleted by watermark cleaning")
        self.actor_count = r.gauge("stream_actor_count", "live actors")
        self.checkpoint_count = r.counter(
            "meta_checkpoint_count", "committed checkpoints")
        self.host_state_bytes = r.gauge(
            "stream_host_state_bytes",
            "accounted host-resident state per cache "
            "(EstimateSize analog)")


STREAMING = StreamingMetrics()
