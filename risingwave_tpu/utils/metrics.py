"""Metrics: counters/gauges/histograms + Prometheus text rendering.

Reference parity: src/common/src/metrics.rs + the per-subsystem
registries (StreamingMetrics src/stream/src/executor/monitor/
streaming_stats.rs, meta barrier_latency src/meta/src/rpc/metrics.rs:57)
— a dependency-free in-process registry with the same exposition
format, so the numbers can feed any Prometheus scraper later.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

# guards read-modify-write updates (Counter.inc, Histogram.observe):
# the async checkpoint uploader runs object-store PUTs — and their
# op/latency/byte metrics — in worker threads, and an unguarded
# `d[k] = d.get(k) + v` can lose increments across a GIL preemption.
# One uncontended lock acquire is ~100ns; every metered path is
# per-chunk or per-object-store-op, not per-row.
_WRITE_LOCK = threading.Lock()


def _help_lines(name: str, help_: str) -> List[str]:
    """`# HELP` precedes `# TYPE` (Prometheus exposition order); an
    empty help string renders nothing — real scrapers tolerate the
    omission but tooling (promtool lint) wants the line when known."""
    if not help_:
        return []
    text = help_.replace("\\", "\\\\").replace("\n", "\\n")
    return [f"# HELP {name} {text}"]


def _fmt_value(v: float) -> str:
    """Full-precision exposition: '%g' truncates to 6 significant
    digits, freezing large counters in a scraper's eyes."""
    if float(v).is_integer() and abs(v) < 2**63:
        return str(int(v))
    return repr(float(v))


def exact_quantile(xs: Sequence[float], q: float) -> float:
    """Exact quantile over raw observations (shared by Histogram,
    BarrierStats and the epoch profiler — one index convention)."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(len(xs) * q))]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class Series:
    """Cached-label handle onto one series: per-message hot paths
    (exchange sends) skip rebuilding the sorted label key each call."""

    __slots__ = ("_values", "_key")

    def __init__(self, values: Dict[LabelKey, float], key: LabelKey):
        self._values = values
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        with _WRITE_LOCK:
            self._values[self._key] = \
                self._values.get(self._key, 0.0) + amount

    def set(self, value: float) -> None:
        self._values[self._key] = value


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        k = _label_key(labels)
        with _WRITE_LOCK:
            self._values[k] = self._values.get(k, 0.0) + amount

    def labeled(self, **labels: str) -> Series:
        return Series(self._values, _label_key(labels))

    def get(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def series(self) -> List[Tuple[Dict[str, str], float]]:
        """Every labeled series as (labels, value) — the system-table
        read path (rw_actor_metrics and friends)."""
        return [(dict(k), v) for k, v in sorted(self._values.items())]

    def remove(self, **labels: str) -> None:
        """Drop a labeled series (actor teardown)."""
        self._values.pop(_label_key(labels), None)

    def render(self) -> List[str]:
        out = _help_lines(self.name, self.help)
        out.append(f"# TYPE {self.name} counter")
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}")
        return out


class Gauge:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels: str) -> None:
        self._values[_label_key(labels)] = value

    def labeled(self, **labels: str) -> Series:
        return Series(self._values, _label_key(labels))

    def get(self, **labels: str) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def remove(self, **labels: str) -> None:
        """Drop a labeled series (executor teardown — avoids leaking
        stale series in the process-global registry)."""
        self._values.pop(_label_key(labels), None)

    def series(self) -> List[Tuple[Dict[str, str], float]]:
        return [(dict(k), v) for k, v in sorted(self._values.items())]

    def render(self) -> List[str]:
        out = _help_lines(self.name, self.help)
        out.append(f"# TYPE {self.name} gauge")
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}")
        return out


DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    """Fixed-bucket histogram with exact-quantile support for tests
    (keeps raw observations up to a cap)."""

    def __init__(self, name: str, help_: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 keep_raw: int = 100_000):
        self.name = name
        self.help = help_
        self.buckets = list(buckets)
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sum: Dict[LabelKey, float] = {}
        self._total: Dict[LabelKey, int] = {}
        self._raw: Dict[LabelKey, List[float]] = {}
        self._keep_raw = keep_raw

    def observe(self, value: float, **labels: str) -> None:
        k = _label_key(labels)
        i = bisect.bisect_left(self.buckets, value)
        with _WRITE_LOCK:
            counts = self._counts.setdefault(
                k, [0] * (len(self.buckets) + 1))
            counts[i] += 1
            self._sum[k] = self._sum.get(k, 0.0) + value
            self._total[k] = self._total.get(k, 0) + 1
            raw = self._raw.setdefault(k, [])
            if len(raw) < self._keep_raw:
                raw.append(value)

    def quantile(self, q: float, **labels: str) -> float:
        return exact_quantile(self._raw.get(_label_key(labels), []), q)

    def count(self, **labels: str) -> int:
        return self._total.get(_label_key(labels), 0)

    def sum(self, **labels: str) -> float:
        return self._sum.get(_label_key(labels), 0.0)

    def series(self) -> List[Tuple[Dict[str, str], int, float]]:
        """(labels, observation count, sum) per labeled series."""
        return [(dict(k), self._total.get(k, 0),
                 self._sum.get(k, 0.0))
                for k in sorted(self._counts)]

    def remove(self, **labels: str) -> None:
        k = _label_key(labels)
        for d in (self._counts, self._sum, self._total, self._raw):
            d.pop(k, None)

    def render(self) -> List[str]:
        out = _help_lines(self.name, self.help)
        out.append(f"# TYPE {self.name} histogram")
        for k, counts in sorted(self._counts.items()):
            acc = 0
            for le, c in zip(self.buckets, counts):
                acc += c
                lk = k + (("le", f"{le:g}"),)
                out.append(f"{self.name}_bucket{_fmt_labels(lk)} {acc}")
            acc += counts[-1]
            lk = k + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(lk)} {acc}")
            out.append(f"{self.name}_sum{_fmt_labels(k)} "
                       f"{_fmt_value(self._sum.get(k, 0.0))}")
            out.append(f"{self.name}_count{_fmt_labels(k)} "
                       f"{self._total.get(k, 0)}")
        return out


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help_, buckets))

    def _get(self, name: str, mk):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = mk()
        return m

    def render(self) -> str:
        lines: List[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"


# the process-global registry (per-node registry analog)
GLOBAL = MetricsRegistry()


class StreamingMetrics:
    """The streaming-side metric family (streaming_stats.rs analog)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or GLOBAL
        self.source_rows = r.counter(
            "stream_source_output_rows_counts",
            "rows emitted by sources")
        self.executor_rows = r.counter(
            "stream_executor_row_count", "rows through executors")
        self.barrier_latency = r.histogram(
            "meta_barrier_duration_seconds",
            "inject→commit latency per barrier")
        self.agg_dirty_groups = r.gauge(
            "stream_agg_dirty_groups_count",
            "dirty groups at last flush")
        self.agg_table_capacity = r.gauge(
            "stream_agg_table_capacity", "device hash-table slots")
        # join payload residency (ISSUE 9): which half of a stored
        # join row lives where — device lane + degree HBM bytes vs the
        # host arena's column bytes, per executor, refreshed at every
        # barrier by HashJoinExecutor
        self.join_device_bytes = r.gauge(
            "stream_join_payload_device_bytes",
            "HBM bytes of device-resident join payload lanes + degree "
            "arrays per executor")
        self.join_host_bytes = r.gauge(
            "stream_join_payload_host_bytes",
            "host arena bytes backing join rows per executor "
            "(varchar/host columns + the durable rebuild copy)")
        self.join_rows_evicted = r.counter(
            "stream_join_rows_evicted",
            "join-state rows evicted to the cold (state-table) tier")
        self.agg_rows_cleaned = r.counter(
            "stream_agg_state_rows_cleaned",
            "state rows deleted by watermark cleaning")
        self.actor_count = r.gauge("stream_actor_count", "live actors")
        self.checkpoint_count = r.counter(
            "meta_checkpoint_count", "committed checkpoints")
        self.host_state_bytes = r.gauge(
            "stream_host_state_bytes",
            "accounted host-resident state per cache "
            "(EstimateSize analog)")
        # -- per-executor instrumentation (MonitoredExecutor) ---------
        self.executor_chunks = r.counter(
            "stream_executor_chunk_count",
            "chunks emitted per (fragment, actor, executor)")
        self.executor_busy = r.counter(
            "stream_executor_busy_seconds",
            "exclusive processing time per (fragment, actor, "
            "executor) — own pull time minus wrapped inputs'")
        self.executor_epoch_seconds = r.histogram(
            "stream_executor_epoch_processing_seconds",
            "per-epoch exclusive processing time per executor")
        self.executor_empty_chunks = r.counter(
            "stream_executor_empty_chunk_count",
            "zero-visible-row chunks emitted per (fragment, actor, "
            "executor) — should stay 0; the spine suppresses empties")
        # -- chunk compaction + coalescing (stream/coalesce.py) -------
        self.device_dispatch = r.counter(
            "stream_device_dispatch_count",
            "fused device kernel dispatches per executor (each is "
            "~2ms of host time through the tunnel — the cost "
            "coalescing amortizes)")
        self.rows_per_dispatch = r.histogram(
            "stream_rows_per_device_dispatch",
            "visible rows carried per device dispatch (dense batches "
            "amortize the per-dispatch overhead)",
            buckets=(1.0, 8.0, 32.0, 128.0, 512.0, 2048.0, 8192.0,
                     32768.0))
        self.kernel_recompile = r.counter(
            "stream_kernel_recompile_count",
            "jitted-kernel (re)traces by kernel label — nonzero "
            "during warmup, any steady-state growth is a shape-churn "
            "bug recompiling on the hot path")
        self.trace_spans_dropped = r.counter(
            "stream_trace_spans_dropped",
            "epoch-trace spans dropped over the per-epoch cap "
            "(utils/spans.py flight recorder bound)")
        self.coalesce_chunks_in = r.counter(
            "stream_coalesce_chunks_in",
            "chunks entering coalescers (ratio vs _out is the "
            "amortization factor)")
        self.coalesce_chunks_out = r.counter(
            "stream_coalesce_chunks_out",
            "chunks leaving coalescers after merging")
        self.compaction_rows_saved = r.counter(
            "stream_compaction_rows_saved",
            "padded row slots dropped by chunk compaction (capacity "
            "that no longer ships over exchanges or the wire)")
        # -- plan-rewrite engine (frontend/opt/) ----------------------
        self.rewrite_rule_fired = r.counter(
            "rewrite_rule_fired_total",
            "plan-rewrite rule applications by rule (frontend/opt "
            "fixpoint engine; a FALLBACK records 0 fires)")
        self.plan_columns_pruned = r.counter(
            "plan_columns_pruned",
            "column lanes removed from plans by the column-pruning "
            "rewrite (narrower joins, exchanges and agg feeds)")
        self.plan_exchanges_elided = r.counter(
            "plan_exchanges_elided",
            "hash exchanges removed because the producer's "
            "distribution already satisfied the consumer's keys")
        # -- exchange edges (permit.rs back-pressure analog) ----------
        self.exchange_backpressure = r.counter(
            "stream_exchange_backpressure_seconds",
            "time senders spent acquiring permits per edge "
            "(stream_exchange_backpressure analog)")
        # -- freshness & bottleneck attribution (ISSUE 14) ------------
        self.backpressure_wait = r.counter(
            "stream_backpressure_wait_seconds",
            "sender-side credit park time per channel — wall time a "
            "sender spent BLOCKED for exchange credits (subtracted "
            "from the parking executor's busy time, so straggler "
            "diagnoses stop blaming the victim of a slow consumer)")
        self.executor_utilization = r.gauge(
            "stream_executor_utilization_ratio",
            "utilization tricolor per (fragment, actor, executor, "
            "node) and state=busy|backpressure|idle: the share of the "
            "last barrier interval spent processing / parked on "
            "downstream credits / parked waiting for input; the "
            "triple sums to <= 1.0 (gated in tier-1 strict mode)")
        self.mv_freshness_lag = r.gauge(
            "stream_mv_freshness_lag_seconds",
            "per-MV event-time freshness lag at the last barrier: "
            "source ingest high-watermark minus the event-time "
            "frontier of what the MV has materialized (seconds of "
            "event time the reader is behind the data)")
        self.mv_freshness_wall_lag = r.gauge(
            "stream_mv_freshness_wall_lag_seconds",
            "per-MV wall-clock freshness lag at the last barrier: "
            "now minus the wall stamp of the newest ingested data "
            "visible in the MV")
        self.bottleneck_streak = r.gauge(
            "stream_bottleneck_streak",
            "contiguous barriers the named operator has been its "
            "domain's walked bottleneck (stream/bottleneck.py); the "
            "series resets when the walk names another operator")
        self.exchange_send_count = r.counter(
            "stream_exchange_send_count",
            "messages sent per exchange edge")
        self.exchange_queue_depth = r.gauge(
            "stream_exchange_queue_depth",
            "messages queued per exchange edge")
        # -- barrier-loop breakdown (epoch profiler) ------------------
        self.barrier_inject_to_collect = r.histogram(
            "meta_barrier_inject_to_collect_seconds",
            "inject→collect time per barrier")
        self.barrier_collect_to_commit = r.histogram(
            "meta_barrier_collect_to_commit_seconds",
            "collect→commit (seal+sync) time per barrier")
        self.barrier_in_flight = r.gauge(
            "meta_barrier_in_flight_count",
            "injected-but-uncollected barriers")
        # -- state tiering (state/tier.py cold tier) ------------------
        self.state_tier_resident = r.gauge(
            "state_tier_resident_keys",
            "hot-tier resident keys per registered executor cache")
        self.state_tier_evicted = r.counter(
            "state_tier_evicted_keys",
            "keys evicted to the cold (state-table) tier per executor")
        self.state_tier_reloads = r.counter(
            "state_tier_reload_keys",
            "evicted keys reloaded on touch per executor (the "
            "degrade-to-reload-traffic path)")
        self.state_tier_bytes = r.gauge(
            "state_tier_resident_bytes",
            "accounted host bytes of tier-governed caches per executor")
        # -- async checkpoint pipeline (storage/uploader.py) ----------
        self.barrier_upload = r.histogram(
            "meta_barrier_upload_seconds",
            "seal→durable-commit time per checkpoint epoch (the "
            "async upload tail, overlapped with later barriers)")
        self.uploader_queue_depth = r.gauge(
            "meta_checkpoint_uploader_queue_depth",
            "checkpoint epochs sealed but not yet durably committed")
        # -- exactly-once sinks (meta/sink_coordinator.py) ------------
        self.sink_committed_epoch = r.gauge(
            "sink_committed_epoch",
            "newest manifest-committed epoch per sink — visibility is "
            "manifest-existence, so this IS the sink's read frontier")
        self.sink_rows_total = r.counter(
            "sink_rows_total",
            "records durably staged per sink and mode (append|upsert; "
            "upsert counts post-fold records — one per touched key "
            "per epoch)")
        self.sink_staged_bytes = r.counter(
            "sink_staged_bytes",
            "segment bytes durably staged per sink (committed and "
            "not-yet-committed epochs both count; staging precedes "
            "the checkpoint floor by design)")
        # -- epoch phase ledger (utils/ledger.py) ---------------------
        self.epoch_phase_seconds = r.counter(
            "stream_epoch_phase_seconds",
            "barrier wall-clock attributed per phase "
            "(host_ingest/host_pack/h2d/device_compute/d2h/host_emit/"
            "barrier_wait; the conservation residual publishes as "
            "phase=unattributed)")
        self.transfer_bytes = r.counter(
            "stream_transfer_bytes_total",
            "host<->device transfer payload bytes by direction "
            "(dir=h2d|d2h) and kernel")
        self.backlog_rows = r.gauge(
            "stream_epoch_backlog_rows",
            "rows carried by the kernel's most recent epoch-batched "
            "dispatch (set at each backlog flush; sampled at every "
            "epoch seal as the Perfetto backlog counter track — the "
            "per-epoch staging volume, not a live queue depth)")
        self.kernel_flops = r.gauge(
            "device_kernel_flops",
            "XLA cost-analysis flops of the last-compiled program per "
            "kernel label (published lazily: ctl phases / bench)")
        self.kernel_bytes_accessed = r.gauge(
            "device_kernel_bytes_accessed",
            "XLA cost-analysis bytes-accessed of the last-compiled "
            "program per kernel label")
        # -- per-MV cost attribution (stream/costs.py, ISSUE 16) ------
        self.mv_device_seconds = r.counter(
            "stream_mv_device_seconds_total",
            "device_compute seconds attributed to the owning MV "
            "(executor-cell split of the phase ledger's books — sums "
            "to at most the ledgered device_compute per epoch)")
        self.mv_state_bytes = r.gauge(
            "stream_mv_state_bytes",
            "accounted state bytes per MV (per-(table,vnode) topology "
            "rollup, refreshed at each checkpoint)")
        self.mv_transfer_bytes = r.counter(
            "stream_mv_transfer_bytes_total",
            "host<->device transfer payload bytes attributed to the "
            "owning MV, by direction (dir splits like "
            "stream_transfer_bytes_total)")


class ClusterMetrics:
    """Cluster control-plane metric family (meta recovery +
    heartbeat/RPC liveness — the supervisor's evidence trail)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or GLOBAL
        self.recovery_total = r.counter(
            "recovery_total",
            "cluster recoveries by cause and action (respawn = dead "
            "slots restarted in place, full = kill-and-redeploy); "
            "absorbed transient faults do NOT count here")
        self.recovery_duration = r.histogram(
            "recovery_duration_seconds",
            "failure-detected → cluster-recovered time per recovery "
            "(MTTR samples)")
        self.rpc_retry = r.counter(
            "rpc_retry_total",
            "idempotent worker-control RPCs retried after a "
            "reconnect (transient faults absorbed below the "
            "supervisor), by verb")
        self.worker_expired = r.counter(
            "cluster_worker_expired_total",
            "workers evicted by heartbeat lease expiry, by worker id")
        self.autoscaler_decision = r.counter(
            "autoscaler_decision_total",
            "autoscaler scaling decisions by mv and direction "
            "(up/down); every completed action counts here, including "
            "ones later rolled back")
        self.autoscaler_rollback = r.counter(
            "autoscaler_rollback_total",
            "autoscaler actions rolled back to the prior parallelism "
            "(failed, timed-out, or health-failing rescales), by mv")


class StorageMetrics:
    """Storage-tier metric family (state_store/object_store analog)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or GLOBAL
        self.block_cache_hits = r.counter(
            "state_store_block_cache_hit_count",
            "block-cache hits (sstable_store block_cache analog)")
        self.block_cache_misses = r.counter(
            "state_store_block_cache_miss_count",
            "block-cache misses → ranged object-store reads")
        self.sst_upload_count = r.counter(
            "state_store_sst_upload_count",
            "SSTs built and uploaded at checkpoint sync")
        self.sst_upload_bytes = r.counter(
            "state_store_sst_upload_bytes",
            "bytes of SST data uploaded")
        self.sst_upload_retries = r.counter(
            "state_store_sst_upload_retry_count",
            "checkpoint SST uploads retried after a transient failure")
        self.object_store_retries = r.counter(
            "object_store_retry_total",
            "object-store ops retried after a transient fault "
            "(RetryingObjectStore jittered-backoff absorption), by op")
        self.object_store_ops = r.counter(
            "object_store_operation_count",
            "object-store operations by op (upload/read/read_range)")
        self.object_store_latency = r.histogram(
            "object_store_operation_latency_seconds",
            "object-store operation latency by op")
        self.compaction_bytes_read = r.counter(
            "compaction_bytes_read",
            "bytes of SST input read by compaction merges, by arm "
            "(inline/dedicated) — the write-amplification numerator's "
            "read side")
        self.compaction_bytes_written = r.counter(
            "compaction_bytes_written",
            "bytes of SST output written by compaction merges, by arm "
            "(inline/dedicated); written/ingested = write amplification")
        self.compaction_pending_tasks = r.gauge(
            "compaction_pending_tasks",
            "compaction tasks currently pending or running in the "
            "CompactionManager (dedicated arm)")
        self.storage_space_amp = r.gauge(
            "storage_space_amp",
            "space amplification: (manifest-live + retired-on-disk) "
            "bytes / manifest-live bytes — 1.0 when the pin-gated "
            "vacuum is caught up")


STREAMING = StreamingMetrics()
STORAGE = StorageMetrics()
CLUSTER = ClusterMetrics()


class MetricsHistory:
    """Bounded per-barrier time series: last N barriers × selected
    counter DELTAS and gauge values (arxiv 1904.03800's concurrent-
    bookkeeping stance: the control loop reads history, not one
    instantaneous scrape). One row lands per sealed barrier
    (utils/ledger.seal), carrying the tracked registry series plus the
    ledger's phase seconds/coverage/bytes as ``extra``. Backs the
    ``rw_metrics_history`` system table and the ROADMAP-item-3
    autoscaler's telemetry feed."""

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        from collections import deque
        self._ring = deque(maxlen=capacity)
        self._last: Dict[str, float] = {}
        self._seq = 0
        self._lock = threading.Lock()

    def _tracked(self):
        """(series name, read fn, kind) — counters report per-barrier
        deltas, gauges report the value at seal."""
        def csum(metric, **labels):
            if labels:
                return sum(v for l, v in metric.series()
                           if all(l.get(k) == val
                                  for k, val in labels.items()))
            return sum(v for _l, v in metric.series())

        S = STREAMING
        return (
            ("source_rows", lambda: csum(S.source_rows), "counter"),
            ("device_dispatches",
             lambda: csum(S.device_dispatch), "counter"),
            ("h2d_bytes",
             lambda: csum(S.transfer_bytes, dir="h2d"), "counter"),
            ("d2h_bytes",
             lambda: csum(S.transfer_bytes, dir="d2h"), "counter"),
            ("checkpoints",
             lambda: csum(S.checkpoint_count), "counter"),
            ("kernel_recompiles",
             lambda: csum(S.kernel_recompile), "counter"),
            ("exchange_backpressure_s",
             lambda: csum(S.exchange_backpressure), "counter"),
            ("uploader_queue_depth",
             lambda: S.uploader_queue_depth.get(), "gauge"),
            ("barrier_in_flight",
             lambda: S.barrier_in_flight.get(), "gauge"),
            ("backlog_rows", lambda: csum(S.backlog_rows), "gauge"),
        )

    def observe(self, epoch: int, interval_s: float,
                extra: Optional[Dict[str, float]] = None,
                domain: str = "") -> None:
        values: Dict[str, float] = {}
        for name, fn, kind in self._tracked():
            v = float(fn())
            if kind == "counter":
                values[name] = v - self._last.get(name, 0.0)
                self._last[name] = v
            else:
                values[name] = v
        if extra:
            values.update(extra)
        with self._lock:
            self._seq += 1
            self._ring.append((self._seq, int(epoch), time.time(),
                               float(interval_s), values, domain))

    def rows(self) -> List[tuple]:
        """(seq, epoch, ts, interval_s, name, value, domain)
        long-format rows — the rw_metrics_history system-table
        payload. ``domain`` names the barrier domain whose seal
        produced the row ("" = the global domain), so the ROADMAP-3
        autoscaler can see WHICH domain is behind, not just the
        cluster aggregate."""
        with self._lock:
            snap = list(self._ring)
        out = []
        for seq, epoch, ts, interval_s, values, domain in snap:
            for name in sorted(values):
                out.append((seq, epoch, ts, interval_s, name,
                            float(values[name]), domain))
        return out

    def domain_rows(self, domain: str) -> List[tuple]:
        """The rows of one barrier domain (autoscaler convenience)."""
        return [r for r in self.rows() if r[6] == domain]

    def barriers(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._last.clear()
            self._seq = 0


# the process-global per-barrier history ring (fed at ledger seal)
HISTORY = MetricsHistory()
