"""Chip-claim discipline: ONE TPU client process at a time.

The axon-tunneled chip admits a single client; a second client blocks
in backend init until the first's claim expires, and a SIGKILLed
client's remote claim takes minutes to expire (this wedged round 3's
bench for the whole round). The guard is an OS-level advisory lock:

- `flock(2)` on a repo-local lockfile — the KERNEL releases it when
  the holder dies, however it dies, so there is no stale-lock state
  to clean up (a pidfile would lie after SIGKILL).
- every in-repo TPU entrypoint (bench.py, profiling scripts) acquires
  it BEFORE importing jax / initializing the backend, so two clients
  can never race for the chip claim.
- holders should still die by SIGTERM, never SIGKILL: the LOCAL lock
  frees instantly either way, but the REMOTE claim only releases
  promptly on a clean client shutdown.

No reference counterpart — this guards a tunnel artifact, not a
RisingWave concern.
"""

from __future__ import annotations

import fcntl
import os
import sys
import time
from contextlib import contextmanager
from typing import Iterator, Optional

LOCK_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".tpu.lock")


class ChipBusy(TimeoutError):
    """Another process holds the chip lock."""


def _try_lock(fd: int) -> bool:
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        return True
    except BlockingIOError:
        return False
    # any other OSError (ENOLCK/ENOTSUP: filesystem without flock)
    # propagates — a spurious ChipBusy would silently cost the round
    # its TPU number, the exact failure this lock exists to prevent


@contextmanager
def chip_lock(timeout_s: float = 600.0, poll_s: float = 2.0,
              path: Optional[str] = None) -> Iterator[None]:
    """Hold the exclusive chip claim for the duration of the block.

    Blocks up to `timeout_s` waiting for the current holder to exit,
    then raises ChipBusy (callers decide whether to fall back to CPU).
    """
    p = path or LOCK_PATH
    fd = os.open(p, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        deadline = time.monotonic() + timeout_s
        while not _try_lock(fd):
            if time.monotonic() >= deadline:
                holder = ""
                try:
                    holder = os.read(fd, 64).decode(errors="replace")
                except OSError:
                    pass
                raise ChipBusy(
                    f"chip lock held (holder: {holder.strip() or '?'}) "
                    f"after {timeout_s:.0f}s — refusing to start a "
                    "second TPU client")
            time.sleep(poll_s)
        os.ftruncate(fd, 0)
        os.pwrite(fd, f"pid={os.getpid()} argv={sys.argv[0]}\n".encode(),
                  0)
        yield
    finally:
        os.close(fd)     # closes → kernel drops the flock
