"""Host-memory accounting: EstimateSize + a central context.

Reference parity: src/common/src/estimate_size/ (EstimateSize derive)
and src/compute/src/memory_management/memory_manager.rs:33-70 (the
LRU-watermark memory manager). TPU re-design: device state is
pre-sized and grows explicitly (kernel capacity ladders), so the
reference's malloc-pressure eviction loop maps to (a) SIZE ACCOUNTING
for every host-resident cache — join arenas, interners, partition
caches, memtables — surfaced through metrics, and (b) an eviction
sweep over the caches that are evictable (clean snapshot caches),
triggered when the accounted total crosses a soft limit. State that
is NOT evictable (arenas, interners) is bounded by live rows via
compaction/GC instead — see hash_join._maybe_gc_interner.

Reporters are CONSTANT-TIME estimators hand-rolled per cache (array
nbytes + per-entry constants) — tick() runs every checkpoint, so a
recursive deep-size walk would cost O(state) per barrier.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from risingwave_tpu.utils.metrics import STREAMING as _METRICS


class MemoryContext:
    """Central registry of host-state size reporters + evictors.

    Operators register a `nbytes` callable (accounting) and optionally
    an `evict` callable (frees what it safely can, returns bytes
    freed). `tick()` refreshes metrics and, when the soft limit is
    crossed, sweeps evictors largest-first — the memory_manager.rs
    watermark loop with explicit evictability instead of LRU epochs."""

    def __init__(self, soft_limit_bytes: Optional[int] = None):
        self.soft_limit = soft_limit_bytes
        # last accounted total, refreshed at every tick() — the state
        # tier (state/tier.py) reads this at its barrier sweeps instead
        # of re-walking every reporter per executor per barrier
        self.last_total = 0
        self._reporters: Dict[str, Callable[[], int]] = {}
        self._evictors: Dict[str, Callable[[], int]] = {}

    def register(self, name: str, nbytes: Callable[[], int],
                 evict: Optional[Callable[[], int]] = None) -> None:
        self._reporters[name] = nbytes
        if evict is not None:
            self._evictors[name] = evict

    def unregister(self, name: str) -> None:
        self._reporters.pop(name, None)
        self._evictors.pop(name, None)
        # drop the gauge series too: names embed object ids, so a
        # stale series per dead executor is unbounded label cardinality
        _METRICS.host_state_bytes.remove(cache=name)

    def sizes(self) -> Dict[str, int]:
        # snapshot first: dead-executor reporters unregister themselves
        # when called (weakref pattern), mutating the registry
        return {n: int(f()) for n, f in list(self._reporters.items())}

    def total_bytes(self) -> int:
        total = sum(self.sizes().values())
        self.last_total = total
        return total

    def tick(self) -> int:
        """Refresh metrics; evict if over the soft limit. Returns the
        accounted total after any eviction."""
        sizes = self.sizes()
        for name, b in sizes.items():
            _METRICS.host_state_bytes.set(b, cache=name)
        total = sum(sizes.values())
        self.last_total = total
        if self.soft_limit is None or total <= self.soft_limit:
            return total
        for name in sorted(self._evictors,
                           key=lambda n: -sizes.get(n, 0)):
            freed = int(self._evictors[name]())
            total -= freed
            if total <= self.soft_limit:
                break
        # deferred evictors (the state tier) see the over-limit total
        # via last_total and sweep at their own barriers
        self.last_total = max(total, 0)
        return total


GLOBAL = MemoryContext()
