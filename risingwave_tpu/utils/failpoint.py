"""Failpoint injection (src/storage/src/storage_failpoints/ +
`fail_point!` macro analog).

Production cost is one dict lookup against an empty registry. Tests arm
named points with an exception factory or a probability:

    with failpoints({"object_store.upload": OSError("disk gone")}):
        ...
    with failpoints({"object_store.read": (0.2, OSError("flaky"))},
                    seed=7):
        ...

Probabilistic points draw from a seeded Generator, so a chaos run is
DETERMINISTIC for a given seed — the madsim stance (SURVEY §4): faults
are reproducible, not racy.

Dict specs are the JSON-able subset — the forms that cross a process
boundary (worker subprocesses arm them from the ``RW_TPU_FAILPOINTS``
env var at boot via ``arm_from_env()``, or live over the worker
control channel's ``arm_failpoints`` verb):

- ``{"sleep_s": 0.2}`` — the fail crate's `sleep` analog: the point
  SLEEPS instead of raising (how trace/latency tests inject a
  deterministic straggler).
- ``{"raise": "OSError", "msg": "disk gone"}`` — raise a BUILTIN
  exception by name (crash injection inside worker processes). Only
  builtin exception *names* round-trip through JSON — arbitrary
  exception objects deliberately do not.
- either form takes ``"times": N`` — the point fires N times then
  goes inert (a transient fault that heals, the chaos harness's
  bread and butter: N ≤ the retry budget is absorbed in place,
  N past it escalates).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional, Union

import numpy as np

_ARMED: Dict[str, object] = {}
_ACTIVE = False
_RNG: Optional[np.random.Generator] = None
FIRED: Dict[str, int] = {}


def _resolve_exc(name: str) -> type:
    """Builtin exception class by name (the JSON round-trip
    restriction: {"raise": "OSError"} crosses the subprocess boundary,
    a pickled exception object would not)."""
    import builtins
    exc = getattr(builtins, str(name), None)
    if not (isinstance(exc, type) and issubclass(exc, BaseException)):
        raise ValueError(
            f"failpoint exception {name!r} must name a builtin "
            "exception class (only names round-trip through JSON)")
    return exc


def fail_point(name: str) -> None:
    """Raise if `name` is armed (call this at the injection site)."""
    if not _ARMED:
        return
    spec = _ARMED.get(name)
    if spec is None:
        return
    if isinstance(spec, dict):
        left = spec.get("_left")
        if left is not None:
            if left <= 0:
                return               # fired out: the fault has healed
            spec["_left"] = left - 1
        FIRED[name] = FIRED.get(name, 0) + 1
        if "sleep_s" in spec:
            time.sleep(float(spec["sleep_s"]))
            return
        raise _resolve_exc(spec["raise"])(
            spec.get("msg", f"failpoint {name}"))
    if isinstance(spec, tuple):
        prob, exc = spec
        if _RNG is None or _RNG.random() >= prob:
            return
    else:
        exc = spec
    FIRED[name] = FIRED.get(name, 0) + 1
    if isinstance(exc, BaseException):
        # fresh instance per fire: re-raising one shared object chains
        # tracebacks without bound and aliases state across catchers
        raise type(exc)(*exc.args)
    raise exc()


def arm_specs(points: Dict[str, Optional[dict]]) -> int:
    """Arm (or, with a None value, disarm) JSON-able dict specs —
    shared by the env boot path and the worker control channel's
    ``arm_failpoints`` verb. Validates eagerly: a bad spec must fail
    the arming call, not the injection site. Returns points touched."""
    for name, spec in points.items():
        if spec is None:
            _ARMED.pop(name, None)
            continue
        if not isinstance(spec, dict) or \
                not ({"sleep_s", "raise"} & spec.keys()):
            raise ValueError(
                f"failpoint {name!r} must be a sleep or raise spec "
                f"(JSON-able dict), got {spec!r}")
        armed = dict(spec)
        if "sleep_s" in armed:
            armed["sleep_s"] = float(armed["sleep_s"])
        else:
            _resolve_exc(armed["raise"])
        if "times" in armed:
            armed["_left"] = int(armed["times"])
        _ARMED[name] = armed
    return len(points)


def arm_from_env() -> int:
    """Arm dict-spec failpoints from RW_TPU_FAILPOINTS (subprocess
    boot path — worker processes can't enter a parent's context
    manager). Returns the number of points armed."""
    import json
    import os
    raw = os.environ.get("RW_TPU_FAILPOINTS")
    if not raw:
        return 0
    return arm_specs(json.loads(raw))


@contextlib.contextmanager
def failpoints(points: Dict[str, Union[BaseException, type, tuple,
                                       dict]],
               seed: int = 0):
    """Arm failpoints for the with-block (exclusive: no nesting)."""
    global _RNG, _ACTIVE
    if _ACTIVE:
        raise RuntimeError("failpoints already armed")
    # build everything fallible BEFORE mutating globals: a failed
    # setup must not leave points permanently armed
    rng = np.random.default_rng(seed)
    prepared = {}
    for name, spec in points.items():
        if isinstance(spec, dict):
            armed = dict(spec)
            if "times" in armed:
                armed["_left"] = int(armed["times"])
            prepared[name] = armed
        else:
            prepared[name] = spec
    _ACTIVE = True
    try:
        _ARMED.update(prepared)
        _RNG = rng
        FIRED.clear()
        yield FIRED
    finally:
        _ARMED.clear()
        _RNG = None
        _ACTIVE = False
