"""Failpoint injection (src/storage/src/storage_failpoints/ +
`fail_point!` macro analog).

Production cost is one dict lookup against an empty registry. Tests arm
named points with an exception factory or a probability:

    with failpoints({"object_store.upload": OSError("disk gone")}):
        ...
    with failpoints({"object_store.read": (0.2, OSError("flaky"))},
                    seed=7):
        ...

Probabilistic points draw from a seeded Generator, so a chaos run is
DETERMINISTIC for a given seed — the madsim stance (SURVEY §4): faults
are reproducible, not racy.

Delay actions (the fail crate's `sleep` analog): a spec of
``{"sleep_s": 0.2}`` makes the point SLEEP instead of raise — how
trace/latency tests inject a deterministic straggler. Subprocesses
(cluster workers) arm points from the ``RW_TPU_FAILPOINTS`` env var
(JSON name → sleep spec) at boot via ``arm_from_env()``; only sleep
specs are env-armable — exceptions don't round-trip through JSON.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Optional, Union

import numpy as np

_ARMED: Dict[str, object] = {}
_ACTIVE = False
_RNG: Optional[np.random.Generator] = None
FIRED: Dict[str, int] = {}


def fail_point(name: str) -> None:
    """Raise if `name` is armed (call this at the injection site)."""
    if not _ARMED:
        return
    spec = _ARMED.get(name)
    if spec is None:
        return
    if isinstance(spec, dict):
        FIRED[name] = FIRED.get(name, 0) + 1
        time.sleep(float(spec["sleep_s"]))
        return
    if isinstance(spec, tuple):
        prob, exc = spec
        if _RNG is None or _RNG.random() >= prob:
            return
    else:
        exc = spec
    FIRED[name] = FIRED.get(name, 0) + 1
    if isinstance(exc, BaseException):
        # fresh instance per fire: re-raising one shared object chains
        # tracebacks without bound and aliases state across catchers
        raise type(exc)(*exc.args)
    raise exc()


def arm_from_env() -> int:
    """Arm sleep-spec failpoints from RW_TPU_FAILPOINTS (subprocess
    boot path — worker processes can't enter a parent's context
    manager). Returns the number of points armed."""
    import json
    import os
    raw = os.environ.get("RW_TPU_FAILPOINTS")
    if not raw:
        return 0
    points = json.loads(raw)
    for name, spec in points.items():
        if not (isinstance(spec, dict) and "sleep_s" in spec):
            raise ValueError(
                f"env failpoint {name!r} must be a sleep spec, "
                f"got {spec!r}")
        _ARMED[name] = {"sleep_s": float(spec["sleep_s"])}
    return len(points)


@contextlib.contextmanager
def failpoints(points: Dict[str, Union[BaseException, type, tuple]],
               seed: int = 0):
    """Arm failpoints for the with-block (exclusive: no nesting)."""
    global _RNG, _ACTIVE
    if _ACTIVE:
        raise RuntimeError("failpoints already armed")
    # build everything fallible BEFORE mutating globals: a failed
    # setup must not leave points permanently armed
    rng = np.random.default_rng(seed)
    _ACTIVE = True
    try:
        _ARMED.update(points)
        _RNG = rng
        FIRED.clear()
        yield FIRED
    finally:
        _ARMED.clear()
        _RNG = None
        _ACTIVE = False
