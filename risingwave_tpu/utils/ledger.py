"""Epoch phase ledger: host/device time-and-bytes accounting.

Every barrier interval is classified into named phases (the Hazelcast
Jet stance, arxiv 2103.10169: a p99 tail you cannot attribute is a tail
you cannot fix — make every microsecond and every byte of an epoch
attributable, continuously, not in one-off cProfile runs):

- ``host_ingest``   — connector decode (JsonRowParser/CsvRowParser) and
                      source-side chunk building.
- ``host_pack``     — chunk codecs, epoch staging (backlog assembly),
                      routing-bucket computation for the sharded kernels.
- ``h2d``           — host→device upload of packed/raw matrices
                      (``jaxtools.upload``), with exact byte counts.
- ``device_compute``— the real ``instrumented_jit``/``shard_map``
                      launch sites (dispatch_span) plus ready-wait time
                      in ``jaxtools.fetch`` — under async dispatch the
                      wait-until-ready segment IS the device's compute
                      tail as seen from the host.
- ``d2h``           — materializing packed results through
                      ``jaxtools.fetch``/``start_fetch`` DMAs, with
                      exact byte counts.
- ``host_emit``     — downstream host processing: packed-matrix
                      reassembly, arena gathers, state-table writes and
                      dispatch. Measured as each non-source executor's
                      EXCLUSIVE busy time minus the named phases
                      recorded during its pulls (the residue that is
                      provably host work but not pack/transfer).
- ``barrier_wait``  — source executors parked on the barrier channel
                      (idle, not processing).
- ``backpressure_wait`` — senders parked for exchange credits (a slow
                      consumer's wall time, subtracted from the parking
                      executor's busy share — stream/monitor.py's
                      utilization tricolor carries the per-actor view).

Two disciplines keep the ledger honest:

- **Exclusive nesting.** Scopes may nest arbitrarily (a fetch inside a
  dispatch span inside an executor pull); each scope records only its
  exclusive time, so phase totals never double-count a wall-clock
  second. Executor-level residue subtracts the named time recorded
  during that executor's own pulls (an asyncio-context cell, so
  interleaved actors never cross-charge).
- **Conservation.** At barrier collection the loop seals the epoch
  against its measured interval; the uncovered remainder is published
  as ``unattributed`` — and gated in tier-1 strict mode (conftest), so
  the ledger can never silently rot: a new uninstrumented stall shows
  up as residual, not as silence.

Attribution is epoch-exact for executor work (cells flush with the
barrier that ends the epoch, the same CURR-epoch key rw_barrier_latency
uses); scopes outside any executor attribute to the newest injected
epoch (the utils/spans approximation).

Output surfaces: ``stream_epoch_phase_seconds{phase,query}`` and
``stream_transfer_bytes_total{dir,kernel}`` Prometheus families, phase
lanes + byte counter tracks in the Perfetto export (utils/spans), the
``rw_metrics_history`` per-barrier ring (utils/metrics.HISTORY — the
feed the elastic-serving control loop reads), the per-query
``phase_breakdown`` block in bench rounds, and ``ctl phases``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict, deque
from contextvars import ContextVar
from typing import Deque, Dict, Iterable, List, Optional

PHASES = ("host_ingest", "host_pack", "h2d", "device_compute", "d2h",
          "host_emit", "barrier_wait", "backpressure_wait")
UNATTRIBUTED = "unattributed"

# open-epoch accumulators kept (epochs are injected faster than sealed
# only up to the in-flight window; the bound guards leaks on epochs
# that never collect, e.g. recovery rollbacks)
OPEN_WINDOW = 64

_ENABLED = True

# active scope's child-duration accumulator (exclusive-nesting math);
# ContextVars are asyncio-task aware, so interleaved actors keep
# separate stacks
_SCOPE: ContextVar[Optional[list]] = ContextVar("ledger_scope",
                                                default=None)
# active executor attribution cell (stream/monitor.py pushes around
# each inner pull; named phases recorded during the pull land here and
# flush epoch-exactly at the barrier)
_CELL: ContextVar[Optional["AttributionCell"]] = ContextVar(
    "ledger_cell", default=None)
# current kernel identity for transfer/compute attribution
_KERNEL: ContextVar[str] = ContextVar("ledger_kernel", default="")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def parse_ledger(spec: str) -> bool:
    """'on'|'off' → bool (SET stream_ledger validator; PlanError so a
    typo fails the SET, not a later epoch)."""
    s = str(spec).strip().lower()
    if s in ("on", "true", "1"):
        return True
    if s in ("off", "false", "0"):
        return False
    from risingwave_tpu.frontend.planner import PlanError
    raise PlanError(f"stream_ledger must be on|off, got {spec!r}")


def current_kernel() -> str:
    return _KERNEL.get()


@contextlib.contextmanager
def kernel_scope(label: str):
    """Stamp transfers/compute recorded in the block with `label`."""
    tok = _KERNEL.set(label)
    try:
        yield
    finally:
        _KERNEL.reset(tok)


def note_backlog(kernel: str, rows: float) -> None:
    """Record one epoch-batch dispatch's staged-row volume (the
    stream_epoch_backlog_rows gauge behind the Perfetto backlog
    counter track) — the ONE copy all four epoch-batching kernels
    call at their backlog flush."""
    if not _ENABLED:
        return
    from risingwave_tpu.utils.metrics import STREAMING
    STREAMING.backlog_rows.set(float(rows), kernel=kernel)


class AttributionCell:
    """Named-phase seconds + transfer bytes recorded during one
    executor's pulls since the last barrier (stream/monitor.py owns
    one per wrapped executor and flushes it epoch-exactly)."""

    __slots__ = ("seconds", "h2d_bytes", "d2h_bytes")

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}
        self.h2d_bytes = 0
        self.d2h_bytes = 0

    def named_total(self) -> float:
        return sum(self.seconds.values())

    def take(self):
        """Pop the accumulated contents (flush-at-barrier)."""
        out = (self.seconds, self.h2d_bytes, self.d2h_bytes)
        self.seconds = {}
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        return out


class _EpochAcc:
    """Open accumulator for one epoch (pre-seal)."""

    __slots__ = ("seconds", "h2d_bytes", "d2h_bytes", "warmup", "idle")

    def __init__(self) -> None:
        self.seconds: Dict[str, float] = {}
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.warmup = False     # saw a kernel (re)compile this epoch
        # per-SOURCE idle seconds (barrier_wait) — kept keyed so the
        # seal can take the across-source MAX instead of the sum:
        # parallel sources park CONCURRENTLY, and summing their idle
        # against one wall-clock interval double-counts it (the
        # BENCH_r10 ad-ctr share-1.05 bug)
        self.idle: Dict[str, float] = {}

    def add(self, phase: str, s: float) -> None:
        if s > 0:
            self.seconds[phase] = self.seconds.get(phase, 0.0) + s

    def add_idle(self, key: str, s: float) -> None:
        if s > 0:
            self.idle[key] = self.idle.get(key, 0.0) + s

    def idle_max(self) -> float:
        return max(self.idle.values()) if self.idle else 0.0


class LedgerRecord:
    """One sealed epoch's phase breakdown."""

    __slots__ = ("epoch", "kind", "interval_s", "seconds", "h2d_bytes",
                 "d2h_bytes", "warmup", "distributed", "workers",
                 "idle_max", "domain")

    def __init__(self, epoch: int, kind: str, interval_s: float,
                 seconds: Dict[str, float], h2d_bytes: int,
                 d2h_bytes: int, warmup: bool, distributed: bool,
                 domain: str = ""):
        self.epoch = epoch
        self.kind = kind
        # barrier domain whose loop sealed this epoch ("" = global):
        # domains partition wall time INDEPENDENTLY — two domains'
        # records legitimately cover the same wall-clock second, and
        # conservation holds per record because epochs are domain-
        # unique (the shared allocator)
        self.domain = domain
        self.interval_s = interval_s
        self.seconds = seconds          # includes UNATTRIBUTED
        self.h2d_bytes = h2d_bytes
        self.d2h_bytes = d2h_bytes
        self.warmup = warmup
        # sealed on a cluster coordinator BEFORE worker ledgers merged:
        # conservation is not checkable until drain_ledger folds them in
        self.distributed = distributed
        self.workers: List[str] = []    # merged-in worker tags
        # largest single-source idle folded into barrier_wait so far
        # (worker merges take max-then-cap, never sum — see
        # attribute_idle)
        self.idle_max = 0.0

    @property
    def attributed_s(self) -> float:
        return sum(s for p, s in self.seconds.items()
                   if p != UNATTRIBUTED)

    @property
    def unattributed_s(self) -> float:
        return self.seconds.get(UNATTRIBUTED, 0.0)

    def coverage(self) -> float:
        """Attributed fraction of the barrier interval (capped at 1:
        concurrent host threads can oversum wall clock)."""
        if self.interval_s <= 0:
            return 1.0
        return min(1.0, self.attributed_s / self.interval_s)

    def recompute_unattributed(self) -> None:
        named = self.attributed_s
        resid = max(0.0, self.interval_s - named)
        if resid > 0:
            self.seconds[UNATTRIBUTED] = resid
        else:
            self.seconds.pop(UNATTRIBUTED, None)

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "kind": self.kind,
                "domain": self.domain,
                "interval_s": self.interval_s,
                "seconds": dict(self.seconds),
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "warmup": self.warmup,
                "distributed": self.distributed,
                "workers": list(self.workers)}


class PhaseLedger:
    """Process-global phase ledger (worker processes drain theirs to
    the coordinator over the control channel, like the span tracer)."""

    # conservation gate (tier-1 strict mode, conftest): a steady-state
    # epoch longer than GATE_MIN_INTERVAL_S whose residual exceeds
    # BOTH the fraction and the absolute floor is a violation. The
    # floor absorbs fixed per-barrier machinery (event loop, barrier
    # send/collect) that dominates micro-epochs; the fraction is the
    # rot detector on real epochs.
    GATE_MIN_INTERVAL_S = 0.4
    GATE_RESIDUAL_FRAC = 0.35
    GATE_RESIDUAL_MIN_S = 0.25

    def __init__(self, window: int = 512):
        self.window = window
        self._open: "OrderedDict[int, _EpochAcc]" = OrderedDict()
        self.records: Deque[LedgerRecord] = deque(maxlen=window)
        # label stamped on the stream_epoch_phase_seconds query axis
        # (bench sets it per lane; sessions leave it "")
        self.query = ""
        # cell commits race the uploader's worker threads' scopes
        self._lock = threading.Lock()

    # module-level kernel-context scope, re-exported on the instance
    # (call sites hold LEDGER, not the module)
    kernel_scope = staticmethod(kernel_scope)

    # -- recording -----------------------------------------------------
    def _acc(self, epoch: Optional[int] = None) -> _EpochAcc:
        if epoch is None:
            from risingwave_tpu.utils import spans as _spans
            epoch = _spans.current_epoch()
        acc = self._open.get(epoch)
        if acc is None:
            acc = self._open[epoch] = _EpochAcc()
            while len(self._open) > OPEN_WINDOW:
                self._open.popitem(last=False)
        return acc

    @contextlib.contextmanager
    def phase(self, name: str, kernel: Optional[str] = None):
        """Scoped timer: the block's EXCLUSIVE wall time (minus nested
        scopes) lands in `name` — in the active executor cell when one
        is set (epoch-exact flush at the barrier), else directly in the
        newest injected epoch's accumulator."""
        if not _ENABLED:
            yield
            return
        parent = _SCOPE.get()
        mine = [0.0]
        tok = _SCOPE.set(mine)
        ktok = _KERNEL.set(kernel) if kernel else None
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            _SCOPE.reset(tok)
            if ktok is not None:
                _KERNEL.reset(ktok)
            if parent is not None:
                parent[0] += dur
            excl = max(0.0, dur - mine[0])
            cell = _CELL.get()
            if cell is not None:
                cell.seconds[name] = cell.seconds.get(name, 0.0) + excl
            else:
                with self._lock:
                    self._acc().add(name, excl)

    def attribute(self, name: str, seconds: float,
                  epoch: Optional[int] = None) -> None:
        """Direct (non-scoped) attribution — executor residue, source
        barrier_wait, barrier-loop commit work."""
        if not _ENABLED or seconds <= 0:
            return
        with self._lock:
            self._acc(epoch).add(name, seconds)

    def attribute_idle(self, seconds: float,
                       epoch: Optional[int] = None,
                       source: str = "") -> None:
        """Source park time (barrier_wait), keyed per source. Parallel
        sources idle CONCURRENTLY — the seal folds the across-source
        MAX (the union approximation) into ``barrier_wait`` instead of
        the sum, so N idle sources can never claim N× the epoch
        (share > 1.0 is definitionally noise)."""
        if not _ENABLED or seconds <= 0:
            return
        with self._lock:
            self._acc(epoch).add_idle(source, seconds)

    def add_bytes(self, direction: str, nbytes: int,
                  kernel: Optional[str] = None) -> None:
        """One host↔device transfer's payload: live Prometheus counter
        (stream_transfer_bytes_total{dir,kernel}) plus the per-epoch
        byte accumulators behind the Perfetto counter tracks."""
        if not _ENABLED or nbytes <= 0:
            return
        from risingwave_tpu.utils.metrics import STREAMING
        STREAMING.transfer_bytes.inc(
            float(nbytes), dir=direction,
            kernel=kernel or _KERNEL.get() or "unlabeled")
        cell = _CELL.get()
        if cell is not None:
            if direction == "h2d":
                cell.h2d_bytes += int(nbytes)
            else:
                cell.d2h_bytes += int(nbytes)
            return
        with self._lock:
            acc = self._acc()
            if direction == "h2d":
                acc.h2d_bytes += int(nbytes)
            else:
                acc.d2h_bytes += int(nbytes)

    def note_compile(self) -> None:
        """A kernel (re)trace marks the epoch warmup: compile stalls
        are expected to blow the conservation budget and are exempt
        from the strict gate (the RecompileGuard polices them)."""
        if not _ENABLED:
            return
        with self._lock:
            self._acc().warmup = True

    # -- executor cells (stream/monitor.py) ----------------------------
    def push_cell(self, cell: AttributionCell):
        return _CELL.set(cell)

    def pop_cell(self, token) -> None:
        _CELL.reset(token)

    def commit_cell(self, epoch: int, cell: AttributionCell) -> None:
        """Fold one executor's cell into the epoch it just finished
        (called at barrier passage with the barrier's CURR epoch)."""
        if not _ENABLED:
            cell.take()
            return
        seconds, h2d, d2h = cell.take()
        if not seconds and not h2d and not d2h:
            return
        with self._lock:
            acc = self._acc(epoch)
            for name, s in seconds.items():
                acc.add(name, s)
            acc.h2d_bytes += h2d
            acc.d2h_bytes += d2h

    # -- sealing -------------------------------------------------------
    def seal(self, epoch: int, interval_s: float, kind: str = "barrier",
             distributed: bool = False,
             warmup: bool = False,
             domain: str = "") -> Optional[LedgerRecord]:
        """Close the epoch's books against its measured barrier
        interval: residual → ``unattributed``, publish the Prometheus
        phase family, the trace phase lanes + counter tracks, and the
        rw_metrics_history row. ``warmup=True`` force-exempts the
        epoch from the conservation gate (callers pass it for
        mutation/topology barriers — deploy work is not epoch work).
        ``domain`` keys the record (and its history row) by the barrier
        domain that ran the epoch — overlapped domains each partition
        their OWN wall timeline, so per-record conservation survives
        the compute/ingest overlap."""
        if not _ENABLED:
            self._open.pop(epoch, None)
            return None
        with self._lock:
            acc = self._open.pop(epoch, None) or _EpochAcc()
        seconds = dict(acc.seconds)
        idle = acc.idle_max()
        if idle > 0:
            # across-source MAX (concurrent parks overlap), capped at
            # the interval — idle can never exceed the epoch it's in
            if interval_s > 0:
                idle = min(idle, float(interval_s))
            seconds["barrier_wait"] = seconds.get("barrier_wait",
                                                  0.0) + idle
        rec = LedgerRecord(epoch, kind, float(interval_s),
                           seconds, acc.h2d_bytes,
                           acc.d2h_bytes, acc.warmup or warmup,
                           distributed, domain=domain)
        rec.idle_max = idle
        rec.recompute_unattributed()
        self.records.append(rec)
        self._publish(rec)
        return rec

    def discard(self, epoch: int) -> None:
        """Drop an open epoch without sealing (virtual-clock loops:
        the measured interval is simulated time, which the wall-clock
        phases can never cover)."""
        with self._lock:
            self._open.pop(epoch, None)

    def _publish(self, rec: LedgerRecord) -> None:
        from risingwave_tpu.utils import spans as _spans
        from risingwave_tpu.utils.metrics import HISTORY, STREAMING
        q = self.query
        for name, s in rec.seconds.items():
            STREAMING.epoch_phase_seconds.inc(s, phase=name, query=q)
        extra = {f"phase.{p}": rec.seconds.get(p, 0.0)
                 for p in PHASES + (UNATTRIBUTED,)}
        extra["coverage"] = rec.coverage()
        extra["epoch_h2d_bytes"] = float(rec.h2d_bytes)
        extra["epoch_d2h_bytes"] = float(rec.d2h_bytes)
        # per-MV freshness of this domain's barrier (ISSUE 14): the
        # materialize passages keyed by the same CURR epoch — so the
        # autoscaler's rw_metrics_history feed carries event-time lag
        # next to the phase shares it must explain
        from risingwave_tpu.stream.freshness import FRESHNESS
        extra.update(FRESHNESS.history_extra(rec.epoch, rec.domain))
        # per-MV cost split of the same sealed epoch (ISSUE 16): the
        # executor cells committed for this epoch roll up by owning MV
        # here, so rw_metrics_history carries mv_device_s.<mv> columns
        # next to the phase shares they partition
        from risingwave_tpu.stream import costs as _costs
        extra.update(_costs.COSTS.history_extra(rec))
        HISTORY.observe(rec.epoch, rec.interval_s, extra=extra,
                        domain=rec.domain)
        if not _spans.enabled():
            return
        now = time.time()
        at = now - rec.interval_s
        for name in PHASES + (UNATTRIBUTED,):
            s = rec.seconds.get(name, 0.0)
            if s <= 0:
                continue
            # phase lanes: stacked from the interval start in taxonomy
            # order — a share view, not a literal timeline (phases
            # interleave within the epoch)
            _spans.EPOCH_TRACER.record(
                f"phase.{name}", "phase", epoch=rec.epoch, start_s=at,
                dur_s=s, share=round(s / rec.interval_s, 4)
                if rec.interval_s > 0 else 0.0,
                **({"domain": rec.domain} if rec.domain else {}))
            at += s
        # counter-track sample (export_chrome renders 'C' events)
        _spans.EPOCH_TRACER.record(
            "ledger.counters", "counter", epoch=rec.epoch, start_s=now,
            transfer_h2d_bytes=rec.h2d_bytes,
            transfer_d2h_bytes=rec.d2h_bytes,
            uploader_queue_depth=STREAMING.uploader_queue_depth.get(),
            backlog_rows=sum(v for _l, v in
                             STREAMING.backlog_rows.series()))

    # -- conservation gate ---------------------------------------------
    def gate_violations(self) -> List[tuple]:
        """(epoch, interval_s, unattributed_s, coverage, domain) per
        sealed steady-state epoch over budget — the tier-1 strict-mode
        gate, domain-keyed so a multi-domain violation names the
        alignment domain whose books leaked."""
        out = []
        for rec in self.records:
            if rec.warmup or rec.distributed:
                continue
            if rec.interval_s < self.GATE_MIN_INTERVAL_S:
                continue
            resid = rec.unattributed_s
            if resid > max(self.GATE_RESIDUAL_FRAC * rec.interval_s,
                           self.GATE_RESIDUAL_MIN_S):
                out.append((rec.epoch, rec.interval_s, resid,
                            rec.coverage(), rec.domain))
        return out

    # -- cross-process merge (cluster drain, like spans.drain_dicts) ---
    def drain_dicts(self) -> List[dict]:
        """Pop every OPEN accumulator as plain dicts (worker →
        coordinator: workers never seal — the coordinator owns the
        barrier interval)."""
        with self._lock:
            out = [{"epoch": e, "seconds": dict(a.seconds),
                    "h2d_bytes": a.h2d_bytes, "d2h_bytes": a.d2h_bytes,
                    "warmup": a.warmup, "idle_max": a.idle_max()}
                   for e, a in self._open.items()]
            self._open.clear()
        return out

    def ingest(self, dicts: Iterable[dict], worker: str = "",
               resolve: bool = True) -> int:
        """Merge drained worker accumulators: into the sealed record
        of the same epoch when one exists (recomputing the residual —
        this is what resolves a distributed record's conservation),
        else into the open accumulator. ``resolve=False`` keeps the
        record conservation-exempt: the caller knows some worker's
        books never arrived (a dead slot), so the residual would be
        a phantom of the missing process, not rot.

        Merged seconds are also published into the
        stream_epoch_phase_seconds family so the cluster's Prometheus
        view carries worker time, not just the coordinator's (the
        residual correction, in contrast, lives only in the records —
        a counter cannot un-count the already-published coordinator
        `unattributed`; rw_metrics_history rows likewise keep their
        seal-time coordinator view)."""
        from risingwave_tpu.utils.metrics import STREAMING
        by_epoch = {r.epoch: r for r in self.records}
        n = 0
        for d in dicts:
            e = int(d["epoch"])
            rec = by_epoch.get(e)
            if rec is not None:
                for name, s in (d.get("seconds") or {}).items():
                    rec.seconds[name] = rec.seconds.get(name, 0.0) \
                        + float(s)
                    STREAMING.epoch_phase_seconds.inc(
                        float(s), phase=name, query=self.query)
                w_idle = float(d.get("idle_max", 0.0))
                if w_idle > 0:
                    # barrier_wait merges as MAX-then-cap across
                    # processes (their sources park over the same wall
                    # interval), never as a sum
                    cap = rec.interval_s if rec.interval_s > 0 \
                        else float("inf")
                    new_max = max(rec.idle_max, w_idle)
                    delta = min(new_max, cap) - min(rec.idle_max, cap)
                    rec.idle_max = new_max
                    if delta > 0:
                        rec.seconds["barrier_wait"] = \
                            rec.seconds.get("barrier_wait", 0.0) + delta
                rec.h2d_bytes += int(d.get("h2d_bytes", 0))
                rec.d2h_bytes += int(d.get("d2h_bytes", 0))
                rec.warmup = rec.warmup or bool(d.get("warmup"))
                if worker and worker not in rec.workers:
                    rec.workers.append(worker)
                if resolve:
                    rec.distributed = False  # conservation checkable
                rec.recompute_unattributed()
            else:
                with self._lock:
                    acc = self._acc(e)
                    for name, s in (d.get("seconds") or {}).items():
                        acc.add(name, float(s))
                    w_idle = float(d.get("idle_max", 0.0))
                    if w_idle > 0:
                        acc.add_idle(worker or "remote", w_idle)
                    acc.h2d_bytes += int(d.get("h2d_bytes", 0))
                    acc.d2h_bytes += int(d.get("d2h_bytes", 0))
                    acc.warmup = acc.warmup or bool(d.get("warmup"))
            n += 1
        return n

    # -- reads ---------------------------------------------------------
    # epochs shorter than this carry only fixed barrier machinery (an
    # empty heartbeat is ~sub-ms of inject/collect bookkeeping): they
    # hold no meaningful share of a run and are excluded from the
    # coverage statistics (still counted, still summed into phases)
    MICRO_EPOCH_S = 0.005

    def phase_breakdown(self, steady_only: bool = True,
                        domain: Optional[str] = None) -> dict:
        """Aggregate share view over sealed epochs (bench's per-query
        ``phase_breakdown`` block and the ``ctl phases`` totals).
        ``steady_only`` drops warmup (compile-bearing) epochs;
        ``domain`` restricts to one barrier domain's records (the
        per-domain bench breakdown)."""
        recs = [r for r in self.records
                if not (steady_only and r.warmup)
                and (domain is None or r.domain == domain)]
        if not recs:
            return {"epochs": 0}
        total = sum(r.interval_s for r in recs)
        phases = {}
        for name in PHASES + (UNATTRIBUTED,):
            s = sum(r.seconds.get(name, 0.0) for r in recs)
            if s > 0 or name == UNATTRIBUTED:
                phases[name] = {
                    "seconds": round(s, 6),
                    "share": round(s / total, 4) if total > 0 else 0.0}
        full = [r for r in recs if r.interval_s >= self.MICRO_EPOCH_S]
        covs = [r.coverage() for r in (full or recs)]
        return {
            "epochs": len(recs),
            "micro_epochs": len(recs) - len(full),
            "interval_s": round(total, 6),
            "phases": phases,
            "coverage_mean": round(sum(covs) / len(covs), 4),
            "coverage_min": round(min(covs), 4),
            "h2d_bytes": int(sum(r.h2d_bytes for r in recs)),
            "d2h_bytes": int(sum(r.d2h_bytes for r in recs)),
        }

    def domains_seen(self) -> List[str]:
        """Distinct barrier domains among the sealed records (bench's
        per-domain breakdown iterates these)."""
        seen: List[str] = []
        for r in self.records:
            if r.domain not in seen:
                seen.append(r.domain)
        return seen

    def report(self, last_n: int = 16) -> str:
        """Human-readable per-epoch table (``ctl phases``)."""
        lines = []
        for rec in list(self.records)[-last_n:]:
            head = (f"epoch {rec.epoch:#x} ({rec.kind}"
                    f"{', warmup' if rec.warmup else ''}): "
                    f"{rec.interval_s * 1e3:.2f}ms, coverage "
                    f"{rec.coverage() * 100:.0f}%")
            lines.append(head)
            for name in PHASES + (UNATTRIBUTED,):
                s = rec.seconds.get(name, 0.0)
                if s <= 0:
                    continue
                share = (100.0 * s / rec.interval_s
                         if rec.interval_s > 0 else 0.0)
                lines.append(f"  {name:<15} {s * 1e3:9.2f}ms "
                             f"{share:5.1f}%")
            if rec.h2d_bytes or rec.d2h_bytes:
                lines.append(f"  bytes: h2d={rec.h2d_bytes} "
                             f"d2h={rec.d2h_bytes}")
        return "\n".join(lines)

    def clear(self) -> None:
        with self._lock:
            self._open.clear()
            self.records.clear()


# the process-global ledger (worker processes drain to the coordinator)
LEDGER = PhaseLedger()
