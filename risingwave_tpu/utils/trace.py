"""Tracing: lightweight spans + an actor await-state registry.

Reference parity: the tracing-crate spans threaded through the
reference (barrier TracingContext, src/stream/src/executor/mod.rs:253)
and the await-tree actor stack dumps exposed by MonitorService
(src/compute/src/rpc/service/monitor_service.rs:72) — reduced to a
ring buffer of spans plus a per-actor "currently awaiting" table that
a debugger (or test) can dump when a barrier stalls.
"""

from __future__ import annotations

import contextvars
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple


@dataclass
class Span:
    name: str
    start_s: float
    end_s: float = 0.0
    attrs: Dict[str, object] = field(default_factory=dict)
    parent: Optional[str] = None

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end_s - self.start_s)


# per-task span stack: concurrent actors must not see each other's
# frames (a shared list would cross-attribute parents under asyncio)
_SPAN_STACK: contextvars.ContextVar[Tuple[str, ...]] = \
    contextvars.ContextVar("rw_span_stack", default=())


class Tracer:
    """Ring buffer of completed spans (OTLP-export seam)."""

    def __init__(self, capacity: int = 4096,
                 clock=time.monotonic) -> None:
        self.spans: Deque[Span] = deque(maxlen=capacity)
        self.clock = clock

    @contextmanager
    def span(self, name: str, **attrs):
        stack = _SPAN_STACK.get()
        s = Span(name, self.clock(),
                 attrs=attrs,
                 parent=stack[-1] if stack else None)
        token = _SPAN_STACK.set(stack + (name,))
        try:
            yield s
        finally:
            _SPAN_STACK.reset(token)
            s.end_s = self.clock()
            self.spans.append(s)

    def find(self, name: str) -> List[Span]:
        return [s for s in self.spans if s.name == name]


GLOBAL_TRACER = Tracer()


class AwaitRegistry:
    """Who is waiting on what (await-tree analog).

    Actors/executors report their current await point; ``dump()`` shows
    the live picture — the first tool to reach for when an epoch never
    collects.
    """

    def __init__(self, clock=time.monotonic) -> None:
        self._state: Dict[str, tuple] = {}
        self.clock = clock

    def enter(self, who: str, what: str) -> None:
        self._state[who] = (what, self.clock())

    def exit(self, who: str) -> None:
        self._state.pop(who, None)

    def dump(self) -> str:
        now = self.clock()
        lines = []
        for who in sorted(self._state):
            what, since = self._state[who]
            lines.append(f"{who}: {what} [{now - since:.3f}s]")
        return "\n".join(lines)


GLOBAL_AWAITS = AwaitRegistry()
