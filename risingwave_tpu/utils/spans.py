"""Epoch-causal tracing: the flight recorder behind rw_epoch_trace.

Reference parity: the tracing-crate spans the reference threads from
barrier inject through every executor (TracingContext on Barrier,
src/stream/src/executor/mod.rs:253) plus the await-tree dumps — grown
into what arxiv 2103.10169 (Hazelcast Jet) treats as table stakes for
a p99 latency discipline: every epoch's barrier round leaves a causal
timeline (inject → per-actor executor processing → exchange transfer →
device dispatch → async upload → commit), so a slow barrier is a
navigable trace, not one opaque number.

Design:

- **Always on, bounded.** Recording is a dict append; the flight
  recorder keeps the last `EPOCH_WINDOW` epochs, each capped at
  `MAX_SPANS_PER_EPOCH` spans (drops are counted, never silent).
  ``set_enabled(False)`` (SET stream_trace = off) reduces every hook
  to one predicate check.
- **Keyed by the barrier's CURR epoch** — the same key
  rw_barrier_latency rows use, so a profile row and its trace join
  trivially. Spans recorded between barriers (device dispatches)
  attribute to the most recently *injected* epoch; with a deep
  in-flight window that is an approximation, exact under the
  stepping/bench drivers (in_flight drains before the next inject).
- **Wall-clock timestamps** (`time.time()`): spans merge across
  worker processes on one host, where monotonic clocks don't compare.
- **Promotion.** The slow-barrier watchdog (meta/barrier.py) moves an
  over-threshold epoch's spans into a retained store (`RETAIN_SLOTS`
  traces) with a one-line straggler diagnosis, surviving after the
  flight ring has rolled past the epoch.
- Export: `export_chrome()` renders Chrome trace-event JSON (Perfetto
  loads it directly); `rows()` backs the rw_epoch_trace system table.

Span ids embed the process id in their high bits so traces drained
from worker processes merge without collisions.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

EPOCH_WINDOW = 64          # epochs kept in the flight ring
MAX_SPANS_PER_EPOCH = 2048  # per-epoch span cap (overflow is counted)
RETAIN_SLOTS = 32          # promoted (slow-barrier) traces kept


@dataclass
class TraceSpan:
    """One timed event in an epoch's causal timeline."""

    name: str                       # e.g. "HashAggExecutor(actor=7)"
    cat: str                        # barrier|actor|exchange|dispatch|
    #                                 compile|upload|commit|diagnosis
    epoch: int                      # barrier CURR epoch value
    start_s: float                  # wall clock (time.time())
    dur_s: float
    span_id: int
    parent_id: Optional[int] = None
    worker: str = ""                # "" = this process / coordinator
    actor: Optional[int] = None
    args: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {"name": self.name, "cat": self.cat, "epoch": self.epoch,
             "start_s": self.start_s, "dur_s": self.dur_s,
             "span_id": self.span_id}
        if self.parent_id is not None:
            d["parent_id"] = self.parent_id
        if self.worker:
            d["worker"] = self.worker
        if self.actor is not None:
            d["actor"] = self.actor
        if self.args:
            d["args"] = self.args
        return d

    @staticmethod
    def from_dict(d: dict) -> "TraceSpan":
        return TraceSpan(
            d["name"], d["cat"], int(d["epoch"]), float(d["start_s"]),
            float(d["dur_s"]), int(d["span_id"]),
            parent_id=(None if d.get("parent_id") is None
                       else int(d["parent_id"])),
            worker=d.get("worker", ""),
            actor=(None if d.get("actor") is None
                   else int(d["actor"])),
            args=dict(d.get("args") or {}))


# -- global switches -------------------------------------------------------

_ENABLED = True           # always-on flight recorder; SET stream_trace
_CURRENT_EPOCH = 0        # newest INJECTED epoch (see module docstring)


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def parse_trace(spec: str) -> bool:
    """'on'|'off' → bool (SET stream_trace validator; PlanError so a
    typo fails the SET, not a later epoch)."""
    s = str(spec).strip().lower()
    if s in ("on", "true", "1"):
        return True
    if s in ("off", "false", "0"):
        return False
    from risingwave_tpu.frontend.planner import PlanError
    raise PlanError(f"stream_trace must be on|off, got {spec!r}")


def set_current_epoch(value: int) -> None:
    global _CURRENT_EPOCH
    _CURRENT_EPOCH = int(value)


def current_epoch() -> int:
    return _CURRENT_EPOCH


class EpochTracer:
    """Per-epoch span ring (flight recorder) + retained slow traces."""

    def __init__(self, epoch_window: int = EPOCH_WINDOW,
                 max_spans: int = MAX_SPANS_PER_EPOCH,
                 retain_slots: int = RETAIN_SLOTS):
        self.epoch_window = epoch_window
        self.max_spans = max_spans
        self.retain_slots = retain_slots
        # epoch -> [TraceSpan] in record order (ring by insertion)
        self._flight: "OrderedDict[int, List[TraceSpan]]" = OrderedDict()
        # epoch -> [spans, diagnosis, barrier total_s] promoted by the
        # watchdog (total_s kept so a later cross-process span merge
        # can recompute the straggler line over the full picture)
        self._retained: "OrderedDict[int, list]" = OrderedDict()
        self._roots: Dict[int, int] = {}     # epoch -> root span id
        self.dropped = 0                     # spans over the epoch cap
        # pid in the high bits: ids minted in a worker process never
        # collide with the coordinator's when traces merge
        self._ids = itertools.count((os.getpid() & 0xFFFF) << 32 | 1)
        # appends race the uploader's commit callback thread; one
        # uncontended acquire per span is noise next to the work the
        # span describes
        self._lock = threading.Lock()

    # -- recording -----------------------------------------------------
    def next_id(self) -> int:
        return next(self._ids)

    def record(self, name: str, cat: str, epoch: Optional[int] = None,
               start_s: Optional[float] = None, dur_s: float = 0.0,
               parent: Optional[int] = None, actor: Optional[int] = None,
               worker: str = "", span_id: Optional[int] = None,
               **args) -> int:
        """Append one completed span; returns its id (0 if disabled)."""
        if not _ENABLED:
            return 0
        e = _CURRENT_EPOCH if epoch is None else int(epoch)
        if parent is None:
            parent = self._roots.get(e)
        s = TraceSpan(name, cat, e,
                      time.time() if start_s is None else start_s,
                      dur_s, span_id if span_id is not None
                      else self.next_id(),
                      parent_id=parent, worker=worker, actor=actor,
                      args=args)
        self._append(s)
        return s.span_id

    def _append(self, s: TraceSpan) -> None:
        with self._lock:
            bucket = self._flight.get(s.epoch)
            if bucket is None:
                bucket = self._flight[s.epoch] = []
                while len(self._flight) > self.epoch_window:
                    old, spans = self._flight.popitem(last=False)
                    self._roots.pop(old, None)
            if len(bucket) >= self.max_spans:
                self.dropped += 1
                from risingwave_tpu.utils.metrics import STREAMING
                STREAMING.trace_spans_dropped.inc()
                return
            bucket.append(s)

    def set_root(self, epoch: int, span_id: int) -> None:
        """The epoch's inject span: default parent for every span
        recorded into that epoch without an explicit parent."""
        self._roots[epoch] = span_id

    def root_id(self, epoch: int) -> Optional[int]:
        return self._roots.get(epoch)

    # -- promotion (slow-barrier watchdog) -----------------------------
    def promote(self, epoch: int, diagnosis: str = "",
                total_s: float = 0.0) -> None:
        """Retain the epoch's full trace past the flight ring's life."""
        with self._lock:
            spans = list(self._flight.get(epoch, ()))
            self._retained[epoch] = [spans, diagnosis, total_s]
            while len(self._retained) > self.retain_slots:
                self._retained.popitem(last=False)

    def refresh_diagnoses(self) -> None:
        """Recompute each retained trace's straggler line — called
        after a worker-span merge, when the coordinator-side diagnosis
        predates the per-actor spans that name the real laggard."""
        for e in list(self._retained):
            entry = self._retained.get(e)
            if entry is not None and entry[2] > 0:
                entry[1] = self.diagnose(e, entry[2])

    def diagnose(self, epoch: int, total_s: float) -> str:
        """One-line straggler attribution: the largest actor-phase span
        of the epoch as actor/executor/phase/% of the barrier round."""
        spans = self.spans_for(epoch)
        # upload spans are excluded: the async checkpoint tail is
        # overlapped with younger barriers and deliberately NOT part
        # of barrier total_s (EpochProfile) — naming it as the
        # straggler would misdirect the operator from the real laggard
        work = [s for s in spans
                if s.cat in ("actor", "dispatch", "exchange")]
        if not work or total_s <= 0:
            return (f"epoch {epoch:#x}: no per-actor spans recorded "
                    f"({total_s * 1e3:.1f}ms barrier)")
        top = max(work, key=lambda s: s.dur_s)
        who = f"actor {top.actor} " if top.actor is not None else ""
        where = f"@{top.worker} " if top.worker else ""
        return (f"epoch {epoch:#x}: straggler {who}{where}"
                f"{top.name} phase={top.cat} "
                f"{top.dur_s * 1e3:.1f}ms = "
                f"{min(100.0, 100.0 * top.dur_s / total_s):.0f}% of "
                f"{total_s * 1e3:.1f}ms barrier")

    # -- reads ---------------------------------------------------------
    def epochs(self) -> List[int]:
        with self._lock:
            return sorted(set(self._flight) | set(self._retained))

    def spans_for(self, epoch: int) -> List[TraceSpan]:
        """Flight + retained spans of one epoch (retained wins on
        overlap — it was snapshotted from the same bucket)."""
        with self._lock:
            if epoch in self._retained:
                spans = self._retained[epoch][0]
                flight = self._flight.get(epoch, ())
                seen = {s.span_id for s in spans}
                return spans + [s for s in flight
                                if s.span_id not in seen]
            return list(self._flight.get(epoch, ()))

    def diagnosis_for(self, epoch: int) -> str:
        entry = self._retained.get(epoch)
        return entry[1] if entry else ""

    def retained_epochs(self) -> List[int]:
        return list(self._retained)

    def rows(self) -> List[tuple]:
        """(epoch, span_id, parent_id, name, cat, worker, actor,
        start_s, dur_s, retained, detail) per span — the rw_epoch_trace
        payload. Retained traces contribute one extra cat='diagnosis'
        row carrying the straggler line."""
        out = []
        for e in self.epochs():
            retained = 1 if e in self._retained else 0
            for s in self.spans_for(e):
                out.append((s.epoch, s.span_id,
                            s.parent_id if s.parent_id is not None
                            else 0,
                            s.name, s.cat, s.worker,
                            s.actor if s.actor is not None else -1,
                            s.start_s, s.dur_s, retained,
                            json.dumps(s.args) if s.args else ""))
            diag = self.diagnosis_for(e)
            if diag:
                out.append((e, 0, 0, diag, "diagnosis", "", -1,
                            0.0, 0.0, 1, ""))
        return out

    # -- cross-process merge -------------------------------------------
    def drain_dicts(self) -> List[dict]:
        """Pop every span as plain dicts (worker → coordinator drain;
        a second drain returns only spans recorded since)."""
        with self._lock:
            out = [s.to_dict() for spans in self._flight.values()
                   for s in spans]
            seen = {d["span_id"] for d in out}
            for entry in self._retained.values():
                out += [s.to_dict() for s in entry[0]
                        if s.span_id not in seen]
            self._flight.clear()
            self._retained.clear()
        return out

    def ingest(self, dicts: Iterable[dict], worker: str = "") -> int:
        """Merge drained spans (tagging their origin process)."""
        n = 0
        for d in dicts:
            s = TraceSpan.from_dict(d)
            if worker and not s.worker:
                s.worker = worker
            # re-promote into retained if this epoch was promoted here
            self._append(s)
            with self._lock:
                entry = self._retained.get(s.epoch)
                if entry is not None and \
                        all(x.span_id != s.span_id for x in entry[0]):
                    entry[0].append(s)
            n += 1
        return n

    # -- export --------------------------------------------------------
    def export_chrome(self, epochs: Optional[Iterable[int]] = None
                      ) -> dict:
        """Chrome trace-event JSON (Perfetto-loadable): one 'X' event
        per span (pid = worker, tid = actor or category) plus 's'/'f'
        flow events binding each span to its parent — the causal edges
        survive across process lanes."""
        def lane(s: TraceSpan) -> Tuple[str, str]:
            return (s.worker or "coordinator",
                    f"actor-{s.actor}" if s.actor is not None
                    else s.cat)

        events = []
        want = self.epochs() if epochs is None else sorted(set(epochs))
        for e in want:
            spans = self.spans_for(e)
            by_id = {s.span_id: s for s in spans}
            for s in spans:
                if s.cat == "counter":
                    # counter tracks ('C' events): one per numeric arg
                    # — transfer bytes, uploader queue depth, backlog
                    # rows sampled at each epoch seal render as value-
                    # over-time lanes next to the span timeline
                    for key, val in s.args.items():
                        if not isinstance(val, (int, float)):
                            continue
                        events.append({
                            "name": key, "cat": "counter", "ph": "C",
                            "ts": s.start_s * 1e6,
                            "pid": s.worker or "coordinator",
                            "args": {"value": float(val)}})
                    continue
                pid, tid = lane(s)
                ts = s.start_s * 1e6
                dur = max(s.dur_s * 1e6, 1.0)
                args = {"epoch": f"{s.epoch:#x}",
                        "span_id": s.span_id, **s.args}
                if s.parent_id is not None:
                    args["parent_id"] = s.parent_id
                events.append({"name": s.name, "cat": s.cat, "ph": "X",
                               "ts": ts, "dur": dur, "pid": pid,
                               "tid": tid, "args": args})
                parent = (by_id.get(s.parent_id)
                          if s.parent_id is not None else None)
                if parent is not None:
                    # one flow id per causal edge (the child's span
                    # id): 's' leaves the PARENT's slice, 'f' lands on
                    # the child's start — Perfetto draws parent→child.
                    # The start is clamped to never postdate the
                    # finish (a zero-duration root would otherwise
                    # make the flow invalid and get dropped).
                    ppid, ptid = lane(parent)
                    ts_s = min(parent.start_s * 1e6, ts)
                    events.append({"name": "causal", "cat": "flow",
                                   "ph": "s", "ts": ts_s, "pid": ppid,
                                   "tid": ptid, "id": s.span_id,
                                   "bp": "e"})
                    events.append({"name": "causal", "cat": "flow",
                                   "ph": "f", "ts": ts, "pid": pid,
                                   "tid": tid, "id": s.span_id,
                                   "bp": "e"})
            diag = self.diagnosis_for(e)
            if diag:
                events.append({"name": diag, "cat": "diagnosis",
                               "ph": "i", "ts": 0, "pid": "coordinator",
                               "tid": "diagnosis", "s": "g"})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def clear(self) -> None:
        with self._lock:
            self._flight.clear()
            self._retained.clear()
            self._roots.clear()
            self.dropped = 0


# the process-global flight recorder (every hook records here; worker
# processes drain theirs to the coordinator over the control channel)
EPOCH_TRACER = EpochTracer()


from contextlib import contextmanager as _contextmanager

from risingwave_tpu.utils import failpoint as _failpoint


@_contextmanager
def dispatch_span(kernel: str, rows: float, **args):
    """Time one device dispatch (the host-side call: pack + transfer +
    launch enqueue) into the current epoch's trace, stamped with kernel
    identity and row payload. A retrace during the call shows up as a
    sibling compile span (note_compile). Near-free when tracing is
    off.

    Phase ledger: the span's EXCLUSIVE time (minus nested h2d/d2h
    scopes) is the launch's device_compute share, stamped with the
    kernel label so transfers recorded inside inherit it."""
    from contextlib import nullcontext

    from risingwave_tpu.utils import ledger as _ledger
    if not _ENABLED and not _ledger.enabled():
        yield
        return
    t0 = time.time()
    try:
        with _ledger.LEDGER.phase("device_compute", kernel=kernel) \
                if _ledger.enabled() else nullcontext():
            # ledger-test seam: a sleep spec here is wall time INSIDE
            # one kernel's dispatch — it must land in the dispatching
            # domain's device_compute books only (the per-domain
            # overlap oracle). Guarded so the unarmed hot path pays
            # one dict-truthiness check, not an f-string per dispatch.
            if _failpoint._ARMED:
                _failpoint.fail_point(f"ledger.dispatch.{kernel}")
            yield
    finally:
        if _ENABLED:
            EPOCH_TRACER.record(kernel, "dispatch", start_s=t0,
                                dur_s=time.time() - t0,
                                rows=float(rows), **args)


def note_compile(label: str) -> None:
    """Called from INSIDE a jitted function's Python body — which runs
    only while jax traces it — so every call IS a (re)trace event:
    first-compile at warmup, shape-churn recompiles in steady state.
    Counts stream_kernel_recompile_count, drops a compile span into
    the current epoch's trace, and marks the epoch warmup in the phase
    ledger (compile stalls are exempt from the conservation gate)."""
    from risingwave_tpu.utils.metrics import STREAMING
    STREAMING.kernel_recompile.inc(1, kernel=label)
    from risingwave_tpu.utils.ledger import LEDGER
    LEDGER.note_compile()
    if _ENABLED:
        EPOCH_TRACER.record(f"compile:{label}", "compile",
                            kernel=label)
