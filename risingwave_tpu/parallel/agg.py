"""Vnode-sharded grouped aggregation over a device mesh.

Reference parity: N parallel HashAggExecutor actors fed by a HASH
dispatcher (SURVEY §2.12 data parallelism; hash_agg.rs:67 +
dispatch.rs:582). TPU re-design: ONE SPMD program under ``shard_map`` —
each mesh shard owns a contiguous vnode range (VnodeMapping semantics)
and a private slice of the hash-table/accumulator arrays; rows hop to
their owner via the bucketized all_to_all (parallel/exchange.py) and are
then aggregated with the exact same kernel math as the single-chip path
(ops/hash_agg._update_call — one code path, two launch shapes).

State is the single-chip ``AggState`` with a leading [n_dev] axis,
sharded ``P('d')``. The barrier flush gathers per-shard dirty slots the
same way the single-chip kernel does; shards never share groups because
ownership is a function of the key hash.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from risingwave_tpu.common.hash import VNODE_COUNT
from risingwave_tpu.ops import hash_table as ht
from risingwave_tpu.ops import lanes
from risingwave_tpu.ops.hash_agg import (
    AggSpec, AggState, _call_slices, _update_call, decode_outputs,
    make_agg_state, n_input_lanes,
)
from risingwave_tpu.parallel.exchange import (
    bucketize_by_owner, exchange, vnodes_from_lanes,
)

AXIS = "d"


def _stack_state(n_dev: int, capacity: int, key_width: int,
                 specs: Sequence[AggSpec]) -> AggState:
    """AggState with a leading device axis on every leaf."""
    one = make_agg_state(capacity, key_width, specs)
    return jax.tree.map(
        lambda a: jnp.broadcast_to(a[None], (n_dev,) + a.shape), one)


class ShardedAggKernel:
    """Multi-chip grouped aggregation (fixed capacity v1 — growth and
    elastic resharding land with the reschedule path).

    apply(): one jitted SPMD step — vnode routing, all_to_all, local
    probe+scatter per shard. snapshot(): host-side decode of all live
    groups (test/flush support).
    """

    def __init__(self, mesh: Mesh, key_width: int,
                 specs: Sequence[AggSpec], capacity: int = 1 << 12,
                 bucket: Optional[int] = None):
        self.mesh = mesh
        self.n_dev = mesh.devices.size
        self.specs = tuple(specs)
        self.key_width = key_width
        self.capacity = capacity
        self.bucket = bucket
        # vnode → owning shard: contiguous even split (VnodeMapping)
        owners = np.repeat(np.arange(self.n_dev, dtype=np.int32),
                           VNODE_COUNT // self.n_dev)
        pad = VNODE_COUNT - len(owners)
        if pad:
            owners = np.concatenate(
                [owners, np.full(pad, self.n_dev - 1, np.int32)])
        self.owner_map = jnp.asarray(owners)
        sharding = NamedSharding(mesh, P(AXIS))
        self.state: AggState = jax.tree.map(
            lambda a: jax.device_put(a, sharding),
            _stack_state(self.n_dev, capacity, key_width, self.specs))
        self._step_cache: Dict[Tuple[int, int], object] = {}

    # -- the SPMD step ----------------------------------------------------
    def _build_step(self, n_rows: int, bucket: int):
        specs = self.specs
        slices = _call_slices(specs)
        n_dev = self.n_dev

        def local_step(state: AggState, key_lanes, signs, vis, flat_in,
                       owner_map):
            # shard_map hands each shard a [1, ...] block: drop the axis
            state = jax.tree.map(lambda a: a[0], state)
            vn = vnodes_from_lanes(key_lanes)
            owner = owner_map[vn]
            # payload layout: keys, signs, then per call: lanes* + valid
            payloads = [key_lanes, signs] + list(flat_in)
            buckets, bvalid, overflow = bucketize_by_owner(
                owner, vis, payloads, n_dev, bucket)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * bucket
            rkeys = recv[0].reshape(m, key_lanes.shape[1])
            rsigns = recv[1].reshape(m)
            rflat = [r.reshape(m) for r in recv[2:]]
            rvis = rvalid.reshape(m)
            table, slots, ins = ht.probe_insert(state.table, rkeys, rvis)
            cap = state.table.capacity
            scat = jnp.where(rvis, slots, cap)
            s32 = rsigns.astype(jnp.int32)
            group_rows = state.group_rows.at[scat].add(s32, mode="drop")
            dirty = state.dirty.at[scat].set(True, mode="drop")
            accs = list(state.accs)
            k = 0
            for spec, sl in zip(specs, slices):
                n_in = n_input_lanes(spec)
                in_lanes = tuple(rflat[k:k + n_in])
                val_ok = rflat[k + n_in]
                k += n_in + 1
                _update_call(spec, accs, sl, in_lanes, val_ok, slots,
                             rvis, s32, cap)
            new = AggState(table, group_rows, dirty, tuple(accs),
                           state.emitted_valid, state.emitted_rows,
                           state.emitted_accs)
            new = jax.tree.map(lambda a: a[None], new)
            return new, ins[None], overflow[None]

        state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        mapped = jax.shard_map(
            local_step, mesh=self.mesh,
            in_specs=(state_spec, P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                      P()),
            out_specs=(state_spec, P(AXIS), P(AXIS)),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0,))

    def apply(self, key_lanes: np.ndarray, signs: np.ndarray,
              vis: np.ndarray,
              inputs: Sequence[Tuple[Sequence[np.ndarray], np.ndarray]]
              ) -> None:
        """One SPMD step over a host batch.

        Rows are split evenly across shards (row-sharded upload); the
        all_to_all then moves each row to its vnode owner. `inputs` is
        per call (value lanes, valid mask) — the single-chip layout;
        lanes AND validity travel through the exchange. Batch rows must
        divide n_dev.
        """
        n = key_lanes.shape[0]
        assert n % self.n_dev == 0, (n, self.n_dev)
        # per-shard post-exchange batch is n_dev*bucket rows in ONE
        # scatter step — same int32 limb bound as the single-chip kernel
        if n > lanes.MAX_CHUNK_ROWS:
            raise RuntimeError(
                f"batch {n} > {lanes.MAX_CHUNK_ROWS} breaks limb math")
        flat: List[jnp.ndarray] = []
        for in_lanes, valid in inputs:
            flat.extend(jnp.asarray(a) for a in in_lanes)
            if valid is None:            # count(*) — same API as the
                valid = np.ones(n, dtype=bool)   # single-chip kernel
            flat.append(jnp.asarray(valid))
        # each shard holds n/n_dev local rows, so no owner can receive
        # more than that: bucket = n/n_dev is overflow-free by
        # construction AND keeps the exchanged tensor at n rows/shard
        bucket = self.bucket or n // self.n_dev
        key = (n, bucket)
        if key not in self._step_cache:
            self._step_cache[key] = self._build_step(n, bucket)
        step = self._step_cache[key]
        self.state, _ins, overflow = step(
            self.state, jnp.asarray(key_lanes), jnp.asarray(signs),
            jnp.asarray(vis), tuple(flat), self.owner_map)
        if bool(np.asarray(overflow).any()):
            # not an assert: dropping routed rows corrupts aggregates,
            # and `python -O` must not strip this guard
            raise RuntimeError(
                "bucket overflow: raise `bucket` (host retry path TBD)")

    # -- elastic resharding (scale.rs:174 / Mutation::Update analog) ------
    def reshard(self, new_owner_map: np.ndarray) -> None:
        """Move device state to a new vnode→shard mapping at a barrier.

        The reference reschedules by swapping vnode bitmaps and lazily
        reloading state from Hummock (state_table.rs:650); the TPU-
        native equivalent moves the HBM-resident groups directly: one
        SPMD step routes every live slot's (key, counters, accs,
        emitted snapshot) to its new owner via the bucketized
        all_to_all, then rebuilds each shard's table with the same
        probe-insert kernel. No host round-trip for the state itself.
        """
        new_map = jnp.asarray(np.asarray(new_owner_map, dtype=np.int32))
        n_dev = self.n_dev
        cap = self.capacity
        specs = self.specs
        key_width = self.key_width

        def local(state: AggState, owner_map):
            state = jax.tree.map(lambda a: a[0], state)
            live = state.table.occ & ((state.group_rows != 0)
                                      | state.dirty | state.emitted_valid)
            owner = owner_map[vnodes_from_lanes(state.table.keys)]
            payloads = [state.table.keys, state.group_rows,
                        state.dirty.astype(jnp.int32),
                        state.emitted_valid.astype(jnp.int32),
                        state.emitted_rows,
                        *state.accs, *state.emitted_accs]
            # bucket = cap: a shard can never receive more rows than
            # fit in one table, so routing is overflow-free
            buckets, bvalid, _overflow = bucketize_by_owner(
                owner, live, payloads, n_dev, cap)
            recv, rvalid = exchange(buckets, bvalid, AXIS)
            m = n_dev * cap
            rvis = rvalid.reshape(m)
            n_received = jnp.sum(rvis, dtype=jnp.int32)
            rkeys = recv[0].reshape(m, key_width)
            fresh = make_agg_state(cap, key_width, specs)
            table, slots, _ins = ht.probe_insert(fresh.table, rkeys,
                                                 rvis)
            scat = jnp.where(rvis, slots, cap)

            def put(dst, src, cast=None):
                v = src.reshape(m)
                if cast is not None:
                    v = v.astype(cast)
                return dst.at[scat].set(v, mode="drop")

            na = len(state.accs)
            new = AggState(
                table=table,
                group_rows=put(fresh.group_rows, recv[1]),
                dirty=put(fresh.dirty, recv[2], jnp.bool_),
                accs=tuple(put(f, r) for f, r in
                           zip(fresh.accs, recv[5:5 + na])),
                emitted_valid=put(fresh.emitted_valid, recv[3],
                                  jnp.bool_),
                emitted_rows=put(fresh.emitted_rows, recv[4]),
                emitted_accs=tuple(put(f, r) for f, r in
                                   zip(fresh.emitted_accs,
                                       recv[5 + na:])),
            )
            return jax.tree.map(lambda a: a[None], new), n_received[None]

        state_spec = jax.tree.map(lambda _: P(AXIS), self.state)
        mapped = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(state_spec, P()), out_specs=(state_spec, P(AXIS)),
            check_vma=False)
        step = jax.jit(mapped, donate_argnums=(0,))
        new_state, received = step(self.state, new_map)
        # destination-table contract: probe_insert needs a free slot
        # per routed row; an overfull shard would silently corrupt
        # accumulators — fail loudly instead
        worst = int(np.asarray(received).max())
        if worst > ht.MAX_LOAD * cap:
            raise RuntimeError(
                f"reshard overfills a shard: {worst} live groups vs "
                f"{cap} slots — raise capacity before rescaling")
        self.state = new_state
        self.owner_map = new_map   # apply steps take it as a runtime arg

    # -- host-side full decode (tests + dryrun assertions) ---------------
    def snapshot(self) -> Dict[tuple, tuple]:
        """group key lanes tuple → decoded outputs, across all shards."""
        st = jax.device_get(self.state)
        out: Dict[tuple, tuple] = {}
        for d in range(self.n_dev):
            occ = st.table.occ[d]
            live = occ & (st.group_rows[d] > 0)
            idx = np.flatnonzero(live)
            if not len(idx):
                continue
            keys = st.table.keys[d][idx]
            accs = [a[d][idx] for a in st.accs]
            outs, nulls = decode_outputs(self.specs, accs)
            for r in range(len(idx)):
                kt = tuple(keys[r].tolist())
                out[kt] = tuple(
                    None if nulls[c][r] else outs[c][r].item()
                    for c in range(len(self.specs)))
        return out
